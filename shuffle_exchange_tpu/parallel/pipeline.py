"""Pipeline parallelism: SPMD microbatch pipeline inside one jitted step.

Capability parity with the reference's pipeline stack (SURVEY.md §2.6 PP,
§3.4): ``PipelineModule`` layer partitioning (``runtime/pipe/module.py:86``),
the instruction-list 1F1B ``TrainSchedule`` (``runtime/pipe/schedule.py:189``),
``PipelineEngine.train_batch`` (``runtime/pipe/engine.py:338``) and the p2p
activation exchange (``runtime/pipe/p2p.py``).

TPU-native design — no host-driven schedule, no p2p process groups:

- Layer partitioning: the model's stacked per-layer params keep their
  leading L dim; the pipeline shards it over the mesh "pipe" axis, so each
  stage owns L/S contiguous layers (the analog of PipelineModule's
  partition_method="uniform").
- The schedule is a ``lax.scan`` over pipeline *ticks* inside the jitted
  train step. Each tick every stage runs its layer block and passes
  activations to the next stage with ``lax.ppermute`` — XLA schedules the
  sends on ICI and overlaps them with compute. The reference's
  SendActivation/RecvActivation instruction pairs (``schedule.py``)
  collapse into that single collective permute.
- The loop runs under a *partial-manual* ``shard_map``: only "pipe" is
  manual; data/fsdp/tensor/expert/seq stay auto, so ZeRO sharding, AutoTP
  matmul sharding and MoE dispatch inside a stage still compile through
  XLA's SPMD partitioner unchanged.
- Backward: ``jax.grad`` through the scan replays ticks in reverse with the
  transposed ppermute — the BackwardPass/SendGrad/RecvGrad instructions of
  the reference schedule, derived instead of hand-written. Activation
  memory is bounded by remat (the model's ``remat`` flag), which is the
  reference's activation-checkpoint interval analog.
- Tied weights (embed used at stage 0, tied unembed at the last stage)
  enter the shard_map replicated over "pipe"; the shard_map transpose
  psums their cotangents — the reference's tied-weight allreduce
  (``runtime/pipe/module.py:454``) by construction.

GPipe vs 1F1B: with everything traced into one XLA program, the
forward/backward interleave is the compiler's scheduling decision; the
tick loop fixes data dependencies only. Bubble fraction is the usual
(S-1)/(n_micro+S-1) — pick micro_batches ≥ 4·stages to amortize.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..config.config_utils import ConfigError
from . import comm


def partition_balanced(weights, n_parts: int):
    """Contiguous partition of ``weights`` into ``n_parts`` minimizing the
    max part weight (reference ``ds_utils.partition_balanced`` used by
    PipelineModule partition_method="parameters"/"type:regex",
    runtime/pipe/module.py:378-398). Returns boundaries [n_parts + 1]."""
    L = len(weights)
    if n_parts <= 0:
        raise ConfigError(f"n_parts must be positive, got {n_parts}")
    prefix = [0]
    for w in weights:
        prefix.append(prefix[-1] + w)

    def parts_needed(cap):
        # greedy: how many contiguous parts with sum <= cap (every single
        # weight must fit — cap >= max(weights) is ensured by the caller)
        parts, cur = 1, 0
        for w in weights:
            if cur + w > cap:
                parts += 1
                cur = w
            else:
                cur += w
        return parts

    lo, hi = max(weights, default=0), prefix[-1]
    while lo < hi:
        mid = (lo + hi) // 2
        if parts_needed(mid) <= n_parts:
            hi = mid
        else:
            lo = mid + 1
    cap = lo
    bounds = [0]
    cur = 0
    for i, w in enumerate(weights):
        # keep enough layers in reserve that every later stage is nonempty
        remaining_stages = n_parts - len(bounds)
        if ((cur + w > cap or L - i <= remaining_stages)
                and cur > 0 and len(bounds) < n_parts):
            bounds.append(i)
            cur = 0
        cur += w
    while len(bounds) < n_parts:
        bounds.append(L)
    bounds.append(L)
    # zero-weight runs (sparse type:regex) can leave trailing stages empty;
    # repair to strictly increasing boundaries (requires L >= n_parts)
    for j in range(1, n_parts):
        bounds[j] = min(max(bounds[j], bounds[j - 1] + 1), L - (n_parts - j))
    return bounds


def pipeline_stage_count(topology=None) -> int:
    from .mesh import get_topology

    topo = topology or get_topology()
    return topo.axis_sizes.get("pipe", 1)


def spmd_pipeline(stage_fn: Callable, x_micro, *, n_stages: int, axis_name: str = "pipe"):
    """Run the microbatch pipeline. Must execute inside shard_map with
    ``axis_name`` manual.

    stage_fn: (h [mb, ...]) -> (h_out [mb, ...], aux scalar) — this stage's
      layer block.
    x_micro: [n_micro, mb, ...] microbatched stage-0 inputs (replicated over
      the pipe axis; only stage 0 reads them).

    Returns (outputs [n_micro, mb, ...] — valid on the LAST stage, zeros
    elsewhere; aux — sum of stage_fn aux over all (stage, microbatch) pairs,
    bubble ticks masked out).
    """
    import jax
    import jax.numpy as jnp

    n_micro = x_micro.shape[0]
    stage = jax.lax.axis_index(axis_name)
    n_ticks = n_micro + n_stages - 1
    # No wrap-around edge: stage 0 always reads fresh microbatch input, so
    # the (S-1 -> 0) send would be dead traffic (devices with no source
    # receive zeros, which stage 0 never consumes).
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        state, outputs, aux_acc = carry
        idx = jnp.clip(t, 0, n_micro - 1)
        inp = jnp.where(stage == 0,
                        jax.lax.dynamic_index_in_dim(x_micro, idx, 0, keepdims=False),
                        state)
        out, aux = stage_fn(inp)
        # Tick t is a real microbatch for this stage iff stage <= t < stage+n_micro.
        active = (t >= stage) & (t < stage + n_micro)
        aux_acc = aux_acc + jnp.where(active, aux, 0.0)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = (stage == n_stages - 1) & (t >= n_stages - 1)
        cur = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, cur), out_idx, 0)
        state = comm.ppermute(out, axis_name, perm)
        return (state, outputs, aux_acc), None

    state0 = jnp.zeros(x_micro.shape[1:], x_micro.dtype)
    outputs0 = jnp.zeros_like(x_micro)
    carry0 = (state0, outputs0, jnp.zeros((), jnp.float32))
    (state, outputs, aux), _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
    return outputs, aux


class PipelinedModel:
    """Wrap a model-zoo Transformer for pipeline-parallel training.

    Same surface as the wrapped model (``init`` / ``loss`` /
    ``partition_specs``), so the Engine needs no pipeline-specific code —
    the reference's separate PipelineEngine subclass (runtime/pipe/engine.py)
    collapses into a model wrapper because the schedule lives inside the
    jitted step. ``apply``/generation use the wrapped model directly
    (inference uses the non-pipelined path).

    micro_batches plays the role of the reference's gradient accumulation
    steps on the pipeline path (PipelineEngine consumes gas microbatches per
    train_batch — runtime/pipe/engine.py:338).
    """

    def __init__(self, model, n_stages: Optional[int] = None, micro_batches: int = 1,
                 axis_name: str = "pipe", partition_method: str = "uniform"):
        self.model = model
        self.config = model.config
        self.axis_name = axis_name
        self.micro_batches = int(micro_batches)
        self._n_stages = n_stages
        self.partition_method = partition_method
        self._bounds = self._layer_bounds()
        counts = [self._bounds[s + 1] - self._bounds[s]
                  for s in range(self.n_stages)]
        self.stage_size = max(counts)
        # even layout: contiguous equal stages — the stacked dim shards
        # straight over "pipe". Uneven (L % S != 0 or weighted methods):
        # stages pad to the max count with identity-masked rows.
        self._even = (len(set(counts)) == 1
                      and self._bounds == [s * counts[0]
                                           for s in range(self.n_stages + 1)])
        if self.micro_batches < 1:
            raise ConfigError(f"micro_batches must be >= 1, got {self.micro_batches}")

    def _layer_bounds(self):
        """Per-stage layer boundaries (reference PipelineModule
        _partition_layers, runtime/pipe/module.py:378-398):
        "uniform" — balanced layer counts; "parameters" — balanced per-layer
        parameter counts; "type:regex" — balance the count of layers whose
        type name matches the regex (this zoo's scanned layers are typed
        "moe" or "dense" per moe_layer_pattern)."""
        import re

        L, S = self.config.n_layers, self.n_stages
        if S > L:
            raise ConfigError(
                f"pipeline stages {S} > n_layers {L}: at least one stage "
                "would be empty (reference partition_balanced rejects this "
                "too — reduce mesh.pipe)")
        method = (self.partition_method or "uniform").lower()
        if method in ("uniform", "parameters"):
            if method == "parameters":
                # stacked scan layers are homogeneous (same shapes), so
                # per-layer param counts are equal and this reduces to
                # balanced counts — computed anyway for fidelity
                cfg = self.config
                per_layer = (4 * cfg.d_model * cfg.d_model
                             + 3 * cfg.d_model * cfg.ff_dim)
                weights = [per_layer] * L
            else:
                weights = [1] * L
            return partition_balanced(weights, S)
        if method.startswith("type:"):
            pattern = method[len("type:"):]
            mp = self.config.moe_layer_pattern
            types = [("moe" if (self.config.n_experts > 0
                                and (not mp or mp[i % len(mp)]))
                      else "dense") for i in range(L)]
            weights = [1 if re.search(pattern, t) else 0 for t in types]
            if not any(weights):
                raise ConfigError(
                    f"partition_method {self.partition_method!r} matches no "
                    f"layers (types present: {sorted(set(types))})")
            return partition_balanced(weights, S)
        raise ConfigError(
            f"Unknown pipeline partition_method {self.partition_method!r}; "
            "use 'uniform', 'parameters', or 'type:regex'")

    @property
    def n_stages(self) -> int:
        return self._n_stages if self._n_stages is not None else pipeline_stage_count()

    # -- delegation ----------------------------------------------------

    def init(self, rng):
        return self.model.init(rng)

    def apply(self, params, input_ids):
        return self.model.apply(params, input_ids)

    def partition_specs(self, params):
        """Model specs with the stacked-layer leading dim put on "pipe".

        Uneven partitions (padded stages) keep the RAW [L] stacks off the
        pipe axis — L doesn't divide S — and the loss reshards the padded
        [S * stage_size] gather instead; ZeRO still claims a free dim."""
        import jax
        from jax.sharding import PartitionSpec as P

        base = self.model.partition_specs(params)
        if not self._even:
            return base

        def pin_stage_dim(path, spec):
            keys = [getattr(e, "key", getattr(e, "name", None)) for e in path]
            if keys and keys[0] == "layers":
                rest = tuple(spec)[1:] if len(spec) else ()
                return P(self.axis_name, *rest)
            return spec

        return jax.tree_util.tree_map_with_path(pin_stage_dim, base)

    # -- the pipelined loss --------------------------------------------

    def loss(self, params, batch, rng=None):
        """Next-token CE over the pipeline; numerically matches
        ``model.loss`` (up to MoE aux averaging across microbatches)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        model = self.model
        S = self.n_stages
        n_micro = self.micro_batches

        ids = batch["input_ids"]
        if "labels" in batch:
            labels, inputs = batch["labels"], ids
        else:
            labels, inputs = ids[:, 1:], ids[:, :-1]
        B, T = inputs.shape
        if B % n_micro:
            raise ConfigError(f"Batch {B} not divisible by pipeline micro_batches {n_micro}")
        mb = B // n_micro
        inputs = inputs.reshape(n_micro, mb, T)
        labels = labels.reshape(n_micro, mb, T)
        mesh = _current_mesh()
        # Re-constrain params to their model (pipe/tensor) specs before the
        # manual region: any extra ZeRO axis on the masters is all-gathered
        # OUT HERE by XLA (one gather per stage-local stack — the PP analog
        # of the per-stage ZeRO gather), and never reaches the partial-manual
        # shard_map, whose partitioner mishandles such subgroup collectives.
        model_shardings = jax.tree_util.tree_map(
            lambda s: jax.sharding.NamedSharding(mesh, s), self.partition_specs(params))
        params = jax.tree_util.tree_map(jax.lax.with_sharding_constraint, params, model_shardings)

        layer_params = params["layers"]
        other_params = {k: v for k, v in params.items() if k != "layers"}
        keep_flags = ()
        # each stage's rows carry their GLOBAL layer index so per-layer
        # pattern flags (attention_pattern / moe_layer_pattern / random-LTD)
        # resolve correctly inside the stage (stage-local row numbers would
        # silently pick the wrong flags on stages > 0)
        layer_ids = jnp.arange(self.config.n_layers, dtype=jnp.int32)
        if not self._even:
            # Uneven partition (partition_method="parameters"/"type:regex"
            # or L % S != 0): gather each stage's rows into a padded
            # [S * stage_size] stack (pad rows = zeros, masked to identity
            # by stack_apply's layer_keep), so the manual region still
            # shards an even dim over "pipe". The gather/scatter pair is
            # O(params) data movement once per step — noise next to the
            # stage compute.
            S_sz = self.stage_size
            pad_idx, keep = [], []
            L_total = self.config.n_layers
            for s in range(S):
                rows = list(range(self._bounds[s], self._bounds[s + 1]))
                keep += [True] * len(rows) + [False] * (S_sz - len(rows))
                pad_idx += rows + [L_total] * (S_sz - len(rows))
            pad_idx = jnp.asarray(pad_idx, jnp.int32)
            keep_flags = jnp.asarray(keep)
            layer_ids = pad_idx     # pad rows: id == n_layers -> flags off

            def pad_stack(a):
                zero_row = jnp.zeros((1,) + a.shape[1:], a.dtype)
                return jnp.concatenate([a, zero_row])[pad_idx]

            layer_params = jax.tree_util.tree_map(pad_stack, layer_params)
        layer_specs = jax.tree_util.tree_map(lambda _: P(self.axis_name), layer_params)

        # XLA's partial-manual partitioner CHECK-fails when a convert feeds a
        # replicated (P()) shard_map input whose cotangent must psum over the
        # manual axis in low precision. Route replicated params in at fp32
        # and re-cast inside the manual region (double converts cancel when
        # the engine's bf16 cast sits just outside).
        other_dtypes = jax.tree_util.tree_map(lambda v: v.dtype, other_params)
        other_params = jax.tree_util.tree_map(
            lambda v: v.astype(jnp.float32) if jnp.issubdtype(v.dtype, jnp.floating) else v,
            other_params)

        def inner(layer_params, keep_flags, layer_ids, other_params, inputs, labels):
            other_params = jax.tree_util.tree_map(
                lambda v, d: v.astype(d), other_params, other_dtypes)
            # Embed per microbatch (cheap gather; runs on every stage but
            # only stage 0's result is consumed — its cotangent is zero
            # elsewhere, so tied/embed grads stay correct).
            x, rope = model.embed(other_params, inputs)   # [n_micro, mb, T, D]

            # keep_flags (uneven partitions): pad rows are identity skips
            # via stack_apply's layer_keep masking; the even path passes
            # () so stack_apply keeps its fast unmasked scan body
            keep = keep_flags if not isinstance(keep_flags, tuple) else None

            def stage_fn(h):
                return model.stack_apply(layer_params, h, rope,
                                         layer_keep=keep,
                                         layer_ids=layer_ids)

            outputs, aux = spmd_pipeline(stage_fn, x, n_stages=S, axis_name=self.axis_name)

            stage = jax.lax.axis_index(self.axis_name)

            def last_stage_ce(outputs):
                def one(args):
                    o, lb = args
                    logits = model.head(other_params, o)
                    s, c = model.token_loss(logits, lb)
                    return s, c.astype(jnp.float32)

                sums, counts = jax.lax.map(one, (outputs, labels))
                return sums.sum(), counts.sum()

            sp = _current_mesh().shape.get("seq", 1)
            if sp > 1:
                # seq x pipe (round 5): with an auto "seq" axis live inside
                # this region, the CE contains seq-group collectives; a
                # stage-VARYING lax.cond would run them only on the last
                # stage while its pipe partners move on to the next tick's
                # ppermute — a rendezvous deadlock (observed on the 8-dev
                # CPU mesh). Keep the collective schedule uniform: every
                # stage computes the CE (non-last stages on their zero
                # outputs) and the result is masked. Costs (S-1) wasted
                # head matmuls — the pipeline bubble already dwarfs this.
                nll_all, count_all = last_stage_ce(outputs)
                is_last = (stage == S - 1).astype(jnp.float32)
                nll_sum, count = nll_all * is_last, count_all * is_last
            else:
                nll_sum, count = jax.lax.cond(
                    stage == S - 1, last_stage_ce,
                    lambda o: (jnp.zeros((), jnp.float32),
                               jnp.zeros((), jnp.float32)),
                    outputs)
            # Per-stage partials, reduced OUTSIDE the manual region (the
            # reference broadcasts the aggregated loss from the last stage,
            # runtime/pipe/engine.py:584; here summing the [S] vector is
            # that broadcast — claiming replicated P() output for a psum'd
            # scalar trips XLA's partial-manual partitioner instead).
            return (nll_sum.reshape(1), count.reshape(1), aux.reshape(1))

        fn = jax.shard_map(
            inner, mesh=mesh,
            in_specs=(layer_specs,
                      P() if isinstance(keep_flags, tuple) else P(self.axis_name),
                      P(self.axis_name), P(), P(), P()),
            out_specs=(P(self.axis_name), P(self.axis_name), P(self.axis_name)),
            axis_names={self.axis_name}, check_vma=False)
        nll_parts, count_parts, aux_parts = fn(layer_params, keep_flags,
                                               layer_ids, other_params,
                                               inputs, labels)
        nll_sum, count, aux = nll_parts.sum(), count_parts.sum(), aux_parts.sum()
        ce = nll_sum / jnp.maximum(count, 1.0)
        # aux summed layers×micros; dense model sums layers on the full
        # batch, so average over microbatches to keep the coefficient scale.
        return ce + self.config.aux_loss_coef * aux / n_micro


def _current_mesh():
    from .mesh import get_topology

    return get_topology().mesh
