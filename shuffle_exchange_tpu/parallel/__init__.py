from .mesh import (
    AXIS_ORDER,
    MeshTopology,
    get_topology,
    initialize_topology,
    reset_topology,
    topology_is_initialized,
    resolve_axis_sizes,
)
from . import comm
from . import compressed
from .pipeline import PipelinedModel, spmd_pipeline
