"""AutoTP: automatic tensor-parallel sharding inference by parameter name.

Capability parity with the reference's ``module_inject/auto_tp.py:193``
(AutoTP graph walk that classifies Linears into column-parallel
``LinearLayer`` vs row-parallel ``LinearAllreduce``) and ``tp_shard.py``
bookkeeping. TPU-native shape: instead of swapping modules, classify each
*parameter* by its path name and emit a PartitionSpec over the mesh
"tensor" axis — XLA then inserts the column/row-parallel collectives the
reference implements by hand (module_inject/layers.py:388,465).

Works on any pytree (our zoo layouts, HF state dicts, custom models);
unknown names stay replicated, mirroring AutoTP's conservative fallback.
"""

from __future__ import annotations

import re
from typing import Dict, Sequence, Tuple

# Column-parallel: output features split over "tensor" (last dim of an
# [in, out] matrix). Reference: qkv + up/gate projections.
_COL_NAMES = {
    "wq", "wk", "wv", "w_gate", "w_up", "q_proj", "k_proj", "v_proj",
    "gate_proj", "up_proj", "qkv_proj", "gate_up_proj", "c_attn", "c_fc",
    "query", "key", "value", "query_key_value", "dense_h_to_4h", "fc1",
    "w1", "w3", "in_proj", "wi", "lin1",
    # zoo column-parallel biases (row-parallel biases apply post-allreduce
    # and stay replicated, so b_o / b_down are intentionally absent)
    "b_q", "b_k", "b_v", "b_up",
}
# Row-parallel: input features split (first dim); output allreduced.
_ROW_NAMES = {
    "wo", "w_down", "o_proj", "down_proj", "out_proj", "c_proj", "dense",
    "dense_4h_to_h", "fc2", "w2", "wo_proj", "lin2",
}
_VOCAB_NAMES = {"embed", "embed_tokens", "wte", "word_embeddings", "tok_embeddings"}
_UNEMBED_NAMES = {"unembed", "lm_head", "output", "embed_out"}
_BIAS_PREFIXES = ("b_", "bias")


def _leaf_name(path: Sequence[str]) -> str:
    """Last meaningful component ('layers.0.self_attn.q_proj.weight' -> 'q_proj')."""
    parts = [p for p in path if p not in ("weight", "bias", "kernel", "w", "b")]
    return parts[-1] if parts else ""


def classify(path: Sequence[str]) -> str:
    """'column' | 'row' | 'vocab' | 'unembed' | 'replicate' for a param path."""
    name = _leaf_name(path)
    base = re.sub(r"\d+$", "", name).rstrip("._")
    if base in _COL_NAMES or name in _COL_NAMES:
        return "column"
    if base in _ROW_NAMES or name in _ROW_NAMES:
        return "row"
    if base in _VOCAB_NAMES or name in _VOCAB_NAMES:
        return "vocab"
    if base in _UNEMBED_NAMES or name in _UNEMBED_NAMES:
        return "unembed"
    return "replicate"


def infer_partition_specs(params, tensor_axis: str = "tensor",
                          stacked_layer_key: str = "layers"):
    """Pytree of PartitionSpecs for ``params`` (the AutoTP entry point).

    Matrix params classified column/row get ``tensor_axis`` on their
    out/in-feature dim; vocab embeddings shard the vocab dim; 1-D biases of
    column-parallel projections shard their only dim; everything else is
    replicated. Leaves under ``stacked_layer_key`` get a leading None for
    the scan-stacked layer dim.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    def spec_for(keypath, leaf):
        path = []
        for e in keypath:
            if hasattr(e, "key"):
                path.append(str(e.key))
            elif hasattr(e, "idx"):
                path.append(str(e.idx))
        kind = classify(path)
        ndim = leaf.ndim
        stacked = bool(path) and path[0] == stacked_layer_key
        lead = (None,) if (stacked and ndim >= 1) else ()
        eff = ndim - len(lead)
        if kind == "column":
            if eff >= 2:
                return P(*lead, *((None,) * (eff - 1)), tensor_axis)
            if eff == 1:
                return P(*lead, tensor_axis)   # column bias shards with outputs
        elif kind == "row":
            if eff >= 2:
                return P(*lead, *((None,) * (eff - 2)), tensor_axis, None)
            # row-parallel bias is applied post-allreduce: replicate
        elif kind == "vocab":
            if eff >= 2:
                return P(*lead, tensor_axis, *((None,) * (eff - 1)))
        elif kind == "unembed":
            if eff >= 2:
                return P(*lead, *((None,) * (eff - 1)), tensor_axis)
        return P(*((None,) * ndim))

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_sizes(params, specs, axis_sizes: Dict[str, int]) -> Dict[str, Tuple[int, int]]:
    """Per-leaf (replicated_elems, sharded_elems) bookkeeping (tp_shard.py
    analog) — lets callers sanity-check what AutoTP decided."""
    import jax
    import math

    out = {}
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_s = jax.tree_util.tree_leaves(specs)
    for (keypath, leaf), spec in zip(flat_p, flat_s):
        name = ".".join(str(getattr(e, "key", getattr(e, "idx", ""))) for e in keypath)
        n = math.prod(leaf.shape) if leaf.shape else 1
        div = 1
        for entry in spec:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                div *= axis_sizes.get(ax, 1)
        out[name] = (n, n // max(div, 1))
    return out
