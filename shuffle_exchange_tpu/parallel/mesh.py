"""Named-axis device mesh topology.

This is the TPU-native replacement for the reference's process-group
machinery: ``utils/groups.py`` (MP/DP/EP/SP group registry),
``runtime/pipe/topology.py`` (ProcessTopology rank grid) and
``comm/comm.py:616`` (``initialize_mesh_device``). Instead of NCCL process
groups, every parallel dimension is a named axis of one
``jax.sharding.Mesh``; collectives ride ICI when the axis maps onto
physically-adjacent chips and DCN across slices/hosts.

Axes (reference strategy → mesh axis):
  DP / decentralized-sync replicas  → "data"
  ZeRO partitioning (stages 1-3)    → "fsdp"
  Tensor parallel (AutoTP)          → "tensor"
  Expert parallel (MoE)             → "expert"
  Ulysses / ring sequence parallel  → "seq"
  Pipeline stages                   → "pipe"

Axis order is (pipe, data, fsdp, expert, seq, tensor): innermost axes get
ICI-contiguous device ranges, so tensor/seq/expert collectives (latency
sensitive, per-layer) ride ICI while pipe/data (less frequent) may cross DCN.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config.config_utils import ConfigError
from ..utils.logging import log_dist, logger

AXIS_ORDER: Tuple[str, ...] = ("pipe", "data", "fsdp", "expert", "seq", "tensor")

# ZeRO parameter/optimizer partitioning shards over both data-like axes: the
# reference partitions over the whole DP world; here the DP world is
# data × fsdp (fsdp is the dedicated shard axis, data may add replicas).
ZERO_AXES: Tuple[str, ...] = ("data", "fsdp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Resolved axis sizes for a device count."""

    sizes: Dict[str, int]

    @property
    def total(self) -> int:
        out = 1
        for v in self.sizes.values():
            out *= v
        return out


def resolve_axis_sizes(mesh_config, n_devices: int) -> MeshSpec:
    """Fill in data=-1 from the device count and validate divisibility."""
    sizes = {ax: getattr(mesh_config, ax) for ax in AXIS_ORDER}
    fixed = 1
    for ax, v in sizes.items():
        if v == 0 or v < -1:
            raise ConfigError(f"mesh.{ax} must be positive or -1, got {v}")
        if v != -1:
            fixed *= v
    wildcard = [ax for ax, v in sizes.items() if v == -1]
    if len(wildcard) > 1:
        raise ConfigError(f"Only one mesh axis may be -1, got {wildcard}")
    if wildcard:
        if n_devices % fixed:
            raise ConfigError(
                f"Device count {n_devices} not divisible by fixed mesh axes product {fixed} ({sizes})")
        sizes[wildcard[0]] = n_devices // fixed
    else:
        if fixed != n_devices:
            raise ConfigError(f"Mesh sizes {sizes} multiply to {fixed} != device count {n_devices}")
    return MeshSpec(sizes)


class MeshTopology:
    """The one device mesh + axis bookkeeping for a run.

    Construction: ``MeshTopology.build(mesh_config)`` uses all visible
    devices. Thin API mirrors the reference groups registry (§2.7) so
    engine/moe/sequence code asks topology questions in one place.
    """

    def __init__(self, mesh: "jax.sharding.Mesh"):
        self.mesh = mesh
        self.axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    # -- construction -------------------------------------------------

    @classmethod
    def build(cls, mesh_config=None, n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> "MeshTopology":
        import jax

        if devices is None:
            devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
        if mesh_config is None:
            from ..config.config import MeshConfig

            mesh_config = MeshConfig()
        spec = resolve_axis_sizes(mesh_config, len(devices))
        shape = tuple(spec.sizes[ax] for ax in AXIS_ORDER)
        dev_array = np.asarray(devices).reshape(shape)
        mesh = jax.sharding.Mesh(dev_array, AXIS_ORDER)
        log_dist(f"Mesh built: {dict(zip(AXIS_ORDER, shape))} over {len(devices)} devices", ranks=[0])
        return cls(mesh)

    # -- axis queries (reference utils/groups.py getters) --------------

    def size(self, *axes: str) -> int:
        out = 1
        for ax in axes:
            out *= self.axis_sizes[ax]
        return out

    @property
    def world_size(self) -> int:
        return self.size(*AXIS_ORDER)

    @property
    def data_parallel_world_size(self) -> int:
        # ZeRO/DP world = data × fsdp (see ZERO_AXES).
        return self.size(*ZERO_AXES)

    @property
    def replica_world_size(self) -> int:
        return self.size("data")

    @property
    def model_parallel_world_size(self) -> int:
        return self.size("tensor")

    @property
    def expert_parallel_world_size(self) -> int:
        return self.size("expert")

    @property
    def sequence_parallel_world_size(self) -> int:
        return self.size("seq")

    @property
    def pipe_parallel_world_size(self) -> int:
        return self.size("pipe")

    def active_axes(self) -> List[str]:
        return [ax for ax in AXIS_ORDER if self.axis_sizes[ax] > 1]

    # -- shardings -----------------------------------------------------

    def named_sharding(self, *spec) -> "jax.sharding.NamedSharding":
        import jax
        from jax.sharding import PartitionSpec

        return jax.sharding.NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> "jax.sharding.NamedSharding":
        return self.named_sharding()

    def batch_sharding(self, extra_axes: Sequence[str] = ()) -> "jax.sharding.NamedSharding":
        """Global batch dim sharded over every data-like axis (+ optional)."""
        axes = tuple(ax for ax in ("data", "fsdp", *extra_axes) if self.axis_sizes.get(ax, 1) >= 1)
        return self.named_sharding(axes)

    # -- pipeline grid (reference runtime/pipe/topology.py) ------------

    def pipe_coord(self, device_index: int) -> Dict[str, int]:
        """Axis coordinates of a flat device index in the mesh grid."""
        shape = tuple(self.axis_sizes[ax] for ax in AXIS_ORDER)
        coords = np.unravel_index(device_index, shape)
        return dict(zip(AXIS_ORDER, (int(c) for c in coords)))

    def __repr__(self) -> str:
        return f"MeshTopology({self.axis_sizes})"


# ----------------------------------------------------------------------
# Module-level registry (reference utils/groups.py singleton pattern)
# ----------------------------------------------------------------------

_TOPOLOGY: Optional[MeshTopology] = None


def initialize_topology(mesh_config=None, n_devices: Optional[int] = None, devices=None, force: bool = False) -> MeshTopology:
    global _TOPOLOGY
    if _TOPOLOGY is not None and not force:
        logger.warning("MeshTopology already initialized; reusing (pass force=True to rebuild)")
        return _TOPOLOGY
    _TOPOLOGY = MeshTopology.build(mesh_config, n_devices=n_devices, devices=devices)
    return _TOPOLOGY


def get_topology() -> MeshTopology:
    if _TOPOLOGY is None:
        raise RuntimeError("MeshTopology not initialized; call initialize_topology() or sxt.initialize() first")
    return _TOPOLOGY


def topology_is_initialized() -> bool:
    return _TOPOLOGY is not None


def reset_topology() -> None:
    global _TOPOLOGY
    _TOPOLOGY = None


# Reference-compatible getter names (utils/groups.py:57-749).

def native_shard_map() -> bool:
    """True when this jax exposes the first-class ``jax.shard_map`` (>= 0.5),
    whose partial-manual lowering handles collectives with live (size > 1)
    auto axes. The 0.4.x ``jax.experimental.shard_map`` fallback lowers
    FULL-manual regions (and partial-manual regions whose auto axes are all
    size 1) correctly, but a collective inside a partial-manual region with
    a live auto axis trips an XLA SPMD-partitioner CHECK
    (spmd_partitioner.cc:512 IsManualSubgroup) — a process abort, not an
    exception — so callers must gate statically on this, never probe."""
    import jax

    return hasattr(jax, "shard_map")


def shard_map(f, mesh=None, in_specs=None, out_specs=None, axis_names=None,
              check_vma: bool = True):
    """``jax.shard_map``-compatible facade that also runs on jax 0.4.x.

    ``axis_names`` is the set of MANUAL axes (partial-manual region);
    None means every mesh axis is manual. On 0.4.x this maps onto
    ``jax.experimental.shard_map.shard_map``'s complementary ``auto=`` set
    and ``check_vma`` onto ``check_rep``. See :func:`native_shard_map` for
    the 0.4.x lowering limits.
    """
    import jax

    if native_shard_map():
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map

    all_axes = frozenset(mesh.axis_names)
    manual = frozenset(axis_names) if axis_names is not None else all_axes
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=bool(check_vma), auto=all_axes - manual)


def _abstract_mesh_ctx():
    import jax

    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is None:
        return None  # jax 0.4.x: no trace-context abstract mesh
    try:
        return get()
    except Exception:
        return None


def inside_manual_region() -> bool:
    """True when tracing inside a (partial-)manual shard_map region.
    On jax 0.4.x (no abstract-mesh trace context) this returns False."""
    import jax

    ctx = _abstract_mesh_ctx()
    if ctx is None or not getattr(ctx, "axis_names", ()):
        return False
    try:
        return any(t == jax.sharding.AxisType.Manual for t in ctx.axis_types)
    except Exception:
        return False


def constraint_mesh(default=None):
    """Mesh to use for in-trace sharding constraints / nested shard_maps.

    Inside a (partial-)manual region, constraints must be built on the
    CONTEXT abstract mesh (whose enclosing axes are typed Manual) — a
    NamedSharding over the concrete topology mesh (all-Auto) trips the
    mesh-equality check. Outside any region — and always on jax 0.4.x,
    where nested shard_maps take the concrete mesh — returns ``default``
    (or the topology mesh)."""
    import jax

    ctx = _abstract_mesh_ctx()
    if ctx is not None and getattr(ctx, "axis_names", ()):
        try:
            if any(t == jax.sharding.AxisType.Manual for t in ctx.axis_types):
                return ctx
        except Exception:
            pass
    if default is not None:
        return default
    return get_topology().mesh


def get_data_parallel_world_size() -> int:
    return get_topology().data_parallel_world_size


def get_model_parallel_world_size() -> int:
    return get_topology().model_parallel_world_size


def get_expert_parallel_world_size() -> int:
    return get_topology().expert_parallel_world_size


def get_sequence_parallel_world_size() -> int:
    return get_topology().sequence_parallel_world_size


def get_pipe_parallel_world_size() -> int:
    return get_topology().pipe_parallel_world_size
