"""Compressed collectives: sign (1-bit) and int8-quantized reductions.

Capability parity with the reference's compressed communication stack
(SURVEY.md §2.8): the 1-bit error-feedback allreduce backends
(``runtime/comm/nccl.py:16``, ``runtime/comm/compressed.py``) and the ZeRO++
quantized collectives — qwZ quantized weight all-gather
(``partition_parameters.py:824``) and qgZ quantized hierarchical gradient
reduce (``runtime/comm/coalesced_collectives.py:31``).

TPU-native shape: these run *inside* jit/shard_map, so "compression" means
the collective's operand dtype shrinks — int8 signs or int8 blockwise
quantized values ride the ICI/DCN wire instead of fp32 (4× bytes). XLA
schedules the quantize → collective → dequantize pipeline. True sub-byte
packing (the CUDA backends' bit-packed payloads) trades ALU for bytes in a
way that only pays on host-mediated DCN paths — that path uses the native
``ops/native`` packbits on CPU.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..ops.quant import dequantize_int8, quantize_int8
from .comm import comms_logger


def _logical_bytes(x) -> int:
    """Bytes an UNcompressed collective would move for this operand."""
    return x.size * x.dtype.itemsize


def sign_psum(x, axis_name: str, err=None) -> Tuple["jax.Array", "jax.Array"]:
    """1-bit error-feedback averaging over ``axis_name``.

    Each participant contributes sign(x + err) as int8 plus one fp32 scale
    (mean |x + err|); the wire carries 1 byte/element. Returns
    (averaged_tensor, new_local_error). Must run under shard_map/pmap with
    ``axis_name`` bound.
    """
    import jax
    import jax.numpy as jnp

    combined = x + (err if err is not None else jnp.zeros_like(x))
    scale = jnp.mean(jnp.abs(combined))
    signs = jnp.where(combined >= 0, 1, -1).astype(jnp.int8)

    comms_logger.record("compressed_all_reduce", _logical_bytes(x),
                        wire_bytes=signs.size + 4, note=axis_name)
    n = jax.lax.psum(1, axis_name)
    # int8 signs summed as int32 (overflow-safe for any axis size), one
    # scalar psum for the scales. The transmitted approximation uses the
    # *mean* scale for every worker (sign_i * mean_scale), so the error
    # feedback must compensate against exactly that — not against
    # sign_i * scale_i — or the per-worker scale variance is silently
    # dropped (reference backends allreduce the exact compressed tensors).
    sign_sum = jax.lax.psum(signs.astype(jnp.int32), axis_name)
    mean_scale = jax.lax.psum(scale, axis_name) / n
    new_err = combined - signs.astype(jnp.float32) * mean_scale
    avg = sign_sum.astype(jnp.float32) * mean_scale / n
    return avg, new_err


def quantized_psum(x, axis_name: str, group_size: int = 256):
    """int8 blockwise-quantized averaging over ``axis_name`` (qgZ-style
    wire reduction: each hop moves int8 + per-group scales)."""
    import jax
    import jax.numpy as jnp

    q, scales = quantize_int8(x, group_size)
    comms_logger.record("quantized_all_reduce", _logical_bytes(x),
                        wire_bytes=q.size + 4 * scales.size, note=axis_name)
    n = jax.lax.psum(1, axis_name)
    # Dequantize-then-psum keeps exact additive semantics while the wire
    # payload (post-XLA-fusion) is the int8 operand; for the strict
    # two-level hierarchy use quantized_hierarchical_reduce.
    deq = dequantize_int8(q, scales, x.shape, jnp.float32)
    return jax.lax.psum(deq, axis_name) / n


def quantized_reduce_scatter(x, axis_name: str, group_size: int = 256,
                             scatter_dimension: int = 0):
    """int8-wire reduce-scatter (qgZ grad path, reference
    coalesced_collectives.py:31): quantize locally, all-to-all the *int8*
    payload + scales so every hop moves 1 byte/element, then dequantize and
    sum the received pieces — each rank ends with its shard of the
    quantization-rounded sum. Requires dim0 divisible by the axis size."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.psum(1, axis_name)
    assert scatter_dimension == 0, "grad flats scatter on dim 0"
    s0 = x.shape[0]
    assert s0 % n == 0, f"reduce_scatter dim {s0} not divisible by axis size {n}"
    pieces = x.reshape((n, s0 // n) + x.shape[1:])

    # per-piece quantization (quantize_int8 flattens to [groups, group]), so
    # the piece dim stays leading for the all-to-all
    q, scales = jax.vmap(lambda p: quantize_int8(p, group_size))(pieces)
    comms_logger.record("quantized_reduce_scatter", _logical_bytes(x),
                        wire_bytes=q.size + 4 * scales.size, note=axis_name)
    # all_to_all on the piece dim: the wire payload is the int8 tensor.
    q_x = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=False)
    s_x = jax.lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0, tiled=False)

    def deq_one(qi, si):
        return dequantize_int8(qi, si, pieces.shape[1:], jnp.float32)

    return jax.vmap(deq_one)(q_x, s_x).sum(axis=0)


def quantized_all_gather(x, axis_name: str, group_size: int = 256, axis: int = 0):
    """qwZ-style weight gather: each shard is quantized to int8 + scales,
    all participants gather the *quantized* payload, then dequantize —
    the gather itself moves 1/4 the bytes of a bf16/fp32 gather."""
    import jax
    import jax.numpy as jnp

    q, scales = quantize_int8(x, group_size)
    comms_logger.record("quantized_all_gather", _logical_bytes(x),
                        wire_bytes=q.size + 4 * scales.size, note=axis_name)
    q_g = jax.lax.all_gather(q, axis_name, axis=0, tiled=False)
    s_g = jax.lax.all_gather(scales, axis_name, axis=0, tiled=False)
    n = q_g.shape[0]

    def deq_one(qi, si):
        return dequantize_int8(qi, si, x.shape, jnp.float32)

    parts = jax.vmap(deq_one)(q_g, s_g)  # [n, *x.shape]
    if axis == 0:
        return parts.reshape((n * x.shape[0],) + x.shape[1:])
    order = list(range(parts.ndim))
    order.pop(0)
    order.insert(axis, 0)
    moved = parts.transpose(order)
    shape = list(x.shape)
    shape[axis] *= n
    return moved.reshape(shape)


def _int8_wire_allreduce(x, axis_name, group_size: int, log_name: Optional[str] = None):
    """Sum over ``axis_name`` (a name or tuple of names) where the wire
    payload is int8: all-gather the quantized tensor + per-group scales,
    dequantize and sum locally. A plain psum of the dequantized fp32 would
    let XLA put fp32 on the wire — this form forces the collective operand
    dtype to s8 (verifiable in HLO)."""
    import jax
    import jax.numpy as jnp

    q, s = quantize_int8(x, group_size)
    if log_name:
        comms_logger.record(log_name, _logical_bytes(x),
                            wire_bytes=q.size + 4 * s.size, note=str(axis_name))
    q_g = jax.lax.all_gather(q, axis_name, axis=0, tiled=False)     # s8 wire
    s_g = jax.lax.all_gather(s, axis_name, axis=0, tiled=False)     # scales: tiny fp32

    def deq_one(qi, si):
        return dequantize_int8(qi, si, x.shape, jnp.float32)

    return jax.vmap(deq_one)(q_g, s_g).sum(axis=0)


def quantized_hierarchical_reduce(x, intra_axis: str, inter_axis: str,
                                  group_size: int = 256):
    """qgZ two-level gradient reduction (reference coalesced_collectives.py:31):
    int8-wire reduce within the fast domain (ICI analog), re-quantize the
    partial sums, then int8-wire reduce across the slow domain (DCN analog).
    Returns the full average over both axes. Every cross-device hop carries
    1 byte/element (+ per-group fp32 scales)."""
    import jax

    n_intra = jax.lax.psum(1, intra_axis)
    n_inter = jax.lax.psum(1, inter_axis)
    lvl1 = _int8_wire_allreduce(x, intra_axis, group_size,
                                log_name="quantized_a2a_lvl1")
    lvl2 = _int8_wire_allreduce(lvl1, inter_axis, group_size,
                                log_name="quantized_a2a_lvl2")
    return lvl2 / (n_intra * n_inter)


def quantized_two_level_reduce(x, intra_axis: str, inter_axis: str,
                               group_size: int = 256):
    """The declared-hierarchy qgZ schedule (``zeropp.hierarchical_axes``):

      1. full-precision reduce-scatter INSIDE ``intra_axis`` (the fast
         domain — ICI — where bytes are cheap and exactness is free),
      2. int8-wire all-reduce of the 1/n_intra-sized partials ACROSS
         ``inter_axis`` (the slow domain — DCN — where the 4x matters),
      3. full-precision all-gather back inside ``intra_axis``.

    Returns the average over both axes. Rounding model: exactly ONE
    quantize/dequantize round-trip, applied to the intra-summed partials —
    vs the flat schedule's round-trip per level. The inter-domain wire
    moves (|x| / n_intra) int8 bytes per device: n_intra x fewer slow-wire
    bytes than flat qgZ on top of the 4x dtype win."""
    import jax
    import jax.numpy as jnp

    n_intra = jax.lax.psum(1, intra_axis)
    n_inter = jax.lax.psum(1, inter_axis)
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n_intra
    if pad:
        flat = jnp.pad(flat, (0, pad))
    comms_logger.record("qgz_intra_reduce_scatter", _logical_bytes(flat),
                        note=intra_axis)
    piece = jax.lax.psum_scatter(flat, intra_axis, scatter_dimension=0,
                                 tiled=True)
    piece = _int8_wire_allreduce(piece, inter_axis, group_size,
                                 log_name="qgz_inter_all_reduce")
    comms_logger.record("qgz_intra_all_gather", piece.size * 4,
                        note=intra_axis)
    full = jax.lax.all_gather(piece, intra_axis, axis=0, tiled=True)
    if pad:
        full = full[:x.size]
    return full.reshape(x.shape) / (n_intra * n_inter)
