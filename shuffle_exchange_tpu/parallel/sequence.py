"""Sequence parallelism and long context.

Capability parity with the reference's SP stack (SURVEY.md §5.7):

- **Ulysses** (``sequence/layer.py:277,331`` ``_SeqAllToAll`` +
  ``DistributedAttention``): activations arrive sharded on the sequence dim;
  two all-to-alls swap seq↔head sharding around any core attention so each
  device sees full sequence for a subset of heads.
- **Ring attention** (the TPU-idiomatic replacement for FPDT chunked
  attention, ``sequence/fpdt_layer.py:510,971``): KV blocks rotate around
  the "seq" mesh axis via ``ppermute`` while each device keeps its Q shard,
  with online-softmax (log-sum-exp) accumulation — full-sequence attention
  with O(T/sp) activation memory and comm overlapped by XLA.
- **Tiled compute** (``runtime/sequence_parallel/ulysses_sp.py:757,915``
  TiledMLP / tiled loss): lax.map over sequence chunks bounds activation
  memory for the MLP and the logits/loss.
- **Vocab-parallel cross entropy** (``sequence/cross_entropy.py``): CE with
  logits sharded over the "tensor" axis, no full-vocab gather.

All functions are written for use inside ``shard_map`` (axis names must be
bound); pure-jit callers get the same math when the axis is size 1.
"""

from __future__ import annotations

from typing import Callable, Optional

from . import comm


# ----------------------------------------------------------------------
# Ulysses
# ----------------------------------------------------------------------


def seq_to_head_a2a(x, axis_name: str = "seq"):
    """[B, T/sp, H, D] -> [B, T, H/sp, D] (head-scatter, seq-gather).

    H must divide sp here; :class:`DistributedAttention` handles uneven
    head counts by padding before calling this (reference
    ``uneven_heads_all2all``, sequence/layer.py:111)."""
    import jax

    sp = jax.lax.psum(1, axis_name)
    if x.shape[2] % sp:
        raise ValueError(
            f"head count ({x.shape[2]}) not divisible by the sequence-parallel "
            f"degree ({sp}); route through DistributedAttention, which pads")
    return comm.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def head_to_seq_a2a(x, axis_name: str = "seq"):
    """[B, T, H/sp, D] -> [B, T/sp, H, D] (seq-scatter, head-gather)."""
    return comm.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)


class DistributedAttention:
    """Ulysses wrapper around any local attention fn (reference
    ``sequence/layer.py:331``): q/k/v sharded on seq dim in, output sharded
    on seq dim out.

    Uneven head counts (reference ``uneven_heads_all2all``,
    sequence/layer.py:111): when H (or the GQA kv count) does not divide the
    sp degree, heads are zero-padded — but GQA KV is NEVER expanded to H
    before the wire. The per-rank q-chunk is rounded up to a multiple of
    the GQA group size ``n_rep`` (Hc = ceil(H / sp / n_rep) * n_rep), so a
    contiguous head scatter keeps every q chunk colocated with exactly its
    kv groups: the kv all-to-all carries Hp/n_rep heads (a ceil-rounding
    factor over KV), not H (which would be n_rep x the bytes). The local
    attention sees unexpanded GQA kv and pad heads attend to zero kv heads
    whose outputs are sliced away after the reverse all-to-all."""

    def __init__(self, local_attention: Callable, sequence_axis: str = "seq",
                 scatter_idx: int = 2, gather_idx: int = 1):
        self.local_attn = local_attention
        self.axis = sequence_axis

    def __call__(self, q, k, v, *args, **kwargs):
        import jax
        import jax.numpy as jnp

        sp = jax.lax.psum(1, self.axis)
        H, KV = q.shape[2], k.shape[2]
        even = H % sp == 0 and KV % sp == 0
        if not even:
            n_rep = H // KV
            # per-rank q chunk, rounded to whole GQA groups
            hc = -(-H // sp // n_rep) * n_rep
            hp, kvp = sp * hc, sp * hc // n_rep
            hp_expand = -(-H // sp) * sp   # old path: expand KV to H, pad
            # >= : on wire-byte ties the expand path wins — group-aligned
            # padding always has at least as much q padding, so it costs
            # strictly more local attention FLOPs for the same bytes.
            if hp + 2 * kvp >= 3 * hp_expand:
                # Group-aligned padding loses when ceil(H/sp) < n_rep
                # (MQA-ish KV with large sp: q pads to sp*n_rep heads).
                # Fall back to expanding KV to H — total wire heads
                # 3*hp_expand — whenever that is cheaper.
                from ..ops.flash_attention import _repeat_kv

                k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
                hp = kvp = hp_expand
            q = jnp.pad(q, ((0, 0), (0, 0), (0, hp - H), (0, 0)))
            k = jnp.pad(k, ((0, 0), (0, 0), (0, kvp - k.shape[2]), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, 0), (0, kvp - v.shape[2]), (0, 0)))
        qh = seq_to_head_a2a(q, self.axis)
        kh = seq_to_head_a2a(k, self.axis)
        vh = seq_to_head_a2a(v, self.axis)
        out = self.local_attn(qh, kh, vh, *args, **kwargs)
        out = head_to_seq_a2a(out, self.axis)
        return out if even else out[:, :, :H]


def ulysses_attention(q, k, v, axis_name: str = "seq", attn_fn: Optional[Callable] = None,
                      causal: bool = True):
    """Functional form of DistributedAttention."""
    from ..ops.flash_attention import flash_attention

    attn = attn_fn or (lambda q, k, v: flash_attention(q, k, v, causal=causal))
    return DistributedAttention(attn, axis_name)(q, k, v)


# ----------------------------------------------------------------------
# Ring attention (causal, online softmax)
# ----------------------------------------------------------------------


def _ring_kv_chunk(Tq: int, requested: int = 1024) -> int:
    """Largest divisor of Tq that is <= requested (flash-style kv tiling).
    Shard lengths with no usable divisor (prime-ish Tq would otherwise
    degrade to ck=1 — a Tq-step scan of rank-1 einsums) fall back to one
    whole-block chunk; remat still bounds backward residuals per hop."""
    c = min(Tq, requested)
    while Tq % c:
        c -= 1
    if c < min(64, Tq):
        return Tq
    return c


def _ring_hop_kernel_ok(q, interpret: bool) -> bool:
    """Can the per-hop Pallas flash kernel serve this ring? (mirrors the
    ALiBi-family gate: MXU-friendly blocks, supported head dim)."""
    from ..ops.dispatch import pallas_enabled
    from ..ops.flash_attention import _pick_block

    if not (pallas_enabled() or interpret):
        return False
    _, Tq, _, D = q.shape
    from ..ops.flash_attention import BLOCK_CANDIDATES

    bq = _pick_block(Tq, q.dtype.itemsize)
    # candidate blocks only — the n-itself fallback would be one giant tile
    return D in (64, 128) and Tq % bq == 0 and bq in BLOCK_CANDIDATES


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = True,
                   kv_chunk: int = 1024, use_kernel: str = "auto",
                   interpret: bool = False, alibi_slopes=None,
                   hop_remat: bool = True):
    """Blockwise full-sequence attention with rotating KV — flash-grade.

    q/k/v: [B, T_local, H|Hkv, D] — this device's sequence shard (layout
    matches ops.flash_attention). Must run inside shard_map with
    ``axis_name`` bound. Accumulation in fp32.

    Memory (VERDICT r3 weak #5): each ring hop is a CHECKPOINTED chunked
    online-softmax — the forward never holds more than one
    [B, H, T/sp, kv_chunk] logits tile, and backward recomputes the tiles
    per hop, so autodiff residuals are the O(T/sp * D) hop inputs
    (q, the rotated kv blocks, and the running (acc, m, l) carry), never
    [T/sp, T/sp] score matrices.

    Compute (round 5, VERDICT r4 #5 / SURVEY §5.7 "splash kernel +
    ppermute"): when the shapes pass :func:`_ring_hop_kernel_ok`, each hop
    runs the Pallas :func:`~..ops.alibi_attention.flash_attention_lse`
    kernel (diagonal hop: causal variant; earlier-source hops: full
    variant; later-source hops skip compute via ``lax.cond``) and partial
    outputs merge by logsumexp — the MXU sees flash tiles, not jnp einsum
    chunks. ``use_kernel``: "auto" | True | False. The jnp chunked path
    remains for shapes the kernel gate rejects.

    ``hop_remat=False`` (ISSUE 15, the ``save_flash_lse`` composition):
    drops the per-hop ``jax.checkpoint`` so an ENCLOSING layer-level
    checkpoint with ``remat_policy="save_flash_lse"`` governs instead —
    each hop's kernel (out, lse) pair carries the ``flash_out``/
    ``flash_lse`` checkpoint names, the policy saves exactly those, and
    the backward ring enters the dq/dkv kernels from SAVED lse with the
    forward kernel DCE'd out of the recompute (the PR 3 discipline, per
    hop). Residuals are then sp x O(T/sp · D) per layer = the unsharded
    activation footprint, vs the default hop checkpoint's O(T/sp · D)
    with a per-hop forward re-run in backward. Kernel path only: the jnp
    chunked path has no named hop outputs for the policy to save, so it
    keeps its per-hop checkpoint regardless (dropping it would just let
    backward linearize all sp hops' score chunks at once).
    """
    import jax
    import jax.numpy as jnp

    sp = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    if alibi_slopes is not None and use_kernel is True:
        raise ValueError("ring hop kernel has no per-hop bias offset; "
                         "ALiBi rings use the jnp chunked path")
    kernel_on = (alibi_slopes is None and
                 (use_kernel is True or
                  (use_kernel == "auto" and _ring_hop_kernel_ok(q, interpret))))
    if use_kernel is True and not _ring_hop_kernel_ok(q, interpret):
        from ..ops.dispatch import pallas_enabled

        if not (pallas_enabled() or interpret):
            raise ValueError(
                "ring hop kernel forced but Pallas is disabled on this "
                "backend — run on TPU, pass interpret=True, or drop "
                "use_kernel=True")
        raise ValueError(
            f"ring hop kernel forced but the shape gate rejects it "
            f"(Tq={Tq}, D={D}; need D in (64,128) and a swept block "
            f"size dividing Tq)")
    if kernel_on:
        return _ring_attention_kernel(q, k, v, axis_name, causal, interpret,
                                      hop_remat=hop_remat)
    # GQA: rotate the UN-repeated kv shards (KV-sized ring hops — repeating
    # first would multiply ppermute bytes by H/KV); expand per chunk inside
    # the accumulate step, where the broadcast stays local (and is
    # recomputed, not saved, under the hop checkpoint).
    n_rep = H // k.shape[2]
    scale = D ** -0.5
    q32 = q.astype(jnp.float32) * scale

    q_pos = my_idx * Tq + jnp.arange(Tq)
    ck = _ring_kv_chunk(Tq, kv_chunk)
    n_chunks = Tq // ck

    def hop_attn(carry, q32, k_blk, v_blk, src_idx):
        """One ring hop: online softmax over the hop's kv block, tiled in
        ``ck``-sized chunks so the score tile is [B,H,Tq,ck]."""
        def chunk_body(c, chunk_idx):
            acc, m_run, l_run = c
            ks = jax.lax.dynamic_slice_in_dim(k_blk, chunk_idx * ck, ck, axis=1)
            vs = jax.lax.dynamic_slice_in_dim(v_blk, chunk_idx * ck, ck, axis=1)
            if n_rep > 1:
                from ..ops.flash_attention import _repeat_kv

                ks = _repeat_kv(ks, n_rep)
                vs = _repeat_kv(vs, n_rep)
            logits = jnp.einsum("bthd,bshd->bhts", q32, ks.astype(jnp.float32))
            kv_pos = src_idx * Tq + chunk_idx * ck + jnp.arange(ck)
            if alibi_slopes is not None:
                # BLOOM ALiBi under CP: absolute key positions are global
                # in the ring, so the bias is exact across hops
                logits = logits + (alibi_slopes[None, :, None, None]
                                   * kv_pos.astype(jnp.float32)[None, None, None, :])
            if causal:
                mask = q_pos[:, None] >= kv_pos[None, :]
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
            m_blk = jnp.max(logits, axis=-1)                      # [B,H,Tq]
            m_new = jnp.maximum(m_run, m_blk)
            # guard fully-masked chunks (m_new = -inf): contribute nothing
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(logits - m_safe[..., None])
            p = jnp.where(jnp.isfinite(logits), p, 0.0)
            correction = jnp.where(jnp.isfinite(m_run), jnp.exp(m_run - m_safe), 0.0)
            l_new = l_run * correction + p.sum(-1)
            acc_new = (acc * correction[..., None]
                       + jnp.einsum("bhts,bshd->bhtd", p, vs.astype(jnp.float32)))
            return (acc_new, m_new, l_new), None

        if n_chunks == 1:
            carry, _ = chunk_body(carry, jnp.asarray(0, jnp.int32))
            return carry
        carry, _ = jax.lax.scan(chunk_body, carry,
                                jnp.arange(n_chunks, dtype=jnp.int32))
        return carry

    # Remat per hop: backward recomputes one hop's score tiles at a time
    # instead of saving sp of them. Unconditional on this jnp path —
    # hop_remat=False exists for the KERNEL path, whose hop outputs carry
    # the save_flash_lse names an enclosing layer checkpoint saves; here
    # there are no named hop outputs, so dropping the boundary would only
    # let backward linearize all sp hops at once (O(sp) score-chunk
    # residuals on exactly the long-context shapes CP targets).
    hop_attn = jax.checkpoint(hop_attn)

    def rotate(kv):
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        return jax.tree_util.tree_map(lambda x: comm.ppermute(x, axis_name, perm), kv)

    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    m0 = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    # The chunk scan's carry must already be device-varying over the seq
    # axis (its outputs are), or shard_map's vma check rejects the scan.
    # (jax 0.4.x has no pcast and no vma checking — skip the cast there.)
    _pcast = getattr(jax.lax, "pcast", None)
    if _pcast is not None:
        acc0, m0, l0 = (_pcast(t, (axis_name,), to="varying")
                        for t in (acc0, m0, l0))

    carry = (acc0, m0, l0)
    kv = (k, v)
    # Unrolled python loop over sp hops (sp is static); XLA overlaps each
    # ppermute with the previous block's compute.
    for r in range(sp):
        src_idx = (my_idx - r) % sp
        carry = hop_attn(carry, q32, kv[0], kv[1], src_idx)
        if r != sp - 1:
            kv = rotate(kv)
    acc, m_run, l_run = carry
    out = acc / jnp.maximum(l_run[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B,T,H,D]


def _ring_attention_kernel(q, k, v, axis_name: str, causal: bool,
                           interpret: bool, hop_remat: bool = True):
    """Ring attention with a Pallas flash kernel inside each hop.

    Each hop attends the local Q shard against one rotated KV shard through
    :func:`~..ops.alibi_attention.flash_attention_lse` and the partial
    (out, lse) pairs merge exactly:
    ``out = Σ_h out_h · exp(lse_h − lse_tot)``. For causal rings the hop's
    role is data-dependent per device (the source block's causal offset):
    the r=0 hop is the diagonal (causal kernel, trace-time static), and
    each later hop runs the full kernel iff the source shard precedes this
    one — selected with ``lax.cond`` so skipped devices do no attention
    work. (Load is inherently ring-position-skewed for causal; a zigzag
    block permutation would even it out — future knob.)"""
    import jax
    import jax.numpy as jnp

    from ..ops.alibi_attention import flash_attention_lse

    sp = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape

    def merge(carry, out_h, lse_h):
        out_run, lse_run = carry
        m = jnp.maximum(lse_run, lse_h)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        w1 = jnp.where(jnp.isfinite(lse_run), jnp.exp(lse_run - m_safe), 0.0)
        w2 = jnp.where(jnp.isfinite(lse_h), jnp.exp(lse_h - m_safe), 0.0)
        r = w1 + w2
        r_safe = jnp.maximum(r, 1e-30)
        # lse layout [B,H,T] -> weight layout [B,T,H,1] for the outputs
        as_bth = lambda t: t.transpose(0, 2, 1)[..., None]
        out_new = (out_run * as_bth(w1 / r_safe)
                   + out_h.astype(jnp.float32) * as_bth(w2 / r_safe))
        lse_new = jnp.where(r > 0, m_safe + jnp.log(r_safe), -jnp.inf)
        return out_new, lse_new

    def hop(carry, q, k_blk, v_blk, src_idx):
        if causal:
            def full_branch(q, kb, vb):
                return flash_attention_lse(q, kb, vb, False, interpret)

            def skip_branch(q, kb, vb):
                # constants must carry the same varying-axes set as the
                # kernel branches' outputs or cond rejects the branch types
                # (jax 0.4.x: no vma tracking — constants pass as-is)
                if getattr(jax.lax, "pcast", None) is None:
                    return (jnp.zeros(q.shape, q.dtype),
                            jnp.full((B, H, Tq), -jnp.inf, jnp.float32))
                vma = frozenset()
                for t in (q, kb, vb):
                    vma = vma | jax.typeof(t).vma

                def mk(z):
                    need = tuple(sorted(vma - jax.typeof(z).vma))
                    return jax.lax.pcast(z, need, to="varying") if need else z

                return (mk(jnp.zeros(q.shape, q.dtype)),
                        mk(jnp.full((B, H, Tq), -jnp.inf, jnp.float32)))

            def diag_branch(q, kb, vb):
                return flash_attention_lse(q, kb, vb, True, interpret)

            # diagonal iff src == me; earlier shards attend fully; later
            # shards are entirely masked -> skip the kernel
            out_h, lse_h = jax.lax.cond(
                src_idx == my_idx, diag_branch,
                lambda q, kb, vb: jax.lax.cond(
                    src_idx < my_idx, full_branch, skip_branch, q, kb, vb),
                q, k_blk, v_blk)
        else:
            out_h, lse_h = flash_attention_lse(q, k_blk, v_blk, False,
                                               interpret)
        return merge(carry, out_h, lse_h)

    # Remat per hop: residuals are the hop inputs (O(Tq·D)), and the
    # kernel's own custom_vjp recomputes score tiles in its dq/dkv passes.
    # hop_remat=False (save_flash_lse composition): no inner boundary —
    # the enclosing layer checkpoint's save_only_these_names policy saves
    # each hop's tagged (flash_out, flash_lse) pair, so the backward ring
    # enters the dq/dkv kernels from saved lse and the forward kernel is
    # DCE'd out of the backward recompute entirely (asserted by pallas-
    # call counting in tests/test_context_parallel.py).
    if hop_remat:
        hop = jax.checkpoint(hop)

    def rotate(kv):
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        return jax.tree_util.tree_map(
            lambda x: comm.ppermute(x, axis_name, perm), kv)

    out0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    lse0 = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    _pcast = getattr(jax.lax, "pcast", None)
    if _pcast is not None:
        out0, lse0 = (_pcast(t, (axis_name,), to="varying")
                      for t in (out0, lse0))
    carry = (out0, lse0)
    kv = (k, v)
    for r in range(sp):
        src_idx = (my_idx - r) % sp
        carry = hop(carry, q, kv[0], kv[1], src_idx)
        if r != sp - 1:
            kv = rotate(kv)
    out_run, _ = carry
    return out_run.astype(q.dtype)  # [B,T,H,D]


# ----------------------------------------------------------------------
# Tiled compute
# ----------------------------------------------------------------------


def tiled_mlp(fn: Callable, x, n_tiles: int, axis: int = 1):
    """Apply ``fn`` over sequence tiles to bound activation memory
    (reference TiledMLP ulysses_sp.py:757). fn must be pointwise along
    ``axis`` (true for transformer MLPs)."""
    import jax
    import jax.numpy as jnp

    if n_tiles <= 1:
        return fn(x)
    T = x.shape[axis]
    assert T % n_tiles == 0, f"seq {T} not divisible by n_tiles {n_tiles}"
    tiles = jnp.moveaxis(x, axis, 0).reshape((n_tiles, T // n_tiles) + x.shape[:axis] + x.shape[axis + 1:])
    out_tiles = jax.lax.map(lambda t: fn(jnp.moveaxis(t, 0, axis)), tiles)
    # out_tiles: [n_tiles, ..., tile, ...] with tile at `axis`+1
    out = jnp.concatenate([out_tiles[i] for i in range(n_tiles)], axis=axis)
    return out


def tiled_loss(loss_fn: Callable, logits_fn: Callable, x, labels, n_tiles: int):
    """Chunked logits+loss (reference tiled loss ulysses_sp.py:915; FPDT
    chunked logits fpdt_layer.py:1137): never materializes [B, T, vocab]."""
    import jax
    import jax.numpy as jnp

    B, T = labels.shape
    assert T % n_tiles == 0
    chunk = T // n_tiles

    def body(i, acc):
        sl = jax.lax.dynamic_slice_in_dim(x, i * chunk, chunk, axis=1)
        lb = jax.lax.dynamic_slice_in_dim(labels, i * chunk, chunk, axis=1)
        logits = logits_fn(sl)
        loss, count = loss_fn(logits, lb)
        return (acc[0] + loss, acc[1] + count)

    total, count = jax.lax.fori_loop(0, n_tiles, body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)))
    return total / jnp.maximum(count, 1.0)


# ----------------------------------------------------------------------
# Vocab-parallel cross entropy (reference sequence/cross_entropy.py)
# ----------------------------------------------------------------------


def vocab_parallel_cross_entropy(logits_shard, labels, axis_name: str = "tensor",
                                 vocab_shard_size: Optional[int] = None, ignore_index: int = -100):
    """CE where logits [.., V/tp] are sharded on the vocab dim over
    ``axis_name``. Returns mean NLL over non-ignored labels. Runs inside
    shard_map."""
    import jax
    import jax.numpy as jnp

    V_local = logits_shard.shape[-1]
    tp_idx = jax.lax.axis_index(axis_name)
    vocab_start = tp_idx * V_local
    logits32 = logits_shard.astype(jnp.float32)

    local_max = logits32.max(-1)
    global_max = comm.pmax(local_max, axis_name)
    sumexp = jnp.exp(logits32 - global_max[..., None]).sum(-1)
    global_sumexp = comm.psum(sumexp, axis_name)
    lse = global_max + jnp.log(global_sumexp)

    local_label = labels - vocab_start
    in_shard = (local_label >= 0) & (local_label < V_local)
    safe = jnp.clip(local_label, 0, V_local - 1)
    picked = jnp.take_along_axis(logits32, safe[..., None], axis=-1)[..., 0]
    target_logit = comm.psum(jnp.where(in_shard, picked, 0.0), axis_name)

    mask = labels != ignore_index
    nll = jnp.where(mask, lse - target_logit, 0.0)
    return nll.sum() / jnp.maximum(mask.sum(), 1)
