"""Communication facade: JAX collectives + op-level accounting.

Capability parity with the reference's ``deepspeed.comm`` (``comm/comm.py``:
global backend, ``@timed_op`` comms logging, ``init_distributed`` env/MPI
rank discovery, ``log_summary`` straggler/bandwidth report). The TPU-native
difference (SURVEY.md §2.8): collectives are *traced* into jit programs and
scheduled by XLA over ICI/DCN, so instrumentation happens at trace time —
every wrapper records op name, payload bytes and axis — and wall-clock
timing is measured around the jitted step, not per op. Eager (host-driven)
collectives (checkpoint barriers, multihost sync) are timed directly.
"""

from __future__ import annotations

import os
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence

from ..utils.logging import log_dist, logger

# ----------------------------------------------------------------------
# Comms logger (reference: utils/comms_logging.py + comm/comm.py:102-142)
# ----------------------------------------------------------------------


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False, prof_all: bool = True,
                 debug: bool = False, prof_ops: Optional[List[str]] = None):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        self.prof_ops = prof_ops or []
        # op name -> {"count": n, "bytes": logical, "wire_bytes": on-the-wire,
        #             "times": [..] (eager only)}. ``bytes`` is the LOGICAL
        # payload (operand dtype × elements — what an uncompressed collective
        # would move); ``wire_bytes`` is what actually rides the wire
        # (the s8 payload + fp32 scales for quantized collectives; equal to
        # ``bytes`` for plain ops). The 4x ZeRO++ reduction is the ratio.
        self.stats: Dict[str, Dict[str, Any]] = defaultdict(
            lambda: {"count": 0, "bytes": 0, "wire_bytes": 0, "times": []})

    def configure(self, config) -> None:
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.debug = config.debug
        self.prof_ops = list(config.prof_ops)

    def _should_log(self, name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or name in self.prof_ops

    def record(self, name: str, nbytes: int, elapsed: Optional[float] = None, note: str = "",
               wire_bytes: Optional[int] = None) -> None:
        """``nbytes`` is the logical payload; ``wire_bytes`` what actually
        crosses the wire (defaults to ``nbytes`` for uncompressed ops)."""
        if not self._should_log(name):
            return
        rec = self.stats[name]
        rec["count"] += 1
        rec["bytes"] += int(nbytes)
        rec["wire_bytes"] += int(wire_bytes if wire_bytes is not None else nbytes)
        if elapsed is not None:
            rec["times"].append(elapsed)
        if self.verbose:
            log_dist(f"comm op: {name} | bytes: {nbytes} | wire: "
                     f"{wire_bytes if wire_bytes is not None else nbytes} | {note}",
                     ranks=[0])

    def log_summary(self, show_straggler: bool = False) -> str:
        """Bandwidth/count table; eager ops include measured time. ``Wire MB``
        and ``Comp x`` expose the quantized-collective compression: logical
        bytes / wire bytes (~4x for fp32-grad qgZ, ~2x for bf16-weight qwZ)."""
        lines = [f"{'Op':<24}{'Count':>8}{'Total MB':>12}{'Wire MB':>12}"
                 f"{'Comp x':>8}{'Avg ms':>10}{'Busbw GB/s':>12}"]
        for name, rec in sorted(self.stats.items()):
            mb = rec["bytes"] / 1e6
            wire_mb = rec.get("wire_bytes", rec["bytes"]) / 1e6
            comp = rec["bytes"] / max(1, rec.get("wire_bytes", rec["bytes"]))
            if rec["times"]:
                avg_ms = 1000 * sum(rec["times"]) / len(rec["times"])
                busbw = (rec["bytes"] / max(1, rec["count"])) / max(1e-9, (sum(rec["times"]) / len(rec["times"]))) / 1e9
            else:
                avg_ms, busbw = 0.0, 0.0
            lines.append(f"{name:<24}{rec['count']:>8}{mb:>12.2f}{wire_mb:>12.2f}"
                         f"{comp:>8.2f}{avg_ms:>10.3f}{busbw:>12.2f}")
        report = "\n".join(lines)
        log_dist("comms log summary:\n" + report, ranks=[0])
        return report

    def op_stats(self, name: str) -> Dict[str, Any]:
        """A copy of one op's accumulated stats ({}-like zeros if unseen)."""
        rec = self.stats.get(name)
        if rec is None:
            return {"count": 0, "bytes": 0, "wire_bytes": 0, "times": []}
        return {k: (list(v) if isinstance(v, list) else v) for k, v in rec.items()}

    def reset(self) -> None:
        self.stats.clear()


comms_logger = CommsLogger()


def configure(comms_config) -> None:
    comms_logger.configure(comms_config)


def log_summary(show_straggler: bool = False) -> str:
    return comms_logger.log_summary(show_straggler=show_straggler)


def _nbytes(x) -> int:
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(x)
        return sum(getattr(l, "size", 0) * getattr(getattr(l, "dtype", None), "itemsize", 4) for l in leaves)
    except Exception:
        return 0


# ----------------------------------------------------------------------
# Distributed bootstrap (reference: comm/comm.py:643 init_distributed +
# mpi_discovery :712). On TPU this is jax.distributed.initialize; rank/size
# come from the TPU runtime or from env/MPI-style variables.
# ----------------------------------------------------------------------

_INITIALIZED = False


def init_distributed(dist_backend: str = "xla", auto_mpi_discovery: bool = True,
                     init_method: Optional[str] = None, rank: int = -1, world_size: int = -1,
                     timeout=None, dist_init_required: Optional[bool] = None) -> None:
    """Idempotent multi-host bring-up.

    Discovery order mirrors the reference: explicit args > launcher env
    (COORDINATOR_ADDRESS/PROCESS_ID/NUM_PROCESSES, or RANK/WORLD_SIZE/
    MASTER_ADDR:MASTER_PORT) > MPI-style env (OMPI_COMM_WORLD_*) > single
    process.
    """
    global _INITIALIZED
    if _INITIALIZED or dist_init_required is False:
        return
    import jax

    coordinator = init_method or os.environ.get("COORDINATOR_ADDRESS")
    if coordinator is None and os.environ.get("MASTER_ADDR"):
        coordinator = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', '29500')}"
    if rank < 0:
        rank = int(os.environ.get("PROCESS_ID", os.environ.get("RANK",
                   os.environ.get("OMPI_COMM_WORLD_RANK", "-1") if auto_mpi_discovery else "-1")))
    if world_size < 0:
        world_size = int(os.environ.get("NUM_PROCESSES", os.environ.get("WORLD_SIZE",
                         os.environ.get("OMPI_COMM_WORLD_SIZE", "-1") if auto_mpi_discovery else "-1")))
    try:
        if coordinator and world_size > 1:
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=world_size,
                                       process_id=max(0, rank))
            log_dist(f"jax.distributed initialized: {coordinator} rank={rank}/{world_size}", ranks=[0])
        elif jax.process_count() > 1:
            pass  # TPU runtime already initialized multi-host
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise
    _INITIALIZED = True


def get_rank() -> int:
    import jax

    return jax.process_index()


def get_world_size() -> int:
    import jax

    return jax.process_count()


def barrier(name: str = "barrier") -> None:
    """Host-level sync across processes (eager, timed)."""
    import jax

    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    t0 = time.time()
    multihost_utils.sync_global_devices(name)
    comms_logger.record("barrier", 0, elapsed=time.time() - t0, note=name)


# ----------------------------------------------------------------------
# In-jit collectives. Thin wrappers over lax so every collective the
# framework issues is (a) named consistently and (b) accounted at trace time.
# ----------------------------------------------------------------------


def psum(x, axis_name, axis_index_groups=None):
    import jax

    comms_logger.record("all_reduce", _nbytes(x), note=str(axis_name))
    return jax.lax.psum(x, axis_name, axis_index_groups=axis_index_groups)


def pmean(x, axis_name, axis_index_groups=None):
    import jax

    comms_logger.record("all_reduce", _nbytes(x), note=str(axis_name))
    return jax.lax.pmean(x, axis_name, axis_index_groups=axis_index_groups)


def pmax(x, axis_name):
    import jax

    comms_logger.record("all_reduce", _nbytes(x), note=str(axis_name))
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name, axis: int = 0, tiled: bool = True, axis_index_groups=None):
    import jax

    comms_logger.record("all_gather", _nbytes(x), note=str(axis_name))
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled, axis_index_groups=axis_index_groups)


def reduce_scatter(x, axis_name, scatter_dimension: int = 0, tiled: bool = True, axis_index_groups=None):
    import jax

    comms_logger.record("reduce_scatter", _nbytes(x), note=str(axis_name))
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled,
                                axis_index_groups=axis_index_groups)


def all_to_all(x, axis_name, split_axis: int, concat_axis: int, tiled: bool = False, axis_index_groups=None):
    import jax

    comms_logger.record("all_to_all", _nbytes(x), note=str(axis_name))
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
                              tiled=tiled, axis_index_groups=axis_index_groups)


def ppermute(x, axis_name, perm: Sequence):
    import jax

    comms_logger.record("send_recv", _nbytes(x), note=str(axis_name))
    return jax.lax.ppermute(x, axis_name, perm=list(perm))


def axis_index(axis_name):
    import jax

    return jax.lax.axis_index(axis_name)


def broadcast_one_to_all(x, is_source: Optional[bool] = None):
    """Eager host-level broadcast from process 0 (reference: dist.broadcast
    of initial weights, engine.py:1242)."""
    from jax.experimental import multihost_utils

    t0 = time.time()
    out = multihost_utils.broadcast_one_to_all(x, is_source=is_source)
    comms_logger.record("broadcast", _nbytes(x), elapsed=time.time() - t0)
    return out


def process_allgather(x):
    """Eager host-level all-gather: every process receives every process's
    value, stacked on a leading process dim (reference: dist.all_gather on
    host tensors for cross-rank consistency checks)."""
    from jax.experimental import multihost_utils

    t0 = time.time()
    out = multihost_utils.process_allgather(x)
    comms_logger.record("all_gather", _nbytes(x), elapsed=time.time() - t0)
    return out
