"""MoE gating: top-1 / top-2 / top-k with capacity and aux losses.

Capability parity with the reference's ``moe/sharded_moe.py`` (top1gating
:183, top2gating :290, topkgating :374 — itself the GShard formulation):
softmax gate over experts, iterative top-k selection, per-expert capacity
``ceil(k·S/E · capacity_factor)`` with overflow drop, load-balancing aux
loss ``E · Σ_e mean(gates_e)·mean(mask_e)``, optional gate-noise for
exploration, and the (combine_weights, dispatch_mask) einsum-dispatch
contract.

All shapes are static: [S, E] in, ([S, E, C], [S, E, C] bool, aux) out —
XLA-friendly (no dynamic token routing; drops are masked, not ragged).
"""

from __future__ import annotations

from typing import NamedTuple


class GateOutput(NamedTuple):
    combine_weights: "jax.Array"   # [S, E, C] f32
    dispatch_mask: "jax.Array"     # [S, E, C] bool
    aux_loss: "jax.Array"          # scalar
    metadata: dict                 # expert_counts, dropped fraction (traced)


class GateCompact(NamedTuple):
    """Index-form capacity assignment (same semantics as GateOutput's dense
    masks, O(S·k) instead of O(S·E·C)): the dense dispatch/combine einsums
    are one-hot MATMULS costing 2·S·E·C·M flops each — 4x the expert
    compute itself at bench shapes — while gather/scatter dispatch moves
    the same rows for free (round-5 on-chip profile)."""

    eidx: "jax.Array"       # [S, k] i32  expert id per choice
    loc: "jax.Array"        # [S, k] i32  slot within the expert's buffer
    kept: "jax.Array"       # [S, k] bool False = dropped (over capacity)
    weights: "jax.Array"    # [S, k] f32  post-drop (+renorm) combine weight
    capacity: int
    aux_loss: "jax.Array"
    metadata: dict


def compute_capacity(num_tokens: int, num_experts: int, k: int, capacity_factor: float,
                     min_capacity: int = 4) -> int:
    cap = int(-(-num_tokens * k * capacity_factor // num_experts))
    return max(cap, min_capacity)


def topk_select(logits, k: int, normalize_weights: bool = True,
                train: bool = False, rng=None, noise_std: float = 0.0):
    """The ONE top-k routing rule (iterative argmax — ties broken by
    expert order), shared by the capacity path (topk_gating) and the
    dropless ragged path (moe/layer.expert_mlp_ragged), so the two can
    never diverge on selection/noise/aux semantics.

    logits [S, E] -> (idx [S,k] i32, weights [S,k] f32, aux_loss, masks)
    where masks is the per-choice one-hot list and aux_loss is the
    reference l_aux on the first choice (moe/sharded_moe.py).
    """
    import jax
    import jax.numpy as jnp

    E = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    if train and noise_std > 0.0 and rng is not None:
        logits = logits + noise_std * jax.random.normal(rng, logits.shape, jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)

    idxs, ws, masks = [], [], []
    masked = logits
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        idxs.append(idx.astype(jnp.int32))
        ws.append(jnp.sum(gates * m, axis=-1))
        masks.append(m)
        masked = jnp.where(m > 0, -jnp.inf, masked)

    # Aux load-balancing loss on the first choice (reference l_aux):
    aux_loss = E * jnp.sum(gates.mean(axis=0) * masks[0].mean(axis=0))

    idx = jnp.stack(idxs, axis=1)
    w = jnp.stack(ws, axis=1)
    if normalize_weights and k > 1:
        w = w / jnp.maximum(w.sum(axis=1, keepdims=True), 1e-9)
    return idx, w, aux_loss, masks


def topk_gating_compact(logits, k: int = 2, capacity_factor: float = 1.0,
                        min_capacity: int = 4, train: bool = True, rng=None,
                        noise_std: float = 0.0, normalize_weights: bool = True,
                        drop_tokens: bool = True) -> GateCompact:
    """logits [S, E] -> GateCompact: the ONE capacity-assignment rule
    (selection, buffer positions, drops, weight renormalization, aux loss).
    ``topk_gating`` densifies this into the GShard einsum contract."""
    import jax
    import jax.numpy as jnp

    S, E = logits.shape
    # weights re-normalize AFTER capacity drops below, so take them raw here
    idx, raw_w, aux_loss, masks = topk_select(
        logits, k, normalize_weights=False, train=train, rng=rng, noise_std=noise_std)
    gates = raw_w  # per-choice raw gate probabilities [S, k]

    capacity = compute_capacity(S, E, k, capacity_factor, min_capacity) if drop_tokens else S

    # Position of each token within its expert's buffer, priority: choice
    # order first (all 1st choices beat 2nd choices), token order second.
    locations = []
    running = jnp.zeros((E,), jnp.float32)
    kept_masks = []
    for m in masks:
        loc = jnp.cumsum(m, axis=0) - m + running[None, :]
        running = running + m.sum(axis=0)
        if drop_tokens:
            m = m * (loc < capacity)
        kept_masks.append(m)
        locations.append(loc)

    gate_weights = []
    for j, m in enumerate(kept_masks):
        # raw per-choice probability, zeroed when the slot was dropped
        gate_weights.append(gates[:, j] * m.sum(axis=-1))  # [S]
    if normalize_weights and k > 1:
        denom = sum(gate_weights)
        denom = jnp.maximum(denom, 1e-9)
        gate_weights = [g / denom for g in gate_weights]

    loc_idx = jnp.stack([(loc * m).sum(axis=-1).astype(jnp.int32)
                         for loc, m in zip(locations, kept_masks)], axis=1)
    kept_sk = jnp.stack([m.sum(axis=-1) > 0 for m in kept_masks], axis=1)
    w_sk = jnp.stack(gate_weights, axis=1)

    expert_counts = sum(kept_masks).sum(axis=0)
    kept = sum(m.sum() for m in kept_masks)
    total = sum(m.sum() for m in masks)
    metadata = {"expert_counts": expert_counts, "drop_fraction": 1.0 - kept / jnp.maximum(total, 1.0),
                "capacity": capacity}
    return GateCompact(idx, loc_idx, kept_sk, w_sk, capacity, aux_loss, metadata)


def topk_gating(logits, k: int = 2, capacity_factor: float = 1.0, min_capacity: int = 4,
                train: bool = True, rng=None, noise_std: float = 0.0,
                normalize_weights: bool = True, drop_tokens: bool = True) -> GateOutput:
    """logits [S, E] -> GateOutput. top1/top2 are k=1/2 (reference dispatch
    table moe/sharded_moe.py:587-678 calls into the same machinery).
    Densifies ``topk_gating_compact`` into the [S, E, C] einsum contract."""
    import jax
    import jax.numpy as jnp

    ca = topk_gating_compact(logits, k=k, capacity_factor=capacity_factor,
                             min_capacity=min_capacity, train=train, rng=rng,
                             noise_std=noise_std,
                             normalize_weights=normalize_weights,
                             drop_tokens=drop_tokens)
    S, E = logits.shape
    combine = jnp.zeros((S, E, ca.capacity), jnp.float32)
    for j in range(k):
        m = jax.nn.one_hot(ca.eidx[:, j], E, dtype=jnp.float32) \
            * ca.kept[:, j, None].astype(jnp.float32)
        loc_oh = jax.nn.one_hot(ca.loc[:, j], ca.capacity, dtype=jnp.float32)
        combine = combine + ca.weights[:, j, None, None] * m[:, :, None] * loc_oh[:, None, :]
    dispatch = combine > 0
    return GateOutput(combine, dispatch, ca.aux_loss, ca.metadata)


def top1_gating(logits, **kw) -> GateOutput:
    return topk_gating(logits, k=1, **kw)


def top2_gating(logits, **kw) -> GateOutput:
    return topk_gating(logits, k=2, **kw)
