"""MoE gating: top-1 / top-2 / top-k with capacity and aux losses.

Capability parity with the reference's ``moe/sharded_moe.py`` (top1gating
:183, top2gating :290, topkgating :374 — itself the GShard formulation):
softmax gate over experts, iterative top-k selection, per-expert capacity
``ceil(k·S/E · capacity_factor)`` with overflow drop, load-balancing aux
loss ``E · Σ_e mean(gates_e)·mean(mask_e)``, optional gate-noise for
exploration, and the (combine_weights, dispatch_mask) einsum-dispatch
contract.

All shapes are static: [S, E] in, ([S, E, C], [S, E, C] bool, aux) out —
XLA-friendly (no dynamic token routing; drops are masked, not ragged).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple


class GateOutput(NamedTuple):
    combine_weights: "jax.Array"   # [S, E, C] f32
    dispatch_mask: "jax.Array"     # [S, E, C] bool
    aux_loss: "jax.Array"          # scalar
    metadata: dict                 # expert_counts, dropped fraction (traced)


def compute_capacity(num_tokens: int, num_experts: int, k: int, capacity_factor: float,
                     min_capacity: int = 4) -> int:
    cap = int(-(-num_tokens * k * capacity_factor // num_experts))
    return max(cap, min_capacity)


def topk_gating(logits, k: int = 2, capacity_factor: float = 1.0, min_capacity: int = 4,
                train: bool = True, rng=None, noise_std: float = 0.0,
                normalize_weights: bool = True, drop_tokens: bool = True) -> GateOutput:
    """logits [S, E] -> GateOutput. top1/top2 are k=1/2 (reference dispatch
    table moe/sharded_moe.py:587-678 calls into the same machinery)."""
    import jax
    import jax.numpy as jnp

    S, E = logits.shape
    logits = logits.astype(jnp.float32)
    if train and noise_std > 0.0 and rng is not None:
        logits = logits + noise_std * jax.random.normal(rng, logits.shape, jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)

    capacity = compute_capacity(S, E, k, capacity_factor, min_capacity) if drop_tokens else S

    masks = []
    masked_logits = logits
    for _ in range(k):
        idx = jnp.argmax(masked_logits, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        masks.append(m)
        masked_logits = jnp.where(m > 0, -jnp.inf, masked_logits)

    # Aux load-balancing loss on the first choice (reference l_aux):
    me = gates.mean(axis=0)                  # mean gate prob per expert
    ce = masks[0].mean(axis=0)               # fraction of tokens routed (top-1)
    aux_loss = E * jnp.sum(me * ce)

    # Position of each token within its expert's buffer, priority: choice
    # order first (all 1st choices beat 2nd choices), token order second.
    locations = []
    running = jnp.zeros((E,), jnp.float32)
    kept_masks = []
    for m in masks:
        loc = jnp.cumsum(m, axis=0) - m + running[None, :]
        running = running + m.sum(axis=0)
        if drop_tokens:
            m = m * (loc < capacity)
        kept_masks.append(m)
        locations.append(loc)

    gate_weights = []
    for m in kept_masks:
        gate_weights.append(jnp.sum(gates * m, axis=-1))  # [S]
    if normalize_weights and k > 1:
        denom = sum(gate_weights)
        denom = jnp.maximum(denom, 1e-9)
        gate_weights = [g / denom for g in gate_weights]

    combine = jnp.zeros((S, E, capacity), jnp.float32)
    for m, loc, gw in zip(kept_masks, locations, gate_weights):
        loc_idx = (loc * m).sum(axis=-1).astype(jnp.int32)        # [S]
        loc_oh = jax.nn.one_hot(loc_idx, capacity, dtype=jnp.float32)  # [S, C]
        combine = combine + gw[:, None, None] * m[:, :, None] * loc_oh[:, None, :]
    dispatch = combine > 0

    expert_counts = sum(kept_masks).sum(axis=0)
    kept = sum(m.sum() for m in kept_masks)
    total = sum(m.sum() for m in masks)
    metadata = {"expert_counts": expert_counts, "drop_fraction": 1.0 - kept / jnp.maximum(total, 1.0),
                "capacity": capacity}
    return GateOutput(combine, dispatch, aux_loss, metadata)


def top1_gating(logits, **kw) -> GateOutput:
    return topk_gating(logits, k=1, **kw)


def top2_gating(logits, **kw) -> GateOutput:
    return topk_gating(logits, k=2, **kw)
