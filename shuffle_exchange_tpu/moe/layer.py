"""Expert-parallel MoE layer.

Capability parity with the reference MoE stack (SURVEY.md §2.6 EP row):
``MoE`` wrapper (``moe/layer.py:17``), einsum dispatch → all-to-all over the
expert group → local expert FFNs → return all-to-all → combine
(``moe/sharded_moe.py:587-678``), EP×DP group construction
(``utils/groups.py:240``), residual MoE (``layer.py:105-131``), expert
param identification for the optimizer (``moe/utils.py:72``).

TPU-native shape: expert weights are stacked on a leading E dim sharded
over the mesh "expert" axis; dispatched activations get a
``with_sharding_constraint`` putting the expert dim on the same axis, and
XLA lowers the resharding into exactly the all-to-all pair the reference
issues by hand — scheduled/overlapped by the compiler (SURVEY §2.13
moe_gemm → the per-expert matmul is a single batched einsum on the MXU).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

from .gating import topk_gating


def init_expert_mlp(rng, n_experts: int, d_model: int, d_ff: int, activation: str = "swiglu",
                    bias: bool = False):
    """Stacked expert FFN weights: leading dim E (shard over "expert").

    ``bias=True`` adds per-expert b_up/b_down (+ b_gate for swiglu) leaves —
    the classic Megatron/DeepSpeed-MoE expert layout (reference
    module_inject/containers/megatron_gpt_moe.py imports biased experts)."""
    import jax
    import jax.numpy as jnp

    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    params = {
        "w_up": jax.random.normal(k2, (n_experts, d_model, d_ff), jnp.float32) * scale_in,
        "w_down": jax.random.normal(k3, (n_experts, d_ff, d_model), jnp.float32) * scale_out,
    }
    if activation == "swiglu":
        params["w_gate"] = jax.random.normal(k1, (n_experts, d_model, d_ff), jnp.float32) * scale_in
    if bias:
        params["b_up"] = jnp.zeros((n_experts, d_ff), jnp.float32)
        params["b_down"] = jnp.zeros((n_experts, d_model), jnp.float32)
        if activation == "swiglu":
            params["b_gate"] = jnp.zeros((n_experts, d_ff), jnp.float32)
    return params


def expert_partition_specs(params):
    from jax.sharding import PartitionSpec as P

    def spec(k):
        if k in ("w_gate", "w_up"):
            return P("expert", None, "tensor")
        if k in ("b_gate", "b_up"):
            return P("expert", "tensor")
        if k == "b_down":
            return P("expert", None)
        return P("expert", "tensor", None)

    return {k: spec(k) for k in params}


def _dense_w(w, dtype):
    """Expert weight -> dense compute form. int8/fp8 STORAGE leaves
    (``QuantizedMatrix``, inference quantized serving) dequantize HERE,
    explicitly: XLA fuses the convert into the consuming einsum operand,
    so expert weights cross HBM at quantized width and convert in
    registers — the streamed-weight decode contract. (``.astype`` on a
    QuantizedMatrix materializes identically; the explicit branch keeps
    the contract visible at the use site.)"""
    from ..ops.quant_matmul import QuantizedMatrix

    if isinstance(w, QuantizedMatrix):
        return w.dequantize().astype(dtype)
    return w.astype(dtype)


def expert_mlp(params, x, activation: str = "swiglu"):
    """x [E, C', M] -> [E, C', M]: per-expert FFN as one batched einsum.
    Optional per-expert biases (b_gate/b_up/b_down) add as [E, 1, F]
    broadcasts — the Megatron biased-expert layout. Expert weights may be
    int8/fp8 ``QuantizedMatrix`` leaves (see :func:`_dense_w`)."""
    import jax
    import jax.numpy as jnp

    def b(key, t):
        return t + params[key].astype(t.dtype)[:, None, :] if key in params else t

    up = b("b_up", jnp.einsum("ecm,emf->ecf", x, _dense_w(params["w_up"], x.dtype)))
    if activation == "swiglu":
        gate = b("b_gate", jnp.einsum("ecm,emf->ecf", x, _dense_w(params["w_gate"], x.dtype)))
        h = jax.nn.silu(gate) * up
    else:
        from ..models.transformer import activation_fn

        h = activation_fn(activation)(up)
    return b("b_down", jnp.einsum("ecf,efm->ecm", h, _dense_w(params["w_down"], x.dtype)))


def _gather_expert_sharded(params, expert_axis: str = "expert"):
    """GSPMD on jax 0.4.x mis-partitions ``lax.ragged_dot`` when the RHS
    is sharded over the group (expert) dim — wrong numerics, not just a
    slow program (observed on the 8-device CPU mesh: max err ~2.4 vs the
    replicated reference). Under a live expert axis, pin the stacked
    expert leaves to replicated inside the trace so XLA inserts an
    explicit all-gather before the grouped matmuls: weights stay
    expert-sharded at rest, the ragged math runs on the gathered copy."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    try:
        from ..parallel.mesh import (constraint_mesh, get_topology,
                                     topology_is_initialized)

        if not topology_is_initialized():
            return params
        mesh = get_topology().mesh
        if mesh.shape.get(expert_axis, 1) == 1:
            return params
        rep = NamedSharding(constraint_mesh(mesh), P())
        # tree.map (not a dict comprehension) so QuantizedMatrix expert
        # leaves pin BOTH children (q + scales) — a constraint on the
        # wrapper node would be structure-mismatched, and skipping it
        # would re-open the ragged_dot mispartition this gather fixes
        return jax.tree.map(
            lambda v: jax.lax.with_sharding_constraint(v, rep), params)
    except Exception:
        return params


def expert_mlp_ragged(params, xs, topk_idx, topk_w, activation: str = "swiglu"):
    """Dropless grouped-GEMM experts (reference cutlass moe_gemm /
    megablocks, SURVEY §2.13): tokens sort by expert and one grouped matmul
    per projection (``ops/grouped_gemm.py``: Pallas megablox ``gmm`` on
    TPU, ``lax.ragged_dot`` elsewhere) — no capacity padding slots, no
    dropped tokens, ragged group sizes straight onto the MXU.

    xs [S, M]; topk_idx [S, k] int32; topk_w [S, k] f32 -> [S, M].
    """
    import jax
    import jax.numpy as jnp

    params = _gather_expert_sharded(params)
    S, M = xs.shape
    k = topk_idx.shape[1]
    E = params["w_up"].shape[0]
    flat_e = topk_idx.reshape(-1)                        # [S*k]
    order = jnp.argsort(flat_e, stable=True)
    token_of = order // k
    xsort = jnp.take(xs, token_of, axis=0)               # [S*k, M]
    group_sizes = jnp.bincount(flat_e, length=E).astype(jnp.int32)

    from ..ops.grouped_gemm import grouped_matmul
    from ..ops.quant_matmul import QuantizedMatrix

    dtype = xs.dtype
    e_sorted = jnp.take(flat_e, order)                   # [S*k] expert per row

    def b(key, t):
        # grouped-GEMM bias epilogue: gather each row's expert bias
        if key not in params:
            return t
        return t + jnp.take(params[key].astype(dtype), e_sorted, axis=0)

    def w(key):
        # int8/fp8 QuantizedMatrix expert stacks pass through UNCAST:
        # grouped_matmul owns the dequant policy (fused into ragged_dot's
        # operand on the fallback path; materialized once for the
        # megablox kernel) — an .astype here would densify at the call
        # site and forfeit the streamed-weight HBM win
        wt = params[key]
        return wt if isinstance(wt, QuantizedMatrix) else wt.astype(dtype)

    up = b("b_up", grouped_matmul(xsort, w("w_up"), group_sizes))
    if activation == "swiglu":
        gate = b("b_gate", grouped_matmul(xsort, w("w_gate"), group_sizes))
        h = jax.nn.silu(gate) * up
    else:
        from ..models.transformer import activation_fn

        h = activation_fn(activation)(up)
    out_sorted = b("b_down", grouped_matmul(h, w("w_down"), group_sizes))
    out_flat = jnp.zeros_like(out_sorted).at[order].set(out_sorted)   # unsort
    return (out_flat.reshape(S, k, M) * topk_w[..., None].astype(dtype)).sum(axis=1)


class MoEResult(NamedTuple):
    output: "jax.Array"
    aux_loss: "jax.Array"
    metadata: dict


def resolve_moe_impl(impl: str, ep_size: int, scanned: bool = False) -> str:
    """Resolve ``impl="auto"`` to a concrete dispatch path.

    - an expert axis > 1 -> "capacity" (the EP path; XLA inserts the
      all-to-all pair around the sharded dispatch);
    - under a scanned layer stack -> "capacity" even without an expert
      axis: the Pallas megablox gmm ran the bench step ~4x slower inside
      a ``lax.scan`` over stacked layer weights (5.3% vs 23.1% active-param
      MFU on-chip, scripts/bench_moe_impl.py) — the scan context starves
      the grouped kernel; standalone gmm is fine;
    - otherwise -> "ragged" (dropless grouped-GEMM).
    """
    if impl != "auto":
        return impl
    if ep_size > 1 or scanned:
        return "capacity"
    return "ragged"


def moe_layer(gate_w, expert_params, x, k: int = 2, capacity_factor: float = 1.0,
              activation: str = "swiglu", train: bool = True, rng=None,
              noise_std: float = 0.0, min_capacity: int = 4, expert_axis: str = "expert",
              mesh=None, impl: str = "auto", normalize_weights: bool = True,
              scanned: bool = False) -> MoEResult:
    """x [..., M] -> MoEResult. gate_w [M, E].

    impl:
      - "capacity": GShard capacity/drop semantics dispatched BY INDEX
        (scalar slot scatter + row gathers, zero matmul flops); the EP path
        (dispatched tensor sharding-constrained to the expert axis -> XLA
        inserts the all-to-all pair).
      - "capacity_einsum": the dense [S, E, C] one-hot einsum dispatch —
        identical semantics, kept as the parity oracle (the one-hot
        matmuls cost 2·S·E·C·M flops each, ~4x the expert compute at
        bench shapes — round-5 on-chip profile).
      - "ragged": dropless grouped-GEMM (``expert_mlp_ragged``) — no
        capacity padding FLOPs, no drops; the single-device/data-parallel
        path (reference cutlass moe_gemm). Perf note (v5e, 2026-07, both
        measured on-chip): under a ``lax.scan`` over stacked layer weights
        the Pallas megablox gmm ran the bench step 2.4x SLOWER than the
        capacity einsums (5.3% vs 12.5% active-param MFU) — measure before
        picking ragged for a scanned stack; standalone gmm is fine.
      - "auto": capacity when the mesh has an expert axis > 1 OR the layer
        runs under a scanned stack (``scanned=True`` — the model's
        ``stack_apply`` passes it; megablox gmm measured ~4x slower there,
        see ``resolve_moe_impl``); ragged otherwise.
    """
    import jax
    import jax.numpy as jnp

    if impl not in ("auto", "capacity", "capacity_einsum", "ragged"):
        # validate BEFORE the dispatch chain: an unrecognized string (e.g. a
        # typo like "einsum" or "index") would otherwise silently fall
        # through to the index-dispatch capacity path (ADVICE r5 #1)
        raise ValueError(
            f"moe impl must be one of 'auto', 'capacity', 'capacity_einsum', "
            f"'ragged'; got {impl!r}")

    orig_shape = x.shape
    M = orig_shape[-1]
    xs = x.reshape(-1, M)
    S = xs.shape[0]
    logits = (xs.astype(jnp.float32)) @ gate_w.astype(jnp.float32)   # [S, E]

    if impl == "auto":
        # the explicit mesh argument wins; fall back to the global topology
        if mesh is not None:
            ep = dict(getattr(mesh, "shape", {})).get(expert_axis, 1)
        else:
            from ..parallel.mesh import get_topology, topology_is_initialized

            ep = get_topology().size(expert_axis) if topology_is_initialized() else 1
        impl = resolve_moe_impl("auto", ep, scanned)
        from ..utils.logging import warning_once

        if impl == "ragged":
            warning_once(
                "moe_impl=auto resolved to the dropless ragged grouped-GEMM "
                "path (no expert axis > 1, unscanned): capacity_factor/"
                "min_capacity/drop semantics do not apply — set "
                "moe_impl='capacity' to keep GShard capacity/drop behavior")
        elif ep <= 1 and scanned:
            warning_once(
                "moe_impl=auto resolved to the capacity (index-dispatch) "
                "path: this layer runs under a scanned stack, where the "
                "ragged megablox grouped-GEMM measured ~4x SLOWER on-chip "
                "(5.3% vs 23.1% active-param MFU, scripts/bench_moe_impl.py)."
                " Capacity/drop semantics apply (capacity_factor/"
                "min_capacity; overflow tokens drop) — set "
                "moe_impl='ragged' to force dropless routing despite the "
                "perf cliff")
    if impl == "ragged":
        from .gating import topk_select

        idx, w, aux, _ = topk_select(logits, k, normalize_weights=normalize_weights,
                                     train=train, rng=rng, noise_std=noise_std)
        out = expert_mlp_ragged(expert_params, xs, idx, w, activation)
        counts = jnp.bincount(idx.reshape(-1), length=gate_w.shape[1])
        return MoEResult(out.reshape(orig_shape), aux,
                         {"expert_counts": counts, "drop_fraction": jnp.zeros(()),
                          "capacity": S})

    if impl == "capacity_einsum":
        # the GShard dense-mask contract, kept as the parity oracle: the
        # one-hot dispatch/combine einsums are real matmuls costing
        # 2·S·E·C·M flops EACH — ~4x the expert compute at bench shapes
        gate = topk_gating(logits, k=k, capacity_factor=capacity_factor, train=train,
                           rng=rng, noise_std=noise_std, min_capacity=min_capacity,
                           normalize_weights=normalize_weights)

        dispatched = jnp.einsum("sec,sm->ecm", gate.dispatch_mask.astype(xs.dtype), xs)
        dispatched = _constrain_expert(dispatched, expert_axis, mesh)
        expert_out = expert_mlp(expert_params, dispatched, activation)
        expert_out = _constrain_expert(expert_out, expert_axis, mesh)
        combined = jnp.einsum("sec,ecm->sm", gate.combine_weights.astype(xs.dtype), expert_out)
        return MoEResult(combined.reshape(orig_shape), gate.aux_loss, gate.metadata)

    # "capacity": same assignment/drop semantics in index form — dispatch is
    # one scalar scatter (slot -> token id) plus a row gather, combine is a
    # row gather weighted by the compact gate weights. Zero matmul flops
    # (round 5; the reference's own v2 engine dispatches by index the same
    # way, inference/v2/ragged_ops/moe_scatter). EP evidence: parity +
    # training on the 8-device CPU mesh (test_moe_expert_parallel_*,
    # dryrun config 3) and 1.84x on one real chip; how XLA lowers the
    # cross-shard gather on a real EP pod (a2a vs all-gather of xs) is
    # unmeasured until multi-chip hardware is available — if it regresses
    # there, set moe_impl="capacity_einsum" to restore the proven wire.
    from .gating import topk_gating_compact

    ca = topk_gating_compact(logits, k=k, capacity_factor=capacity_factor,
                             train=train, rng=rng, noise_std=noise_std,
                             min_capacity=min_capacity,
                             normalize_weights=normalize_weights)
    E = gate_w.shape[1]
    C = ca.capacity
    slot = ca.eidx * C + ca.loc                              # [S, k]
    trash = E * C                                            # dropped -> trash slot
    tgt = jnp.where(ca.kept, slot, trash)
    token_ids = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[:, None], tgt.shape)
    # kept slots are unique by construction (cumsum buffer positions), so
    # the scatter never collides; empty slots keep sentinel S -> zero row
    inv = jnp.full((E * C + 1,), S, jnp.int32).at[tgt.reshape(-1)].set(
        token_ids.reshape(-1), mode="drop")[:E * C]
    xs_pad = jnp.concatenate([xs, jnp.zeros((1, M), xs.dtype)], axis=0)
    dispatched = xs_pad[inv].reshape(E, C, M)
    dispatched = _constrain_expert(dispatched, expert_axis, mesh)
    expert_out = expert_mlp(expert_params, dispatched, activation)
    expert_out = _constrain_expert(expert_out, expert_axis, mesh)
    eo = expert_out.reshape(E * C, M)
    gath = eo[jnp.clip(slot, 0, E * C - 1)]                  # [S, k, M]
    # ca.weights is already zero for dropped choices (the one drop-zeroing
    # site, topk_gating_compact), so the clipped gather row is harmless
    w = ca.weights.astype(xs.dtype)
    combined = (w[..., None] * gath).sum(axis=1)
    return MoEResult(combined.reshape(orig_shape), ca.aux_loss, ca.metadata)


def _constrain_expert(t, expert_axis, mesh):
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax.sharding import NamedSharding

        if mesh is None:
            from ..parallel.mesh import topology_is_initialized, get_topology

            if not topology_is_initialized():
                return t
            mesh = get_topology().mesh
        if mesh.shape.get(expert_axis, 1) == 1:
            return t
        from ..parallel.mesh import constraint_mesh

        return jax.lax.with_sharding_constraint(
            t, NamedSharding(constraint_mesh(mesh), P(expert_axis, None, None)))
    except Exception:
        return t


def residual_moe(gate_w, expert_params, dense_params, coef_w, x, activation: str = "swiglu",
                 **moe_kwargs) -> MoEResult:
    """Residual MoE (reference moe/layer.py:105-131): blend a dense MLP path
    with the MoE path via a learned 2-way coefficient."""
    import jax
    import jax.numpy as jnp

    res = moe_layer(gate_w, expert_params, x, activation=activation, **moe_kwargs)
    dense = expert_mlp({k: v[None] for k, v in dense_params.items()},
                       x.reshape(1, -1, x.shape[-1]), activation).reshape(x.shape)
    coef = jax.nn.softmax((x.astype(jnp.float32) @ coef_w.astype(jnp.float32)), axis=-1)
    out = dense * coef[..., 0:1].astype(x.dtype) + res.output * coef[..., 1:2].astype(x.dtype)
    return MoEResult(out, res.aux_loss, res.metadata)
