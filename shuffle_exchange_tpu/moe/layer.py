"""Expert-parallel MoE layer.

Capability parity with the reference MoE stack (SURVEY.md §2.6 EP row):
``MoE`` wrapper (``moe/layer.py:17``), einsum dispatch → all-to-all over the
expert group → local expert FFNs → return all-to-all → combine
(``moe/sharded_moe.py:587-678``), EP×DP group construction
(``utils/groups.py:240``), residual MoE (``layer.py:105-131``), expert
param identification for the optimizer (``moe/utils.py:72``).

TPU-native shape: expert weights are stacked on a leading E dim sharded
over the mesh "expert" axis; dispatched activations get a
``with_sharding_constraint`` putting the expert dim on the same axis, and
XLA lowers the resharding into exactly the all-to-all pair the reference
issues by hand — scheduled/overlapped by the compiler (SURVEY §2.13
moe_gemm → the per-expert matmul is a single batched einsum on the MXU).
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional

from .gating import GateOutput, topk_gating


def init_expert_mlp(rng, n_experts: int, d_model: int, d_ff: int, activation: str = "swiglu"):
    """Stacked expert FFN weights: leading dim E (shard over "expert")."""
    import jax
    import jax.numpy as jnp

    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    params = {
        "w_up": jax.random.normal(k2, (n_experts, d_model, d_ff), jnp.float32) * scale_in,
        "w_down": jax.random.normal(k3, (n_experts, d_ff, d_model), jnp.float32) * scale_out,
    }
    if activation == "swiglu":
        params["w_gate"] = jax.random.normal(k1, (n_experts, d_model, d_ff), jnp.float32) * scale_in
    return params


def expert_partition_specs(params):
    from jax.sharding import PartitionSpec as P

    return {k: P("expert", None, "tensor") if k in ("w_gate", "w_up") else P("expert", "tensor", None)
            for k in params}


def expert_mlp(params, x, activation: str = "swiglu"):
    """x [E, C', M] -> [E, C', M]: per-expert FFN as one batched einsum."""
    import jax
    import jax.numpy as jnp

    up = jnp.einsum("ecm,emf->ecf", x, params["w_up"].astype(x.dtype))
    if activation == "swiglu":
        gate = jnp.einsum("ecm,emf->ecf", x, params["w_gate"].astype(x.dtype))
        h = jax.nn.silu(gate) * up
    else:
        from ..models.transformer import activation_fn

        h = activation_fn(activation)(up)
    return jnp.einsum("ecf,efm->ecm", h, params["w_down"].astype(x.dtype))


class MoEResult(NamedTuple):
    output: "jax.Array"
    aux_loss: "jax.Array"
    metadata: dict


def moe_layer(gate_w, expert_params, x, k: int = 2, capacity_factor: float = 1.0,
              activation: str = "swiglu", train: bool = True, rng=None,
              noise_std: float = 0.0, min_capacity: int = 4, expert_axis: str = "expert",
              mesh=None) -> MoEResult:
    """x [..., M] -> MoEResult. gate_w [M, E].

    Under jit with a mesh in context, the dispatched [E, C, M] tensor is
    sharding-constrained to the expert axis (EP all-to-all inserted by XLA).
    """
    import jax
    import jax.numpy as jnp

    orig_shape = x.shape
    M = orig_shape[-1]
    xs = x.reshape(-1, M)
    S = xs.shape[0]
    logits = (xs.astype(jnp.float32)) @ gate_w.astype(jnp.float32)   # [S, E]
    gate = topk_gating(logits, k=k, capacity_factor=capacity_factor, train=train,
                       rng=rng, noise_std=noise_std, min_capacity=min_capacity)

    dispatched = jnp.einsum("sec,sm->ecm", gate.dispatch_mask.astype(xs.dtype), xs)
    dispatched = _constrain_expert(dispatched, expert_axis, mesh)
    expert_out = expert_mlp(expert_params, dispatched, activation)
    expert_out = _constrain_expert(expert_out, expert_axis, mesh)
    combined = jnp.einsum("sec,ecm->sm", gate.combine_weights.astype(xs.dtype), expert_out)
    return MoEResult(combined.reshape(orig_shape), gate.aux_loss, gate.metadata)


def _constrain_expert(t, expert_axis, mesh):
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax.sharding import NamedSharding

        if mesh is None:
            from ..parallel.mesh import topology_is_initialized, get_topology

            if not topology_is_initialized():
                return t
            mesh = get_topology().mesh
        if mesh.shape.get(expert_axis, 1) == 1:
            return t
        return jax.lax.with_sharding_constraint(
            t, NamedSharding(mesh, P(expert_axis, None, None)))
    except Exception:
        return t


def residual_moe(gate_w, expert_params, dense_params, coef_w, x, activation: str = "swiglu",
                 **moe_kwargs) -> MoEResult:
    """Residual MoE (reference moe/layer.py:105-131): blend a dense MLP path
    with the MoE path via a learned 2-way coefficient."""
    import jax
    import jax.numpy as jnp

    res = moe_layer(gate_w, expert_params, x, activation=activation, **moe_kwargs)
    dense = expert_mlp({k: v[None] for k, v in dense_params.items()},
                       x.reshape(1, -1, x.shape[-1]), activation).reshape(x.shape)
    coef = jax.nn.softmax((x.astype(jnp.float32) @ coef_w.astype(jnp.float32)), axis=-1)
    out = dense * coef[..., 0:1].astype(x.dtype) + res.output * coef[..., 1:2].astype(x.dtype)
    return MoEResult(out, res.aux_loss, res.metadata)
