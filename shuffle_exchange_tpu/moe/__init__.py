from .gating import GateOutput, compute_capacity, top1_gating, top2_gating, topk_gating
from .layer import (MoEResult, expert_mlp, init_expert_mlp, moe_layer,
                    residual_moe, resolve_moe_impl)
