"""Interprocedural lock-acquisition-graph pass: SXT009 / SXT010.

The threaded serving fleet (PRs 7/11/12) carries an explicit lock
discipline — ``@locked_by`` registrations, ``@requires_lock`` helpers,
and the declared global rank table ``utils.invariants.LOCK_ORDER``
(router -> replica-scheduler -> channel -> monitor). This pass consumes
that metadata and PROVES the ordering statically:

- **SXT009 — lock-order cycle.** Every ``with self.<lock>`` (and
  resolvable foreign ``with <obj>.<lock>``) acquisition is harvested
  with the set of locks already held at that point, both syntactically
  and through resolvable call edges (same-module calls, plus
  ``self.<attr>`` receivers whose class is recorded by a
  ``self.<attr> = ClassName(...)`` constructor assignment — the same
  conservative dataflow SXT002's derivation machinery uses). Two locks
  acquired in inconsistent order across ANY two paths form a cycle in
  the resulting graph; each participating acquisition site is flagged.
  Incident: the PR 11 router/replica deadlock (``submit`` held the
  router lock blocked on a hung replica's lock; the failover that would
  have released the replica needed the router lock to fence it).

- **SXT010 — blocking call under a ``@locked_by`` lock.** While a lock
  registered by ``@locked_by`` is held: (a) acquiring — directly or
  through a resolvable call — a lock whose ``LOCK_ORDER`` rank is not
  strictly greater than the held lock's (or a lock with no declared
  rank at all) and (b) direct ``join``/``wait``/``quiesce``/``tick``/
  ``sleep``/``acquire``-shaped calls (``X.wait()`` on the lock
  currently held is the sanctioned condition-variable pattern and is
  exempt) are flagged. A third shape guards the PR 7 reentrant-SIGTERM
  fix: a function installed via ``signal.signal`` in the same module
  must not acquire ANY known lock (handlers run mid-bytecode on the
  main thread — the reason ``request_drain`` only records).

Everything here is best-effort syntactic resolution, same philosophy as
the rest of sxt-check: unresolvable receivers are SKIPPED (conservative
misses, never false claims about code it cannot see), and nested
function/lambda bodies are excluded (they run later, under their own
discipline). The runtime sanitizer (``testing/sanitizer.py``) covers
the dynamic remainder.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..utils.invariants import LOCK_ORDER
from .rules import Violation, _last_attr
from .scopes import ImportTable, build_import_table

#: direct call names treated as blocking under a @locked_by lock
BLOCKING_CALLS = frozenset({
    "join", "wait", "wait_for", "quiesce", "tick", "sleep", "acquire",
})

_LOCK_CTORS = ("threading.Lock", "threading.RLock", "threading.Condition",
               "threading.Semaphore", "threading.BoundedSemaphore")

_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


# ---------------------------------------------------------------------------
# harvested per-file facts
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class MethodFacts:
    """One function/method's lock-relevant behavior."""
    key: Tuple[str, str]                 # (class name or "", func name)
    path: str
    requires: List[str]                  # lock ids held at entry
    #: (lock_id, line, held-at-that-point) — syntactic `with` acquisitions
    acquires: List[Tuple[str, int, Tuple[str, ...]]]
    #: (callee key, line, held-at-that-point) — resolvable call edges
    calls: List[Tuple[Tuple[str, str], int, Tuple[str, ...]]]
    #: (display name, line, held, wait_target lock id or None)
    blocking: List[Tuple[str, int, Tuple[str, ...], Optional[str]]]


@dataclasses.dataclass
class ClassFacts:
    name: str
    path: str
    lock_attrs: Set[str]                 # attr names that hold locks
    locked_by: Set[str]                  # the @locked_by-registered subset
    attr_types: Dict[str, str]           # self.<attr> -> class simple name


@dataclasses.dataclass
class ModuleFacts:
    path: str
    module_path: str
    classes: Dict[str, ClassFacts]
    methods: Dict[Tuple[str, str], MethodFacts]
    module_locks: Set[str]               # module-level lock names
    #: same-module functions installed as signal handlers, with the
    #: signal.signal call line
    signal_handlers: List[Tuple[str, int]]
    #: module-level ``SXT_LOCK_ORDER = {"Class.attr": rank}`` declaration
    #: — the extension point for lock hierarchies OUTSIDE the serving
    #: fleet's (utils.invariants.LOCK_ORDER, which wins on conflict)
    declared_ranks: Dict[str, int] = dataclasses.field(default_factory=dict)


def _is_lock_ctor(node: ast.AST, imports: ImportTable) -> bool:
    """True when ``node`` contains a threading lock constructor call
    (possibly wrapped, e.g. ``sanitizer.wrap(threading.RLock(), ...)``)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = imports.canonical(sub.func)
            if name in _LOCK_CTORS:
                return True
            # testing.sanitizer construction helpers build (wrapped) locks
            if _last_attr(sub.func) in ("wrap", "make_condition"):
                return True
    return False


def _self_attr_of(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Harvester:
    """One pass over one module collecting ClassFacts/MethodFacts."""

    def __init__(self, path: str, tree: ast.Module, module_path: str):
        self.path = path
        self.tree = tree
        self.module_path = module_path
        self.imports = build_import_table(tree, module_path)
        self.out = ModuleFacts(path, module_path, {}, {}, set(), [])

    # -- prepass: classes, their lock attrs, attr types ----------------

    def run(self) -> ModuleFacts:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value,
                                                              self.imports):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.out.module_locks.add(t.id)
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "SXT_LOCK_ORDER"
                    and isinstance(node.value, ast.Dict)):
                for k, v in zip(node.value.keys, node.value.values):
                    if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, int)):
                        self.out.declared_ranks[k.value] = v.value
            if isinstance(node, ast.ClassDef):
                self._harvest_class_decl(node)
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                cf = self.out.classes[node.name]
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._harvest_function(item, cf)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._harvest_function(node, None)
        self._harvest_signal_handlers()
        return self.out

    def _harvest_class_decl(self, node: ast.ClassDef) -> None:
        locked: Set[str] = set()
        for dec in node.decorator_list:
            if (isinstance(dec, ast.Call) and _last_attr(dec.func) == "locked_by"
                    and dec.args and isinstance(dec.args[0], ast.Constant)
                    and isinstance(dec.args[0].value, str)):
                locked.add(dec.args[0].value)
        cf = ClassFacts(node.name, self.path, set(locked), locked, {})
        # lock attrs + attr types from every `self.X = ...` in the class
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                continue
            attr = _self_attr_of(sub.targets[0])
            if attr is None:
                continue
            if _is_lock_ctor(sub.value, self.imports):
                cf.lock_attrs.add(attr)
            if isinstance(sub.value, ast.Call):
                cname = self.imports.canonical(sub.value.func)
                simple = (cname.rsplit(".", 1)[-1] if cname
                          else _last_attr(sub.value.func))
                if simple and simple[:1].isupper():
                    cf.attr_types[attr] = simple
        self.out.classes[node.name] = cf

    # -- per-function event walk ---------------------------------------

    def _harvest_function(self, fn: ast.FunctionDef,
                          cf: Optional[ClassFacts]) -> None:
        cls = cf.name if cf is not None else ""
        requires: List[str] = []
        for dec in fn.decorator_list:
            if (isinstance(dec, ast.Call)
                    and _last_attr(dec.func) == "requires_lock"):
                for a in dec.args:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        lid = self._resolve_self_lock(a.value, cf)
                        if lid:
                            requires.append(lid)
        mf = MethodFacts((cls, fn.name), self.path, requires, [], [], [])
        local_types: Dict[str, str] = {}
        self._walk(fn.body, list(requires), cf, local_types, mf)
        self.out.methods[(cls, fn.name)] = mf

    def _resolve_self_lock(self, attr: str,
                           cf: Optional[ClassFacts]) -> Optional[str]:
        if cf is not None and (attr in cf.lock_attrs
                               or f"{cf.name}.{attr}" in LOCK_ORDER
                               or f"{cf.name}.{attr}" in self.out.declared_ranks):
            return f"{cf.name}.{attr}"
        return None

    def _resolve_lock_expr(self, node: ast.AST, cf: Optional[ClassFacts],
                           local_types: Dict[str, str]) -> Optional[str]:
        """Lock id of a `with` context expression, best-effort."""
        attr = _self_attr_of(node)
        if attr is not None:
            return self._resolve_self_lock(attr, cf)
        if isinstance(node, ast.Name):
            if node.id in self.out.module_locks:
                return f"{self.module_path}:{node.id}"
            return None
        if isinstance(node, ast.Attribute):
            # typed receiver first: rep.lock where rep's class is known
            base = node.value
            bcls = None
            if isinstance(base, ast.Name):
                bcls = local_types.get(base.id)
            else:
                battr = _self_attr_of(base)
                if battr is not None and cf is not None:
                    bcls = cf.attr_types.get(battr)
            if bcls is not None:
                lid = f"{bcls}.{node.attr}"
                own = self.out.classes.get(bcls)
                if own is not None:
                    # same-module class: only attrs known to BE locks
                    return lid if (node.attr in own.lock_attrs
                                   or lid in LOCK_ORDER
                                   or lid in self.out.declared_ranks) else None
                # cross-module class: trust only ranked names (a typed
                # receiver's arbitrary context manager is not a lock)
                if lid in LOCK_ORDER or lid in self.out.declared_ranks:
                    return lid
                return None
            # fall back to a unique attr-name match across the rank table
            # (resolution, not policy: LOCK_ORDER doubles as the registry
            # of cross-class lock attr names)
            table = dict(self.out.declared_ranks)
            table.update(LOCK_ORDER)
            hits = [k for k in table if k.endswith(f".{node.attr}")]
            if len(hits) == 1:
                return hits[0]
        return None

    def _resolve_call(self, call: ast.Call, cf: Optional[ClassFacts],
                      local_types: Dict[str, str]
                      ) -> Optional[Tuple[str, str]]:
        """(class, func) key of a resolvable callee, else None."""
        f = call.func
        if isinstance(f, ast.Name):
            return ("", f.id)
        if isinstance(f, ast.Attribute):
            attr = _self_attr_of(f)
            if attr is not None and cf is not None:
                return (cf.name, f.attr) if attr not in cf.attr_types else None
            base = f.value
            battr = _self_attr_of(base)
            if battr is not None and cf is not None:
                bcls = cf.attr_types.get(battr)
                if bcls is not None:
                    return (bcls, f.attr)
            if isinstance(base, ast.Name):
                bcls = local_types.get(base.id)
                if bcls is not None:
                    return (bcls, f.attr)
        return None

    def _walk(self, stmts: Sequence[ast.stmt], held: List[str],
              cf: Optional[ClassFacts], local_types: Dict[str, str],
              mf: MethodFacts) -> None:
        for st in stmts:
            self._walk_node(st, held, cf, local_types, mf)

    def _walk_node(self, node: ast.AST, held: List[str],
                   cf: Optional[ClassFacts], local_types: Dict[str, str],
                   mf: MethodFacts) -> None:
        if isinstance(node, _NESTED):
            return   # closures run later, under their own discipline
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            cname = self.imports.canonical(node.value.func)
            simple = (cname.rsplit(".", 1)[-1] if cname
                      else _last_attr(node.value.func))
            if simple and simple[:1].isupper():
                local_types[node.targets[0].id] = simple
        if isinstance(node, ast.With):
            pushed: List[str] = []
            for item in node.items:
                # events inside the context expr see the pre-push stack
                self._walk_node(item.context_expr, held, cf, local_types, mf)
                lid = self._resolve_lock_expr(item.context_expr, cf,
                                              local_types)
                if lid is not None:
                    mf.acquires.append((lid, item.context_expr.lineno,
                                        tuple(held)))
                    held.append(lid)
                    pushed.append(lid)
            self._walk(node.body, held, cf, local_types, mf)
            for lid in pushed:
                held.remove(lid)
            return
        if isinstance(node, ast.Call):
            self._record_call(node, held, cf, local_types, mf)
        for child in ast.iter_child_nodes(node):
            self._walk_node(child, held, cf, local_types, mf)

    def _record_call(self, call: ast.Call, held: List[str],
                     cf: Optional[ClassFacts], local_types: Dict[str, str],
                     mf: MethodFacts) -> None:
        last = _last_attr(call.func)
        if (last == "join" and isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, (ast.Constant, ast.JoinedStr))):
            last = None   # "sep".join(...) is a string op, not a thread join
        if last in BLOCKING_CALLS and held:
            target = None
            if isinstance(call.func, ast.Attribute):
                target = self._resolve_lock_expr(call.func.value, cf,
                                                 local_types)
            name = self.imports.canonical(call.func) or last
            mf.blocking.append((name, call.lineno, tuple(held), target))
        key = self._resolve_call(call, cf, local_types)
        if key is not None:
            mf.calls.append((key, call.lineno, tuple(held)))

    # -- signal handlers ------------------------------------------------

    def _harvest_signal_handlers(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self.imports.canonical(node.func)
            if name != "signal.signal" or len(node.args) < 2:
                continue
            h = node.args[1]
            if isinstance(h, ast.Name):
                self.out.signal_handlers.append((h.id, node.lineno))


# ---------------------------------------------------------------------------
# the global pass
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LockGraph:
    modules: List[ModuleFacts]
    #: (held, acquired) -> first witness (path, line)
    edges: Dict[Tuple[str, str], Tuple[str, int]]
    #: lock id -> declared rank (None entries omitted)
    ranks: Dict[str, int]
    #: (module, class, fn) -> transitive acquisition set (computed once)
    summary: Dict[Tuple[str, str, str], Set[str]] = dataclasses.field(
        default_factory=dict)

    def to_json(self) -> dict:
        return {
            "ranks": dict(sorted(self.ranks.items(),
                                 key=lambda kv: (kv[1], kv[0]))),
            "edges": [{"held": a, "acquired": b, "path": p, "line": ln}
                      for (a, b), (p, ln) in sorted(self.edges.items())],
        }


def _summaries(modules: Sequence[ModuleFacts]
               ) -> Dict[Tuple[str, str, str], Set[str]]:
    """Fixed-point transitive acquisition summary per (module, class, fn).

    Call edges resolve within the harvested set: same-module bare
    functions, same-class methods, and cross-module methods of classes
    recorded by constructor-typed receivers (class simple names are
    unique across this package)."""
    # index: class name -> module_path (for cross-module method lookup)
    cls_home: Dict[str, str] = {}
    for m in modules:
        for cname in m.classes:
            cls_home.setdefault(cname, m.module_path)
    by_mod = {m.module_path: m for m in modules}

    def method_of(mod: ModuleFacts, key: Tuple[str, str]
                  ) -> Optional[Tuple[str, Tuple[str, str]]]:
        cls, fn = key
        if (cls, fn) in mod.methods and cls == "":
            return (mod.module_path, key)
        if cls:
            home = cls_home.get(cls)
            if home is not None and (cls, fn) in by_mod[home].methods:
                return (home, (cls, fn))
        return None

    summary: Dict[Tuple[str, str, str], Set[str]] = {}
    for m in modules:
        for key, mf in m.methods.items():
            summary[(m.module_path,) + key] = {lid for lid, _, _
                                               in mf.acquires}
    changed = True
    while changed:
        changed = False
        for m in modules:
            for key, mf in m.methods.items():
                mine = summary[(m.module_path,) + key]
                for ckey, _, _ in mf.calls:
                    resolved = method_of(m, ckey)
                    if resolved is None:
                        continue
                    theirs = summary.get((resolved[0],) + resolved[1], set())
                    add = theirs - mine
                    if add:
                        mine |= add
                        changed = True
    return summary


def build_lock_graph(entries: Sequence[Tuple[str, ast.Module, str]]
                     ) -> LockGraph:
    """Harvest ``(path, tree, module_path)`` entries into the graph."""
    modules = [_Harvester(p, t, mp).run() for p, t, mp in entries]
    summary = _summaries(modules)
    cls_home: Dict[str, str] = {}
    for m in modules:
        for cname in m.classes:
            cls_home.setdefault(cname, m.module_path)

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add_edge(a: str, b: str, path: str, line: int) -> None:
        if a == b:
            return   # re-entrancy / same-id instances: the runtime
        edges.setdefault((a, b), (path, line))   # sanitizer owns those

    for m in modules:
        for key, mf in m.methods.items():
            for lid, line, held in mf.acquires:
                for h in held:
                    add_edge(h, lid, m.path, line)
            for ckey, line, held in mf.calls:
                if not held:
                    continue
                cls, fn = ckey
                home = m.module_path if not cls else cls_home.get(cls)
                if home is None:
                    continue
                theirs = summary.get((home, cls, fn))
                if not theirs:
                    continue
                for h in held:
                    for lid in theirs:
                        if lid != h and lid not in held:
                            add_edge(h, lid, m.path, line)
    ranks: Dict[str, int] = {}
    for m in modules:
        ranks.update(m.declared_ranks)
    ranks.update(LOCK_ORDER)   # the serving hierarchy wins on conflict
    return LockGraph(list(modules), edges, ranks, summary)


def _sccs(nodes: Set[str], adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan SCCs (iterative), deterministic over sorted nodes."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(sorted(adj.get(v0, ()))))]
        index[v0] = low[v0] = counter[0]; counter[0] += 1
        stack.append(v0); on.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]; counter[0] += 1
                    stack.append(w); on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = set()
                while True:
                    w = stack.pop(); on.discard(w); comp.add(w)
                    if w == v:
                        break
                out.append(comp)

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return out


def check_lock_graph(graph: LockGraph) -> Dict[str, List[Violation]]:
    """SXT009 + SXT010 violations, keyed by file path."""
    out: Dict[str, List[Violation]] = {}

    def add(path: str, rule: str, line: int, msg: str) -> None:
        out.setdefault(path, []).append(Violation(rule, path, line, 0, msg))

    # -- SXT009: cycles -------------------------------------------------
    nodes: Set[str] = set()
    adj: Dict[str, Set[str]] = {}
    for (a, b) in graph.edges:
        nodes.add(a); nodes.add(b)
        adj.setdefault(a, set()).add(b)
    for comp in _sccs(nodes, adj):
        if len(comp) < 2:
            continue
        cyc_edges = sorted((a, b) for (a, b) in graph.edges
                           if a in comp and b in comp)
        witness = "; ".join(
            f"{a} -> {b} at {graph.edges[(a, b)][0]}:"
            f"{graph.edges[(a, b)][1]}" for a, b in cyc_edges)
        for a, b in cyc_edges:
            path, line = graph.edges[(a, b)]
            add(path, "SXT009", line,
                f"lock-order cycle: `{b}` is acquired while `{a}` is held "
                f"here, but the locks {sorted(comp)} are also acquired in "
                f"the opposite order on another path ({witness}) — two "
                f"threads interleaving these paths deadlock (the PR 11 "
                f"router/replica incident shape). Pick one order and "
                f"declare it in utils.invariants.LOCK_ORDER")

    # -- SXT010: blocking / rank-inverted acquisition under @locked_by --
    registered: Set[str] = set()
    for m in graph.modules:
        for cf in m.classes.values():
            for a in cf.locked_by:
                registered.add(f"{cf.name}.{a}")

    def rank_of(lid: str) -> Optional[int]:
        return graph.ranks.get(lid)

    def check_acq(path: str, line: int, held: Tuple[str, ...], lid: str,
                  via: str) -> None:
        for h in held:
            if h not in registered or lid == h or lid in held:
                continue
            rh, rl = rank_of(h), rank_of(lid)
            if rl is None:
                add(path, "SXT010", line,
                    f"`{lid}` acquired{via} while holding `{h}` "
                    f"(@locked_by), but `{lid}` has no declared rank in "
                    f"utils.invariants.LOCK_ORDER — an ordering nobody "
                    f"declared is an ordering nobody checks")
            elif rh is None or rl <= rh:
                add(path, "SXT010", line,
                    f"`{lid}` (rank {rl}) acquired{via} while holding "
                    f"`{h}` (rank {rh}): the declared order "
                    f"(utils.invariants.LOCK_ORDER) only permits "
                    f"strictly-increasing ranks — this is the hold-and-"
                    f"wait half of a deadlock")

    summary = graph.summary
    cls_home: Dict[str, str] = {}
    for mm in graph.modules:
        for c in mm.classes:
            cls_home.setdefault(c, mm.module_path)
    for m in graph.modules:
        for key, mf in m.methods.items():
            for lid, line, held in mf.acquires:
                check_acq(m.path, line, held, lid, "")
            for ckey, line, held in mf.calls:
                if not held or not any(h in registered for h in held):
                    continue
                cls, fn = ckey
                home = m.module_path if not cls else cls_home.get(cls)
                if home is None:
                    continue
                theirs = summary.get((home, cls, fn))
                if not theirs:
                    continue
                for lid in sorted(theirs):
                    check_acq(m.path, line, held, lid,
                              f" via {cls + '.' if cls else ''}{fn}()")
            for name, line, held, target in mf.blocking:
                if target is not None and target in held:
                    continue   # cv.wait() on the held lock: sanctioned
                regs = [h for h in held if h in registered]
                if not regs:
                    continue
                add(m.path, "SXT010", line,
                    f"blocking-shaped call `{name}(...)` while holding "
                    f"{regs} (@locked_by): a call that can park forever "
                    f"under a lock is the PR 11 deadlock shape — release "
                    f"the lock first, or fence with bare writes the way "
                    f"fail_over() does")

    # -- signal handlers ------------------------------------------------
    for m in graph.modules:
        for hname, line in m.signal_handlers:
            mf = m.methods.get(("", hname))
            if mf is None:
                continue
            acquired = set(summary.get((m.module_path, "", hname), set()))
            direct = {lid for lid, _, _ in mf.acquires}
            acquired |= direct
            if acquired:
                add(m.path, "SXT010", line,
                    f"signal handler `{hname}` acquires {sorted(acquired)}:"
                    f" a handler runs mid-bytecode on the main thread, "
                    f"where a (reentrant) lock lets it interleave with a "
                    f"half-finished frame underneath — record the request "
                    f"and apply it at a safe point instead (the PR 7 "
                    f"reentrant-SIGTERM fix, serving/lifecycle.py)")
    return out


def analyze_lock_graph(entries: Sequence[Tuple[str, ast.Module, str]]
                       ) -> Tuple[LockGraph, Dict[str, List[Violation]]]:
    graph = build_lock_graph(entries)
    return graph, check_lock_graph(graph)
