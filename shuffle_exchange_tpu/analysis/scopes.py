"""Import and name resolution for sxt-check.

Everything here is best-effort SYNTACTIC resolution: the analyzer never
imports the code it checks (a lint pass must not need a jax backend, and
must run on files that would crash on import). Names are canonicalized
to dotted paths through the file's import table so rules can match
``jax.jit`` / ``jax.experimental.shard_map.shard_map`` /
``...utils.placement.cache_safe_donate_argnums`` regardless of aliasing
(``import jax.numpy as jnp``, ``from x import y as z``, relative
imports).
"""

from __future__ import annotations

import ast
from typing import Dict, Optional


class ImportTable:
    """Maps local names to canonical dotted module paths for one file.

    Relative imports (``from ..utils.placement import x``) resolve
    against ``module_path`` (the file's own dotted module name) when
    known, else degrade to the bare suffix — rules match by suffix, so
    either form works.
    """

    def __init__(self, module_path: str = ""):
        self.module_path = module_path
        self.names: Dict[str, str] = {}

    def _resolve_relative(self, level: int, module: str) -> str:
        if level == 0:
            return module
        parts = self.module_path.split(".") if self.module_path else []
        # "from . import x" in pkg/mod.py: level 1 strips the module name
        base = parts[:-level] if len(parts) >= level else []
        return ".".join(base + ([module] if module else []))

    def add_import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            # "import jax.numpy as jnp" binds jnp -> jax.numpy;
            # "import jax.numpy" binds jax -> jax
            target = alias.name if alias.asname else alias.name.split(".")[0]
            self.names[local] = target

    def add_import_from(self, node: ast.ImportFrom) -> None:
        base = self._resolve_relative(node.level, node.module or "")
        for alias in node.names:
            if alias.name == "*":
                continue
            local = alias.asname or alias.name
            self.names[local] = f"{base}.{alias.name}" if base else alias.name

    def canonical(self, node: ast.AST) -> Optional[str]:
        """Dotted path of a Name/Attribute chain with the root expanded
        through the import table; None when the chain is not a plain
        name chain (calls, subscripts...). ``self.x`` chains canonicalize
        to ``self.x`` — rules treat ``self`` specially."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = node.id
        parts.append(self.names.get(root, root))
        return ".".join(reversed(parts))


def build_import_table(tree: ast.Module, module_path: str = "") -> ImportTable:
    table = ImportTable(module_path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            table.add_import(node)
        elif isinstance(node, ast.ImportFrom):
            table.add_import_from(node)
    return table


def call_name(node: ast.Call, imports: ImportTable) -> Optional[str]:
    """Canonical dotted name of a call's callee (None if not a name chain)."""
    return imports.canonical(node.func)


def decorator_name(dec: ast.AST) -> Optional[str]:
    """Bare (rightmost) name of a decorator, unwrapping calls:
    ``@atomic_on_reject(check="x")`` -> "atomic_on_reject"."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    while isinstance(dec, ast.Attribute):
        if isinstance(dec.value, ast.Name) or isinstance(dec.value, ast.Attribute):
            return dec.attr
        return dec.attr
    if isinstance(dec, ast.Name):
        return dec.id
    return None


def decorator_call(node: "ast.FunctionDef | ast.AsyncFunctionDef | ast.ClassDef",
                   name: str) -> Optional[ast.AST]:
    """The decorator node matching ``name`` on a def/class, else None."""
    for dec in node.decorator_list:
        if decorator_name(dec) == name:
            return dec
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def is_constant_string(node: ast.AST) -> bool:
    """True for a plain string literal (implicit concatenation of
    literals parses as one Constant, so it counts). f-strings, ``+``
    concatenation, names, calls, and ``%``/``.format`` all count as
    dynamic — their dedup cardinality is unknowable statically."""
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


def self_attr(node: ast.AST) -> Optional[str]:
    """"x" when ``node`` is exactly ``self.x``, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None
