"""CLI: ``python -m shuffle_exchange_tpu.analysis [paths...]``.

Exit codes: 0 clean (stale-suppression warnings allowed), 1 unsuppressed
violations (or malformed suppressions), 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

from .report import fold, render_text, write_json
from .rules import RULES
from .walker import analyze


def _default_target() -> str:
    # the package directory containing this module's parent
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m shuffle_exchange_tpu.analysis",
        description="sxt-check: static analysis of the repo's "
                    "distributed-correctness invariants (see "
                    "shuffle_exchange_tpu/analysis/RULES.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to scan (default: the "
                         "shuffle_exchange_tpu package)")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", dest="json_path", default=None,
                    help="write the machine-readable report to this file")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--lock-graph", action="store_true",
                    help="dump the harvested lock-acquisition graph "
                         "(nodes+declared ranks+witness edges) as JSON — "
                         "the SXT009/SXT010 debugging view; also embedded "
                         "in the --json report")
    ap.add_argument("--verbose", "-v", action="store_true",
                    help="print incident + fix advice under each finding")
    ap.add_argument("--fail-on-stale", action="store_true",
                    help="treat stale suppressions as failures too")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            print(f"{rid}: {rule.title}")
            print(f"    incident: {rule.incident}")
            print(f"    fix: {rule.advice}")
        return 0

    select = None
    if args.select:
        select = {r.strip().upper() for r in args.select.split(",") if r.strip()}
        unknown = select - set(RULES)
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        select.add("SXT000")   # the meta-rule always runs

    paths = args.paths or [_default_target()]
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    results, graph = analyze(paths, select=select, want_graph=True)
    report = fold(results, select=select)
    if graph is not None and (args.lock_graph or args.json_path):
        report.lock_graph = graph.to_json()
    if args.lock_graph:
        import json as _json

        print(_json.dumps(report.lock_graph or {}, indent=2))
    out = render_text(report, verbose=args.verbose)
    if out:
        print(out)
    if args.json_path:
        write_json(report, args.json_path)
    if args.fail_on_stale and report.stale:
        return 1
    return report.exit_code


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:   # e.g. `--list-rules | head`
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
