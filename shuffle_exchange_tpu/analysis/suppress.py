"""Per-line suppression comments for sxt-check.

Grammar (one comment, end-of-line or on a standalone line immediately
above the flagged statement)::

    # sxt: ignore[SXT005] interpolates a fixed-per-process config value

  - the rule id list is mandatory: ``# sxt: ignore`` without
    ``[RULE,...]`` is ITSELF a violation (SXT000) — a suppression that
    does not say what it suppresses suppresses everything, which is how
    guardrails rot;
  - the free-text reason after the bracket is mandatory for the same
    reason: the next reader must learn WHY the sanctioned pattern does
    not apply here without archaeology;
  - a suppression that no longer matches any violation on its line is
    reported as a STALE warning (satellite: stale suppressions must not
    accumulate silently), without failing the run.

SXT000 findings are not themselves suppressible.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize
from typing import Dict, List, Tuple

#: matches the marker anywhere in a comment; groups: rules (optional), reason
_MARKER = re.compile(
    r"#\s*sxt:\s*ignore(?:\[(?P<rules>[A-Za-z0-9_,\s]*)\])?\s*(?P<reason>.*)$")

_RULE_ID = re.compile(r"^SXT\d{3}$")


@dataclasses.dataclass(frozen=True)
class Suppression:
    line: int
    rules: Tuple[str, ...]
    reason: str
    #: True when this comment sits alone on its line — it then also
    #: applies to the statement starting on the NEXT line
    standalone: bool


@dataclasses.dataclass(frozen=True)
class MalformedSuppression:
    line: int
    problem: str


def parse_suppressions(source: str):
    """-> (suppressions, malformed). Tokenize-based so strings that merely
    CONTAIN the marker text (this module, tests) never match."""
    sups: List[Suppression] = []
    bad: List[MalformedSuppression] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return sups, bad
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _MARKER.search(tok.string)
        if m is None:
            continue
        line = tok.start[0]
        standalone = tok.string.strip() == tok.line.strip()
        rules_raw = m.group("rules")
        reason = (m.group("reason") or "").strip()
        if rules_raw is None:
            bad.append(MalformedSuppression(
                line, "missing rule id: write `# sxt: ignore[SXTnnn] reason`"))
            continue
        rules = tuple(r.strip().upper() for r in rules_raw.split(",") if r.strip())
        invalid = [r for r in rules if not _RULE_ID.match(r)]
        if not rules or invalid:
            bad.append(MalformedSuppression(
                line, f"bad rule id list {rules_raw!r}: expected SXTnnn"
                      " (comma-separated)"))
            continue
        if not reason:
            bad.append(MalformedSuppression(
                line, f"missing reason: `# sxt: ignore[{','.join(rules)}]`"
                      " must say WHY the rule does not apply here"))
            continue
        sups.append(Suppression(line, rules, reason, standalone))
    return sups, bad


def build_index(sups: List[Suppression]) -> Dict[int, List[Suppression]]:
    """line -> suppressions applying to that line. A standalone comment
    on line N covers line N+1 (the statement it precedes); an end-of-line
    comment covers its own line. Multi-line statements are handled by the
    caller matching any line in the node's [lineno, end_lineno] span."""
    idx: Dict[int, List[Suppression]] = {}
    for s in sups:
        idx.setdefault(s.line, []).append(s)
        if s.standalone:
            idx.setdefault(s.line + 1, []).append(s)
    return idx
