"""sxt-check: framework-aware static analysis for shuffle_exchange_tpu.

Codifies the repo's hard-won distributed-correctness invariants (see
``analysis/RULES.md`` for the catalog, each rule citing the incident
that motivated it) as an AST pass that needs NO jax import and runs in
well under a second over the whole package::

    python -m shuffle_exchange_tpu.analysis shuffle_exchange_tpu/
    scripts/lint.sh        # sxt-check + ruff (when installed)

Per-line suppressions carry a mandatory rule id and reason::

    x = jax.device_put(np.asarray(b), s)  # sxt: ignore[SXT003] not donated

The tier-1 self-clean gate (``tests/test_analysis.py``) asserts the
package itself has zero unsuppressed violations.
"""

from .lockgraph import LockGraph, analyze_lock_graph, build_lock_graph
from .report import Report, fold, render_text, write_json
from .rules import RULES, FileChecker, Rule, Violation
from .suppress import parse_suppressions
from .walker import analyze, analyze_file, iter_python_files


def run(paths, select=None) -> Report:
    """Analyze ``paths`` (files or directories) and fold the results —
    the one-call API the tests and the CLI share. Includes the
    whole-tree lock-graph pass (SXT009/SXT010)."""
    return fold(analyze(paths, select=select), select=select)


__all__ = [
    "RULES", "Rule", "Violation", "FileChecker", "Report", "LockGraph",
    "analyze", "analyze_file", "analyze_lock_graph", "build_lock_graph",
    "iter_python_files", "fold",
    "render_text", "write_json", "parse_suppressions", "run",
]
