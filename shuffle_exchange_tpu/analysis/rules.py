"""sxt-check rule catalog + the single-pass AST checker.

Every rule codifies an invariant this repo paid to learn — the
originating incident is cited in each rule's ``incident`` field and in
``analysis/RULES.md``. The checker is purely syntactic (no imports, no
jax) and conservative by design: it matches the concrete patterns that
caused the bugs, and the sanctioned replacements, by name. Anything it
cannot prove derived/guarded is flagged; intentionally-divergent sites
carry a ``# sxt: ignore[RULE] reason`` with the written rationale.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..utils.invariants import DEFAULT_ADMISSION_CHECKS
from .scopes import (ImportTable, build_import_table, decorator_call,
                     decorator_name, is_constant_string, self_attr)


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    title: str
    incident: str       # which PR/bug this guards against (see RULES.md)
    advice: str         # the sanctioned pattern


RULES: Dict[str, Rule] = {r.id: r for r in [
    Rule("SXT000", "malformed suppression / unparseable file",
         "meta-rule: a suppression without a rule id and reason suppresses "
         "everything, which is how guardrails rot",
         "write `# sxt: ignore[SXTnnn] reason` (both parts mandatory)"),
    Rule("SXT001", "shard_map outside the parallel/mesh.py facade",
         "PR 4: jax 0.4.x has no jax.shard_map, and raw "
         "jax.experimental.shard_map call sites were the bulk of 55 tier-1 "
         "failures; every manual-region feature must route through the "
         "capability facade",
         "from ..parallel.mesh import shard_map (the facade maps "
         "axis_names/check_vma onto the 0.4.x auto=/check_rep form)"),
    Rule("SXT002", "donate_argnums not derived from cache_safe_donate_argnums",
         "PR 2: donated executables deserialized from the persistent "
         "compile cache race donated-buffer frees on jax 0.4.x CPU — "
         "resumed runs trained on garbage/NaN and segfaulted",
         "jax.jit(f, donate_argnums=cache_safe_donate_argnums(...)) or a "
         "value provably derived from it"),
    Rule("SXT003", "raw jax.device_put of host numpy",
         "PR 2: on CPU, device_put of aligned numpy can zero-copy ALIAS "
         "the host buffer; a donating executable then writes through freed "
         "memory once the numpy side is collected",
         "utils.placement.owned_device_put (materializes an XLA-owned "
         "buffer; no-op overhead off CPU)"),
    Rule("SXT004", "collective in a partial-manual shard_map region",
         "PR 4: ppermute/all_gather/all_to_all with a LIVE auto axis "
         "hard-abort XLA on jax 0.4.x (spmd_partitioner.cc:512 CHECK), a "
         "process abort, not an exception — scripts/repro_*.py hold the "
         "minimized repros",
         "gate on parallel.mesh.native_shard_map() and fall back (or make "
         "the region full-manual)"),
    Rule("SXT005", "warning_once with a non-constant message",
         "PR 8: a per-call-varying message defeats the lru_cache dedup — "
         "the draft-pressure fallback warning spammed once per tick until "
         "it was made a static string",
         "pass a constant string; put varying detail in a one-time "
         "logger.info or a counter"),
    Rule("SXT006", "state mutation before the admission check",
         "PRs 5-8: put()/step()/decode_loop()/begin_import() must be "
         "atomic-on-reject — a refused batch retried verbatim found "
         "double-frees and mid-COW deaths whenever mutation leaked ahead "
         "of the _admission_detail check",
         "validate and run the admission check before touching any "
         "allocator/descriptor/queue state (@atomic_on_reject marks the "
         "contract)"),
    Rule("SXT007", "lock-guarded attribute written outside its lock",
         "PR 7: threaded replica fleets corrupted router bookkeeping and "
         "raised mid-iteration RuntimeErrors until every shared structure "
         "got a lock discipline (@locked_by marks it)",
         "wrap the write in `with self.<lock>:` or mark the helper "
         "@requires_lock(<lock>) when every caller provably holds it"),
    Rule("SXT008", "host-only call inside a jitted body",
         "PR 1/PR 5 reviews: time.*/np.random inside a traced body bake "
         "trace-time constants (a timestamp or one fixed 'random' draw), "
         "and int()/float() on a tracer is a concretization error at best",
         "hoist host work out of the jitted function; use jax.random / "
         "shape-derived ints inside"),
    Rule("SXT009", "lock-order cycle across acquisition paths",
         "PR 11 chaos drill: submit held the router lock while blocked on "
         "a hung replica's lock; failover needed the router lock to fence "
         "that replica — a three-way deadlock whose reduction is two "
         "paths acquiring the same two locks in opposite orders. Fixed by "
         "hand (the lock-free fence), codified here",
         "acquire locks in strictly-increasing utils.invariants.LOCK_ORDER "
         "rank on every path; fence with bare writes below rank 0 when the "
         "order cannot hold (serving/router.py::fail_over)"),
    Rule("SXT010", "blocking call or rank-inverted acquisition under a "
                   "@locked_by lock",
         "PR 11 (hold-and-wait under the router lock is the deadlock's "
         "other half) and PR 7 (a SIGTERM handler draining through the "
         "reentrant router lock interleaved with a half-finished submit "
         "frame — the handler now only RECORDS the drain)",
         "while holding a @locked_by lock, only acquire strictly-higher-"
         "LOCK_ORDER-rank locks and never call join/wait/quiesce/tick/"
         "sleep-shaped methods; signal handlers must not lock at all "
         "(record-and-apply-at-tick, serving/lifecycle.py)"),
]}

#: mutating method names counted as writes for SXT006/SXT007
MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "update",
    "pop", "popleft", "popitem", "remove", "discard", "clear", "insert",
    "setdefault", "write_events",
})

COLLECTIVES = frozenset({
    "jax.lax.ppermute", "jax.lax.all_gather", "jax.lax.all_to_all",
})

#: jit SEAMS beyond ``jax.jit`` itself — helper names whose function-
#: typed arguments end up inside ``jax.jit``. ISSUE 16's sampled serving
#: steps compile through ``InferenceEngineV2._sampled_fn(key, impl)``,
#: so the bare ``jax.jit(self._x_impl)`` prepass no longer sees every
#: jitted body by name; any ``self.<attr>``/name argument at one of
#: these call sites is treated as a jitted body for SXT008 (sampling
#: must stay ``jax.random.fold_in``-seeded — a host ``np.random`` draw
#: in a sampled impl would bake ONE "random" token into the program).
JIT_SEAMS = frozenset({"_sampled_fn"})

_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str
    end_line: int = 0

    def span(self) -> Tuple[int, int]:
        return (self.line, max(self.line, self.end_line))


def _last_attr(node: ast.AST) -> Optional[str]:
    """Rightmost attribute/name of a callee, e.g. begin_import for
    dst.begin_import(...)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _subscript_base_attr(node: ast.AST) -> Optional[str]:
    """"x" for self.x[...] (arbitrarily deep subscripting), else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return self_attr(node)


def _iter_mutations(stmt: ast.stmt):
    """Yield (node, attr_name) for every ``self``-state write inside one
    statement, excluding nested function/lambda bodies (those run later,
    under their own discipline)."""

    def flat_targets(targets):
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                yield from flat_targets(t.elts)
            else:
                yield t

    def walk(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = list(flat_targets(
                node.targets if isinstance(node, (ast.Assign, ast.Delete))
                else [node.target]))
            for t in targets:
                attr = self_attr(t) or _subscript_base_attr(t)
                if attr is not None:
                    yield node, attr
            for child in ast.iter_child_nodes(node):
                if child not in targets:
                    yield from walk(child)
            return
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in MUTATORS:
                attr = self_attr(node.func.value)
                if attr is not None:
                    yield node, attr
        for child in ast.iter_child_nodes(node):
            yield from walk(child)

    yield from walk(stmt)


def _iter_skipping(node: ast.AST, skip):
    """Yield ``node`` and descendants, PRUNING whole subtrees whose root
    matches ``skip`` — unlike ``ast.walk`` + ``continue``, which only
    skips the node itself and still yields its children. Nested
    function/lambda bodies execute later under their own discipline, so
    their raises/calls must not leak into the enclosing analysis."""
    if isinstance(node, skip):
        return
    yield node
    for child in ast.iter_child_nodes(node):
        yield from _iter_skipping(child, skip)


def _contains_call_named(node: ast.AST, names: Sequence[str]) -> bool:
    """Any call whose rightmost callee name is in ``names``, nested
    function/lambda bodies excluded (a closure that merely references the
    checker has not RUN it)."""
    for sub in _iter_skipping(node, _NESTED):
        if isinstance(sub, ast.Call) and _last_attr(sub.func) in names:
            return True
    return False


def _contains_raise(stmts: Sequence[ast.stmt]) -> bool:
    """Any ``raise`` reachable in these statements, excluding except
    handlers (the reject path may legitimately update counters) and
    nested function bodies (a closure's raise fires at call time, not
    here)."""
    skip = _NESTED + (ast.ExceptHandler,)
    for st in stmts:
        for sub in _iter_skipping(st, skip):
            if isinstance(sub, ast.Raise):
                return True
    return False


class _AtomicChecker:
    """SXT006 body analysis for one @atomic_on_reject method."""

    def __init__(self, checker: "FileChecker", fn: ast.FunctionDef,
                 check: Optional[str]):
        self.c = checker
        self.fn = fn
        self.check = check

    def run(self) -> None:
        if self.check == "validate":
            self._walk_validate(self.fn.body, raises_after=False)
        else:
            names = ((self.check,) if self.check
                     else DEFAULT_ADMISSION_CHECKS)
            self._walk_named(self.fn.body, names, checked=False)

    # -- named-check mode: no mutation before the first admission call --

    def _walk_named(self, stmts, names, checked: bool) -> bool:
        for st in stmts:
            if isinstance(st, ast.If):
                test_check = _contains_call_named(st.test, names)
                self._walk_named(st.body, names, checked or test_check)
                self._walk_named(st.orelse, names, checked or test_check)
                # a check inside ONE branch does not cover code after the
                # If (the other branch may have skipped it)
                checked = checked or test_check
            elif isinstance(st, ast.Try):
                inner = self._walk_named(st.body, names, checked)
                self._walk_named(st.orelse, names, inner)
                self._walk_named(st.finalbody, names, inner)
                # handlers are the reject path; counter updates there are
                # fine by construction
                checked = checked or inner
            elif isinstance(st, (ast.For, ast.While, ast.With)):
                inner = self._walk_named(list(st.body), names, checked)
                self._walk_named(getattr(st, "orelse", []) or [], names, inner)
                checked = checked or inner
            else:
                if not checked:
                    for node, attr in _iter_mutations(st):
                        self.c.add("SXT006", node,
                                   f"`self.{attr}` mutated before the "
                                   f"admission check ({'/'.join(names)}) "
                                   f"in @atomic_on_reject method "
                                   f"`{self.fn.name}` — a rejected call "
                                   f"must leave state untouched")
                if _contains_call_named(st, names):
                    checked = True
        return checked

    # -- validate mode: no mutation while a validation raise is ahead --

    def _walk_validate(self, stmts, raises_after: bool) -> None:
        for i, st in enumerate(stmts):
            ahead = raises_after or _contains_raise(stmts[i + 1:])
            if isinstance(st, ast.If):
                self._walk_validate(st.body, ahead)
                self._walk_validate(st.orelse, ahead)
            elif isinstance(st, ast.Try):
                self._walk_validate(st.body, ahead)
                self._walk_validate(st.orelse, ahead)
                self._walk_validate(st.finalbody, ahead)
            elif isinstance(st, (ast.For, ast.While, ast.With)):
                body = list(getattr(st, "body", []))
                # a raise anywhere in the loop body is "ahead" of the
                # body's own mutations (iteration n+1 can still reject)
                self._walk_validate(body, ahead or (
                    isinstance(st, (ast.For, ast.While))
                    and _contains_raise(body)))
                self._walk_validate(getattr(st, "orelse", []) or [], ahead)
            else:
                if ahead:
                    for node, attr in _iter_mutations(st):
                        self.c.add("SXT006", node,
                                   f"`self.{attr}` mutated while a "
                                   f"validation raise is still ahead in "
                                   f"@atomic_on_reject(check=\"validate\") "
                                   f"method `{self.fn.name}` — validate "
                                   f"everything, then mutate")


class FileChecker(ast.NodeVisitor):
    """One pass over one file, all rules. Construct, call ``run()``,
    read ``violations`` (raw — suppressions are applied by report.py)."""

    def __init__(self, path: str, tree: ast.Module, module_path: str = "",
                 select: Optional[Set[str]] = None):
        self.path = path
        self.tree = tree
        self.module_path = module_path
        self.select = select
        self.imports: ImportTable = build_import_table(tree, module_path)
        self.violations: List[Violation] = []
        self._seen: Set[Tuple[str, int, int]] = set()
        # context stacks
        self._class_locks: List[Dict[str, Tuple[str, ...]]] = []  # lock->attrs
        self._attr_to_lock: List[Dict[str, str]] = []
        # multiset per function scope: re-entrant `with self._mu:` nesting
        # must not drop the outer hold when the inner block exits
        self._held_locks: List[List[str]] = [[]]
        self._init_exempt: List[bool] = [False]
        self._fn_stack: List[ast.FunctionDef] = []
        self._derived_vars: List[Set[str]] = [set()]
        self._numpy_vars: List[Set[str]] = [set()]
        self._local_fns: List[Dict[str, ast.FunctionDef]] = [{}]
        # prepass facts
        self._deriving_fns: Set[str] = set()
        self._jit_names: Set[str] = set()
        self._jitted_fns: Set[int] = set()
        self._in_mesh_facade = module_path.endswith("parallel.mesh")

    # -- public ---------------------------------------------------------

    def run(self) -> List[Violation]:
        self._prepass()
        self.visit(self.tree)
        return self.violations

    def add(self, rule: str, node: ast.AST, message: str) -> None:
        if self.select is not None and rule not in self.select:
            return
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        key = (rule, line, col)
        if key in self._seen:
            return
        self._seen.add(key)
        self.violations.append(Violation(
            rule, self.path, line, col, message,
            end_line=getattr(node, "end_lineno", line) or line))

    # -- prepass --------------------------------------------------------

    def _prepass(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef):
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Return) and sub.value is not None
                            and self._derives_donate(sub.value)):
                        self._deriving_fns.add(node.name)
                        break
                for dec in node.decorator_list:
                    if self._is_jit_decorator(dec):
                        self._jitted_fns.add(id(node))
            if isinstance(node, ast.Call):
                name = self.imports.canonical(node.func)
                if name == "jax.jit" and node.args:
                    tgt = node.args[0]
                    if isinstance(tgt, ast.Name):
                        self._jit_names.add(tgt.id)
                    else:
                        attr = self_attr(tgt)
                        if attr:
                            self._jit_names.add(attr)
                elif _last_attr(node.func) in JIT_SEAMS:
                    # a jit seam compiles the function it is handed —
                    # every function-shaped argument is a jitted body
                    for tgt in node.args:
                        if isinstance(tgt, ast.Name):
                            self._jit_names.add(tgt.id)
                        else:
                            attr = self_attr(tgt)
                            if attr:
                                self._jit_names.add(attr)
        if self._jit_names:
            for node in ast.walk(self.tree):
                if (isinstance(node, ast.FunctionDef)
                        and node.name in self._jit_names):
                    self._jitted_fns.add(id(node))

    def _is_jit_decorator(self, dec: ast.AST) -> bool:
        name = self.imports.canonical(dec if not isinstance(dec, ast.Call)
                                      else dec.func)
        if name == "jax.jit":
            return True
        if isinstance(dec, ast.Call) and name == "functools.partial" and dec.args:
            return self.imports.canonical(dec.args[0]) == "jax.jit"
        return False

    def _derives_donate(self, node: ast.AST) -> bool:
        """Value provably derived from cache_safe_donate_argnums: a direct
        call, a call to a same-module function that returns one, or a
        name assigned from either in the current scope chain."""
        if isinstance(node, ast.Call):
            name = self.imports.canonical(node.func)
            if name and name.endswith("cache_safe_donate_argnums"):
                return True
            last = _last_attr(node.func)
            if last in self._deriving_fns:
                return True
        if isinstance(node, ast.Name):
            return any(node.id in scope for scope in self._derived_vars)
        return False

    # -- scope bookkeeping ---------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        locks: Dict[str, Tuple[str, ...]] = {}
        dec = decorator_call(node, "locked_by")
        if isinstance(dec, ast.Call) and dec.args:
            lock = dec.args[0]
            if isinstance(lock, ast.Constant) and isinstance(lock.value, str):
                attrs = tuple(a.value for a in dec.args[1:]
                              if isinstance(a, ast.Constant)
                              and isinstance(a.value, str))
                locks[lock.value] = attrs
        self._class_locks.append(locks)
        self._attr_to_lock.append(
            {a: lk for lk, attrs in locks.items() for a in attrs})
        self.generic_visit(node)
        self._class_locks.pop()
        self._attr_to_lock.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        in_class = bool(self._class_locks)
        held: List[str] = []
        for dec in node.decorator_list:
            if decorator_name(dec) == "requires_lock" and isinstance(dec, ast.Call):
                for a in dec.args:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        held.append(a.value)
        atomic = decorator_call(node, "atomic_on_reject")
        if atomic is not None and in_class:
            check: Optional[str] = None
            if isinstance(atomic, ast.Call):
                for kw in atomic.keywords:
                    if kw.arg == "check" and isinstance(kw.value, ast.Constant):
                        check = kw.value.value
            _AtomicChecker(self, node, check).run()
        self._local_fns[-1][node.name] = node
        self._fn_stack.append(node)
        self._held_locks.append(held)
        self._init_exempt.append(in_class and node.name == "__init__"
                                 or (self._init_exempt[-1] if not in_class
                                     else False))
        self._derived_vars.append(set())
        self._numpy_vars.append(set())
        self._local_fns.append({})
        self.generic_visit(node)
        self._local_fns.pop()
        self._numpy_vars.pop()
        self._derived_vars.pop()
        self._init_exempt.pop()
        self._held_locks.pop()
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node: ast.With) -> None:
        pushed = []
        for item in node.items:
            attr = self_attr(item.context_expr)
            if attr is not None:
                self._held_locks[-1].append(attr)
                pushed.append(attr)
        self.generic_visit(node)
        for attr in pushed:
            self._held_locks[-1].remove(attr)

    # -- imports (SXT001) ----------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        if not self._in_mesh_facade:
            for alias in node.names:
                if "jax.experimental.shard_map" in alias.name:
                    self.add("SXT001", node,
                             f"import of {alias.name} outside the "
                             f"parallel/mesh.py facade")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if not self._in_mesh_facade:
            base = node.module or ""
            for alias in node.names:
                full = f"{base}.{alias.name}" if base else alias.name
                if (node.level == 0
                        and ("jax.experimental.shard_map" in full
                             or full == "jax.shard_map"
                             or (base == "jax.experimental"
                                 and alias.name == "shard_map"))):
                    self.add("SXT001", node,
                             f"import of {full} outside the parallel/"
                             f"mesh.py facade")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if not self._in_mesh_facade:
            name = self.imports.canonical(node)
            if name and (name == "jax.shard_map"
                         or name.startswith("jax.experimental.shard_map")):
                self.add("SXT001", node,
                         f"use of {name} outside the parallel/mesh.py "
                         f"facade")
        self.generic_visit(node)

    # -- statements -----------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self._track_assignment(node.targets, node.value)
        self._check_guarded_mutation(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_guarded_mutation(node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        self._check_guarded_mutation(node)
        self.generic_visit(node)

    def _track_assignment(self, targets, value) -> None:
        if len(targets) == 1 and isinstance(targets[0], ast.Name):
            name = targets[0].id
            if self._derives_donate(value):
                self._derived_vars[-1].add(name)
            if self._is_host_numpy(value):
                self._numpy_vars[-1].add(name)

    def _check_guarded_mutation(self, stmt: ast.stmt) -> None:
        if not self._attr_to_lock or not self._attr_to_lock[-1]:
            return
        if self._init_exempt[-1]:
            return
        table = self._attr_to_lock[-1]
        for node, attr in _iter_mutations(stmt):
            lock = table.get(attr)
            if lock is None:
                continue
            if lock in self._held_locks[-1]:
                continue
            self.add("SXT007", node,
                     f"`self.{attr}` is registered @locked_by(\"{lock}\") "
                     f"but written outside `with self.{lock}:` (mark the "
                     f"helper @requires_lock(\"{lock}\") if every caller "
                     f"holds it)")

    # -- calls (SXT002/3/4/5/7-mutators/8) -------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = self.imports.canonical(node.func)
        if name == "jax.jit":
            self._check_jit(node)
        elif name == "functools.partial" and node.args and \
                self.imports.canonical(node.args[0]) == "jax.jit":
            self._check_jit(node)
        elif name == "jax.device_put":
            self._check_device_put(node)
        last = _last_attr(node.func)
        if last == "warning_once":
            self._check_warning_once(node)
        if last == "shard_map" and not self._in_mesh_facade:
            self._check_shard_map_region(node)
        if self._in_jit():
            self._check_jit_body_call(node, name)
        # mutator calls on guarded attrs (the assignment forms are handled
        # in the statement visitors; calls arrive here)
        if (self._attr_to_lock and self._attr_to_lock[-1]
                and not self._init_exempt[-1]
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATORS):
            attr = self_attr(node.func.value)
            lock = self._attr_to_lock[-1].get(attr) if attr else None
            if lock is not None and lock not in self._held_locks[-1]:
                self.add("SXT007", node,
                         f"`self.{attr}.{node.func.attr}(...)` is "
                         f"registered @locked_by(\"{lock}\") but called "
                         f"outside `with self.{lock}:` (mark the helper "
                         f"@requires_lock(\"{lock}\") if every caller "
                         f"holds it)")
        self.generic_visit(node)

    def _check_jit(self, node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames"):
                continue
            if not self._derives_donate(kw.value):
                self.add("SXT002", node,
                         "donate_argnums must route through "
                         "cache_safe_donate_argnums (or a value derived "
                         "from it): raw donation corrupts memory under "
                         "the persistent compile cache on jax 0.4.x CPU")

    def _is_host_numpy(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            name = self.imports.canonical(node.func)
            if name and (name.startswith("numpy.") or name == "numpy"):
                return True
        if isinstance(node, ast.Name):
            return any(node.id in scope for scope in self._numpy_vars)
        return False

    def _check_device_put(self, node: ast.Call) -> None:
        if node.args and self._is_host_numpy(node.args[0]):
            self.add("SXT003", node,
                     "raw jax.device_put of host numpy — on CPU the result "
                     "can alias the host buffer; donated state then writes "
                     "through freed memory. Use "
                     "utils.placement.owned_device_put")

    def _check_warning_once(self, node: ast.Call) -> None:
        if not node.args:
            return
        if not is_constant_string(node.args[0]):
            self.add("SXT005", node,
                     "warning_once with a non-constant message: dedup is "
                     "by exact string, so a per-call-varying message warns "
                     "every call (pass a constant; put detail in "
                     "logger.info or a counter)")

    # -- SXT004 ---------------------------------------------------------

    def _check_shard_map_region(self, node: ast.Call) -> None:
        partial_manual = False
        for kw in node.keywords:
            if kw.arg == "axis_names":
                partial_manual = True
            if kw.arg == "auto" and not (
                    isinstance(kw.value, (ast.Tuple, ast.List, ast.Set))
                    and not kw.value.elts):
                partial_manual = True
        if not partial_manual or not node.args:
            return
        fn = self._resolve_function(node.args[0])
        if fn is None:
            return
        bad = None
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call):
                cname = self.imports.canonical(sub.func)
                if cname in COLLECTIVES:
                    bad = cname
                    break
        if bad is None:
            return
        # capability-gated sites reference native_shard_map() in the
        # enclosing function — the author consulted the matrix
        for scope in self._fn_stack:
            for sub in ast.walk(scope):
                if (isinstance(sub, (ast.Name, ast.Attribute))
                        and _last_attr(sub) == "native_shard_map"):
                    return
        self.add("SXT004", node,
                 f"{bad} inside a PARTIAL-manual shard_map region: with a "
                 f"live auto axis this CHECK-aborts XLA on jax 0.4.x "
                 f"(spmd_partitioner.cc:512). Gate on native_shard_map() "
                 f"or make the region full-manual")

    def _resolve_function(self, node: ast.AST) -> Optional[ast.AST]:
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            for scope in reversed(self._local_fns):
                if node.id in scope:
                    return scope[node.id]
        return None

    # -- SXT008 ---------------------------------------------------------

    def _in_jit(self) -> bool:
        return any(id(fn) in self._jitted_fns for fn in self._fn_stack)

    def _check_jit_body_call(self, node: ast.Call, name: Optional[str]) -> None:
        if name and name.startswith("time."):
            self.add("SXT008", node,
                     f"{name}() inside a jitted body runs at TRACE time — "
                     f"the compiled program reuses one frozen timestamp")
            return
        if name and name.startswith("numpy.random"):
            self.add("SXT008", node,
                     f"{name}(...) inside a jitted body bakes ONE draw "
                     f"into the compiled program — use jax.random with a "
                     f"threaded key")
            return
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("int", "float", "bool")
                and len(node.args) == 1 and isinstance(node.args[0], ast.Name)):
            fn = self._fn_stack[-1] if self._fn_stack else None
            if fn is not None:
                params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                          + fn.args.kwonlyargs)} - {"self"}
                if node.args[0].id in params:
                    self.add("SXT008", node,
                             f"{node.func.id}({node.args[0].id}) coerces a "
                             f"traced argument inside a jitted body — a "
                             f"ConcretizationTypeError at best, a baked "
                             f"constant at worst")
