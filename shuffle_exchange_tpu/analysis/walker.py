"""File discovery and per-file analysis for sxt-check."""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterator, List, Optional, Sequence, Set

from .rules import FileChecker, Violation
from .suppress import (MalformedSuppression, Suppression, parse_suppressions)

PACKAGE = "shuffle_exchange_tpu"


@dataclasses.dataclass
class FileResult:
    path: str
    violations: List[Violation]          # raw; suppressions applied later
    suppressions: List[Suppression]
    malformed: List[MalformedSuppression]
    #: parse artifacts kept for the whole-tree lock-graph pass (SXT009/
    #: SXT010, analysis/lockgraph.py); None when the file did not parse
    tree: "ast.Module | None" = None
    module_path: str = ""


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if not d.startswith(".") and d != "__pycache__")
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def module_path_of(path: str) -> str:
    """Dotted module path rooted at the package dir when the file lives
    under it (used for relative-import resolution and the mesh-facade
    exemption); best-effort otherwise."""
    norm = os.path.normpath(os.path.abspath(path))
    parts = norm.split(os.sep)
    if PACKAGE in parts:
        parts = parts[parts.index(PACKAGE):]
    else:
        parts = parts[-1:]
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def analyze_file(path: str, select: Optional[Set[str]] = None) -> FileResult:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    sups, malformed = parse_suppressions(source)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return FileResult(path, [Violation(
            "SXT000", path, e.lineno or 1, e.offset or 0,
            f"file does not parse: {e.msg}")], sups, malformed)
    mp = module_path_of(path)
    checker = FileChecker(path, tree, mp, select=select)
    return FileResult(path, checker.run(), sups, malformed,
                      tree=tree, module_path=mp)


def analyze(paths: Sequence[str], select: Optional[Set[str]] = None,
            want_graph: bool = False):
    """Per-file rules plus the whole-tree lock-graph pass (SXT009/SXT010
    need every scanned file's acquisitions to judge an ORDER, so they run
    over the folded set, and their violations land on the owning file so
    the per-line suppression machinery applies unchanged). With
    ``want_graph`` returns ``(results, LockGraph-or-None)`` for the CLI's
    ``--lock-graph`` dump."""
    results = [analyze_file(p, select=select) for p in iter_python_files(paths)]
    graph = None
    if select is None or select & {"SXT009", "SXT010"}:
        from .lockgraph import analyze_lock_graph

        entries = [(fr.path, fr.tree, fr.module_path)
                   for fr in results if fr.tree is not None]
        graph, extra = analyze_lock_graph(entries)
        by_path = {fr.path: fr for fr in results}
        for path, vios in extra.items():
            for v in vios:
                if select is None or v.rule in select:
                    by_path[path].violations.append(v)
    return (results, graph) if want_graph else results
