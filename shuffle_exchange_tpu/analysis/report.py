"""Suppression application, stale detection, and report rendering."""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Sequence

from .rules import RULES, Violation
from .suppress import build_index
from .walker import FileResult


@dataclasses.dataclass
class SuppressedViolation:
    violation: Violation
    reason: str
    suppression_line: int


@dataclasses.dataclass
class StaleSuppression:
    path: str
    line: int
    rules: tuple
    reason: str


@dataclasses.dataclass
class Report:
    violations: List[Violation]              # unsuppressed (fail the run)
    suppressed: List[SuppressedViolation]
    stale: List[StaleSuppression]            # warnings (do not fail)
    files_scanned: int
    #: lock-graph dump (nodes+ranks+edges) when the CLI ran with
    #: ``--lock-graph``; rides into to_json() for debugging SXT009/SXT010
    lock_graph: "dict | None" = None

    @property
    def exit_code(self) -> int:
        return 1 if self.violations else 0

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.violations:
            out[v.rule] = out.get(v.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "tool": "sxt-check",
            "files_scanned": self.files_scanned,
            "exit_code": self.exit_code,
            "counts": self.counts(),
            "violations": [dataclasses.asdict(v) for v in self.violations],
            "suppressed": [{
                **dataclasses.asdict(s.violation),
                "reason": s.reason,
                "suppression_line": s.suppression_line,
            } for s in self.suppressed],
            "stale_suppressions": [dataclasses.asdict(s) for s in self.stale],
            "rules": {rid: {"title": r.title, "incident": r.incident,
                            "advice": r.advice}
                      for rid, r in sorted(RULES.items())},
            **({"lock_graph": self.lock_graph}
               if self.lock_graph is not None else {}),
        }


def fold(results: Sequence[FileResult], select=None) -> Report:
    """Apply suppressions and collect stale ones. ``select`` is the rule
    subset that RAN (None = all): a suppression for a rule that never ran
    cannot be judged stale — without this, ``--select SXT001`` would
    report every valid SXT005 suppression as deletable."""
    violations: List[Violation] = []
    suppressed: List[SuppressedViolation] = []
    stale: List[StaleSuppression] = []
    for fr in results:
        idx = build_index(fr.suppressions)
        used = set()
        for v in fr.violations:
            match = None
            if v.rule != "SXT000":   # the meta-rule is unsuppressable
                lo, hi = v.span()
                for line in range(lo, hi + 1):
                    for s in idx.get(line, ()):
                        if v.rule in s.rules:
                            match = s
                            break
                    if match:
                        break
            if match is not None:
                used.add(id(match))
                suppressed.append(SuppressedViolation(v, match.reason,
                                                      match.line))
            else:
                violations.append(v)
        for m in fr.malformed:
            violations.append(Violation("SXT000", fr.path, m.line, 0,
                                        m.problem))
        for s in fr.suppressions:
            ran = select is None or any(r in select for r in s.rules)
            if ran and id(s) not in used:
                stale.append(StaleSuppression(fr.path, s.line, s.rules,
                                              s.reason))
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return Report(violations, suppressed, stale, files_scanned=len(results))


def render_text(report: Report, verbose: bool = False) -> str:
    lines: List[str] = []
    for v in report.violations:
        rule = RULES.get(v.rule)
        lines.append(f"{v.path}:{v.line}:{v.col + 1}: {v.rule} {v.message}")
        if verbose and rule is not None:
            lines.append(f"    incident: {rule.incident}")
            lines.append(f"    fix: {rule.advice}")
    for s in report.stale:
        lines.append(f"{s.path}:{s.line}: warning: stale suppression "
                     f"[{','.join(s.rules)}] — the rule no longer fires "
                     f"here; delete it (reason was: {s.reason})")
    n, ns, nw = len(report.violations), len(report.suppressed), len(report.stale)
    lines.append(
        f"sxt-check: {report.files_scanned} files, {n} violation"
        f"{'s' if n != 1 else ''}, {ns} suppressed, {nw} stale-suppression "
        f"warning{'s' if nw != 1 else ''}")
    return "\n".join(lines)


def write_json(report: Report, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report.to_json(), f, indent=2, sort_keys=False)
        f.write("\n")
