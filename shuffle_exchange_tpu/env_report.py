"""Environment / capability report (reference ``deepspeed/env_report.py``,
the ``ds_report`` CLI): what backend is live, which native extensions
built, which kernel paths are active.

Usage::

    python -m shuffle_exchange_tpu.env_report
"""

from __future__ import annotations

import importlib
import os
import sys


def _row(name: str, status: str, note: str = "") -> str:
    return f"{name:<28} {status:<12} {note}"


def collect(probe_devices: bool = True) -> list:
    """Rows of (name, status, note). ``probe_devices=False`` skips backend
    bring-up (it can hang when a tunneled device is down)."""
    rows = []

    for mod in ("jax", "flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = importlib.import_module(mod)
            rows.append((mod, "ok", getattr(m, "__version__", "")))
        except Exception as e:  # pragma: no cover
            rows.append((mod, "MISSING", type(e).__name__))

    if probe_devices:
        try:
            import jax

            devs = jax.devices()
            rows.append(("backend", jax.default_backend(),
                         f"{len(devs)} device(s): {devs[0].device_kind}"))
        except Exception as e:
            rows.append(("backend", "ERROR", str(e)[:80]))
    else:
        rows.append(("backend", "skipped", "probe_devices=False"))

    if probe_devices:
        # pallas_enabled() asks the live backend — only safe when probing
        from .ops.dispatch import pallas_enabled

        try:
            on = pallas_enabled()
            rows.append(("pallas kernels", "enabled" if on else "disabled",
                         "" if on else "non-TPU backend or SXT_DISABLE_PALLAS"))
        except Exception as e:  # pragma: no cover
            rows.append(("pallas kernels", "ERROR", str(e)[:80]))
    elif os.environ.get("SXT_DISABLE_PALLAS"):
        rows.append(("pallas kernels", "disabled", "SXT_DISABLE_PALLAS set"))
    else:
        rows.append(("pallas kernels", "auto", "enabled on a TPU backend"))

    try:
        from jax.experimental.pallas.ops.tpu.megablox import gmm  # noqa: F401

        rows.append(("megablox grouped GEMM", "available", ""))
    except Exception:
        rows.append(("megablox grouped GEMM", "unavailable",
                     "MoE ragged path uses lax.ragged_dot"))

    # native (C++) runtime lib (aio + cpu_optim + packbits, csrc/) — built
    # lazily into the build dir; report without triggering a build
    try:
        import glob

        from .ops.native.builder import _build_dir

        sos = glob.glob(os.path.join(_build_dir(), "*.so"))
        rows.append(("native runtime (csrc)", "built" if sos else "not built",
                     sos[0] if sos else "g++ builds it on first use"))
    except Exception as e:  # pragma: no cover
        rows.append(("native runtime (csrc)", "ERROR", str(e)[:80]))
    return rows


def main(argv=None) -> int:
    probe = "--no-device" not in (argv or sys.argv[1:])
    print("shuffle_exchange_tpu environment report")
    print("-" * 72)
    for name, status, note in collect(probe_devices=probe):
        print(_row(name, status, note))
    return 0


if __name__ == "__main__":
    sys.exit(main())
