"""LoRA / OptimizedLinear subsystem (reference ``deepspeed/linear``)."""

from .optimized_linear import (DEFAULT_TARGET_MODS, LoRAConfig,
                               QuantizationConfig, apply_optimized_linear,
                               dequantize_frozen, encode_frozen, full_weight,
                               init_optimized_linear, lora_leaf_paths,
                               lora_merge, lora_split,
                               lora_split_abstract_init, normalize_targets,
                               split_specs)

__all__ = [
    "LoRAConfig", "QuantizationConfig", "DEFAULT_TARGET_MODS",
    "lora_split", "lora_split_abstract_init", "lora_merge",
    "encode_frozen", "dequantize_frozen", "full_weight", "lora_leaf_paths",
    "normalize_targets", "split_specs",
    "init_optimized_linear", "apply_optimized_linear",
]
