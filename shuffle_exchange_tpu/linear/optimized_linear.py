"""LoRA / OptimizedLinear subsystem — TPU-native pytree transforms.

Capability analog of the reference's ``deepspeed/linear`` package
(``optimized_linear.py:76`` ``LoRAOptimizedLinear``, ``quantization.py``
``QuantizedParameter``/``QuantizedLinear``, ``config.py`` ``LoRAConfig``/
``QuantizationConfig``):

* the reference swaps ``nn.Linear`` modules for a ``LoRAOptimizedLinear``
  that holds a frozen (possibly fp-quantized, possibly world-sharded) base
  weight plus two trainable bf16 LoRA factors, and adds
  ``base + (alpha/r) * lora2(lora1(x))`` in forward;
* here the same split is a **params transform**: target leaves move into a
  FROZEN pytree (bf16, or int8 :class:`~..ops.quant_matmul.QuantizedMatrix`
  when quantization is on — the ``QuantizedParameter`` analog) and are
  replaced in the trainable tree by ``{"lora_a", "lora_b"}`` factor pairs.
  :func:`lora_merge` fuses ``W + (alpha/r) A @ B`` back into model-structured
  forward weights INSIDE the differentiated jitted step, so gradients reach
  A/B by chain rule while the frozen base takes none (``stop_gradient``).

The reference's ``base_weight_sharding`` + ``full_weight()`` manual
all-gather (optimized_linear.py:183-199) collapses to a sharding spec: the
frozen tree is placed with ZeRO partition specs and XLA inserts the gather
where the merge consumes it.

Weight convention matches the model zoo: ``y = x @ W`` with ``W [..., in,
out]`` and optional stacked leading layer dims, so ``A [..., in, r]``
(kaiming-uniform, a=sqrt(5), following peft) and ``B [..., r, out]``
(zeros — the fused weight starts exactly at the base).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

LORA_A = "lora_a"
LORA_B = "lora_b"

# Reference default target_mods are llama-HF projection names
# (linear/config.py:34); the model zoo uses its own leaf names. Both spell
# the same seven matrices.
TARGET_ALIASES = {
    "q_proj": "wq", "k_proj": "wk", "v_proj": "wv", "o_proj": "wo",
    "gate_proj": "w_gate", "up_proj": "w_up", "down_proj": "w_down",
}
DEFAULT_TARGET_MODS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@dataclass
class LoRAConfig:
    """Python-API config (field names match reference linear/config.py:13)."""

    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    offload: bool = False
    offload_ratio: float = 0.0
    delay_lora_init: bool = False
    target_mods: List[str] = field(default_factory=lambda: list(DEFAULT_TARGET_MODS))

    @property
    def scaling(self) -> float:
        return self.lora_alpha / self.lora_r


@dataclass
class QuantizationConfig:
    """Frozen-base quantization (reference linear/config.py:39). The TPU
    storage is int8/int4 grouped :class:`QuantizedMatrix` (the fp-quantizer
    CUDA kernels' capability analog); ``mantissa_bits`` is accepted for
    config parity but the integer codes carry no mantissa split."""

    q_bits: int = 8
    mantissa_bits: int = 3
    group_size: int = 512


def normalize_targets(mods: Optional[Sequence[str]]) -> frozenset:
    mods = mods or DEFAULT_TARGET_MODS
    return frozenset(TARGET_ALIASES.get(m, m) for m in mods)


def is_lora_pair(node: Any) -> bool:
    return isinstance(node, dict) and set(node.keys()) == {LORA_A, LORA_B}


def _is_target_leaf(name: str, leaf: Any, targets: frozenset) -> bool:
    return name in targets and getattr(leaf, "ndim", 0) >= 2


def _kaiming_bound(fan_in: int) -> float:
    # kaiming_uniform(a=sqrt(5)): bound = sqrt(6 / ((1 + a^2) * fan_in))
    return math.sqrt(6.0 / (6.0 * fan_in))


def lora_split(params: Dict[str, Any], lora_cfg: LoRAConfig,
               rng: Optional[np.random.Generator] = None,
               abstract: bool = False) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Split a params tree into (trainable-with-lora, frozen-base).

    Target leaves ``W [..., in, out]`` are moved (as-is, still fp32 — casting
    /quantization is :func:`encode_frozen`'s job so it can run inside jit
    with sharded outputs) into the returned ``frozen`` tree, and replaced by
    ``{"lora_a": A, "lora_b": B}``. With ``abstract=True`` leaves are
    ``ShapeDtypeStruct`` templates (the zero.Init deferred-init path).
    """
    import jax
    import jax.numpy as jnp

    targets = normalize_targets(lora_cfg.target_mods)
    r = int(lora_cfg.lora_r)
    if r <= 0:
        raise ValueError(f"lora_r must be positive, got {r}")
    rng = rng or np.random.default_rng(0)
    n_found = 0

    def walk(tree):
        nonlocal n_found
        out, frozen = {}, {}
        for k, v in tree.items():
            if isinstance(v, dict):
                o, f = walk(v)
                out[k] = o
                if f:
                    frozen[k] = f
            elif _is_target_leaf(k, v, targets):
                n_found += 1
                *lead, fan_in, fan_out = v.shape
                a_shape = (*lead, fan_in, r)
                b_shape = (*lead, r, fan_out)
                if abstract:
                    a = jax.ShapeDtypeStruct(a_shape, jnp.float32)
                    b = jax.ShapeDtypeStruct(b_shape, jnp.float32)
                else:
                    bound = _kaiming_bound(fan_in)
                    a = rng.uniform(-bound, bound, size=a_shape).astype(np.float32)
                    b = np.zeros(b_shape, np.float32)
                out[k] = {LORA_A: a, LORA_B: b}
                frozen[k] = v
            else:
                out[k] = v
        return out, frozen

    new_params, frozen = walk(params)
    if n_found == 0:
        raise ValueError(
            f"lora: no target leaves found for target_mods={sorted(targets)}; "
            "check the names against the model's parameter leaves")
    return new_params, frozen


def lora_split_abstract_init(params_init_fn, lora_cfg: LoRAConfig):
    """Wrap a ``rng -> params`` init so it returns ``(params_with_lora,
    frozen_fp32)`` — traced inside jit with sharded outputs (zero.Init)."""
    import jax
    import jax.numpy as jnp

    targets = normalize_targets(lora_cfg.target_mods)
    r = int(lora_cfg.lora_r)

    def init(key):
        p = params_init_fn(key)
        base = jax.random.fold_in(key, 0x10A)
        n_seen = 0

        def walk(tree):
            nonlocal n_seen
            out, frozen = {}, {}
            for k, v in tree.items():
                if isinstance(v, dict):
                    o, f = walk(v)
                    out[k] = o
                    if f:
                        frozen[k] = f
                elif _is_target_leaf(k, v, targets):
                    *lead, fan_in, fan_out = v.shape
                    bound = _kaiming_bound(fan_in)
                    # fold_in per target index: no cap on the number of
                    # target leaves (dict walks are deterministic-order)
                    a = jax.random.uniform(jax.random.fold_in(base, n_seen),
                                           (*lead, fan_in, r),
                                           jnp.float32, -bound, bound)
                    n_seen += 1
                    out[k] = {LORA_A: a, LORA_B: jnp.zeros((*lead, r, fan_out), jnp.float32)}
                    frozen[k] = v
                else:
                    out[k] = v
            return out, frozen

        return walk(p)

    return init


def encode_frozen(frozen: Dict[str, Any], quant_cfg: Optional[QuantizationConfig],
                  dtype) -> Dict[str, Any]:
    """fp32 frozen tree -> storage form: bf16 cast, or int8/int4 grouped
    QuantizedMatrix when quantization is configured (the QuantizedParameter
    analog — reference linear/quantization.py:18 quantizes on device
    placement; here the encode is jit-traceable so it can run sharded)."""
    from ..ops.quant_matmul import quantize_weight

    def enc(leaf):
        if quant_cfg is not None:
            gs = min(quant_cfg.group_size, leaf.shape[-2])
            # group size must divide K; fall back to a divisor
            while leaf.shape[-2] % gs:
                gs -= 1
            return quantize_weight(leaf, group_size=gs, dtype=dtype,
                                   bits=quant_cfg.q_bits)
        return leaf.astype(dtype)

    return _map_frozen(frozen, enc)


def _map_frozen(frozen, fn):
    out = {}
    for k, v in frozen.items():
        out[k] = _map_frozen(v, fn) if isinstance(v, dict) else fn(v)
    return out


def dequantize_frozen(frozen: Dict[str, Any], dtype) -> Dict[str, Any]:
    """Storage form -> dense bf16 forward weights (``full_weight`` analog:
    reference optimized_linear.py:183 dequantizes + all-gathers; the gather
    here is XLA's, inserted where the merge consumes the sharded leaf)."""
    from ..ops.quant_matmul import QuantizedMatrix

    def deq(leaf):
        if isinstance(leaf, QuantizedMatrix):
            return leaf.dequantize().astype(dtype)
        return leaf.astype(dtype)

    return _map_frozen(frozen, deq)


def full_weight(frozen_leaf) -> Any:
    """Dense full weight of one frozen leaf (API parity with reference
    ``LoRAOptimizedLinear.full_weight``)."""
    from ..ops.quant_matmul import QuantizedMatrix

    if isinstance(frozen_leaf, QuantizedMatrix):
        return frozen_leaf.dequantize()
    return frozen_leaf


def lora_merge(params: Dict[str, Any], frozen16: Dict[str, Any],
               scaling: float) -> Dict[str, Any]:
    """Fuse ``W + scaling * A @ B`` back into a model-structured tree.

    ``frozen16`` must already be dense (see :func:`dequantize_frozen`) and is
    ``stop_gradient``-ed: differentiating the result w.r.t. ``params`` gives
    exact chain-rule gradients for A/B and none for the base — the
    requires_grad split of reference optimized_linear.py:135-159.
    """
    import jax
    import jax.numpy as jnp

    def walk(tree, fro):
        out = {}
        for k, v in tree.items():
            if is_lora_pair(v):
                base = jax.lax.stop_gradient(fro[k])
                a, b = v[LORA_A], v[LORA_B]
                delta = jnp.matmul(a, b) * jnp.asarray(scaling, a.dtype)
                out[k] = base + delta.astype(base.dtype)
            elif isinstance(v, dict):
                out[k] = walk(v, fro.get(k, {}) if isinstance(fro, dict) else {})
            else:
                out[k] = v
        return out

    return walk(params, frozen16)


def lora_leaf_paths(params: Dict[str, Any], prefix: str = "") -> List[str]:
    """Dotted paths of every lora factor leaf (test/introspection helper)."""
    out = []
    for k, v in params.items():
        p = f"{prefix}{k}"
        if is_lora_pair(v):
            out += [f"{p}.{LORA_A}", f"{p}.{LORA_B}"]
        elif isinstance(v, dict):
            out += lora_leaf_paths(v, p + ".")
    return out


def split_specs(model_specs: Dict[str, Any], frozen_template: Dict[str, Any]):
    """Transform a model PartitionSpec tree alongside :func:`lora_split`:
    specs of target leaves move to the frozen-spec tree; the lora pair gets
    replicated specs (factors are rank-r — sharding them buys nothing, and
    the fused-weight sharding is decided where the merge output is used)."""
    from jax.sharding import PartitionSpec as P

    def walk(spec_tree, fro):
        out, fro_specs = {}, {}
        for k, v in spec_tree.items():
            in_frozen = isinstance(fro, dict) and k in fro
            if in_frozen and not isinstance(fro[k], dict):
                out[k] = {LORA_A: P(), LORA_B: P()}
                fro_specs[k] = v
            elif isinstance(v, dict):
                o, f = walk(v, fro.get(k, {}) if isinstance(fro, dict) else {})
                out[k] = o
                if f:
                    fro_specs[k] = f
            else:
                out[k] = v
        return out, fro_specs

    return walk(model_specs, frozen_template)


# -- standalone single-matrix API (OptimizedLinear parity) -----------------

def init_optimized_linear(key, input_dim: int, output_dim: int,
                          lora_config: Optional[LoRAConfig] = None,
                          quantization_config: Optional[QuantizationConfig] = None,
                          dtype=None):
    """Single-matrix analog of reference ``OptimizedLinear.__new__``:
    returns ``(trainable, frozen)`` for ``y = x @ W``. With no lora config,
    ``trainable`` is just the dense weight (nn.Linear fallback); with lora,
    ``trainable`` is the A/B pair and ``frozen`` holds the (possibly
    quantized) base."""
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    w = (jax.random.normal(key, (input_dim, output_dim), jnp.float32)
         / math.sqrt(input_dim))
    if lora_config is None and quantization_config is None:
        return {"w": w.astype(dtype)}, {}
    if lora_config is None:
        return {}, encode_frozen({"w": w}, quantization_config, dtype)
    seed = int(jax.random.randint(key, (), 0, 2**31 - 1))
    single = LoRAConfig(lora_r=lora_config.lora_r, lora_alpha=lora_config.lora_alpha,
                        target_mods=["w"])
    trainable, frozen = lora_split({"w": w}, single,
                                   rng=np.random.default_rng(seed))
    return trainable, encode_frozen(frozen, quantization_config, dtype)


def apply_optimized_linear(x, trainable, frozen, lora_config: Optional[LoRAConfig] = None):
    """Forward for :func:`init_optimized_linear` outputs."""
    if not frozen:
        return x @ trainable["w"]
    if not trainable:
        return x @ full_weight(frozen["w"]).astype(x.dtype)
    fro16 = dequantize_frozen(frozen, x.dtype)
    t16 = {k: {LORA_A: v[LORA_A].astype(x.dtype), LORA_B: v[LORA_B].astype(x.dtype)}
           for k, v in trainable.items()}
    merged = lora_merge(t16, fro16, (lora_config or LoRAConfig()).scaling)
    return x @ merged["w"]
