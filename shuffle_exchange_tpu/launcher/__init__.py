"""Launcher: multi-host runner CLI + elastic supervision (reference
``launcher/`` + ``elasticity/elastic_agent.py``)."""

from .elastic_agent import AutoscalePolicy, ElasticAgent, run_elastic
from .runner import (build_commands, collect_env, filter_hosts, main,
                     parse_args, parse_hostfile)

__all__ = ["AutoscalePolicy", "ElasticAgent", "run_elastic", "build_commands",
           "collect_env", "filter_hosts", "main", "parse_args",
           "parse_hostfile"]
