"""Elastic agent: supervised training with restart + checkpoint resume.

Capability parity with the reference's ``DSElasticAgent``
(``elasticity/elastic_agent.py:32``, SURVEY.md §5.3): monitor the training
worker, and on failure restart it against the (possibly changed) device
world, with the elasticity batch plan guaranteeing an identical effective
batch size at the new world size and checkpoint-resume supplying the
state. Where the reference plugs into torch-elastic's rendezvous, the TPU
runtime re-forms the pod on process restart — so the agent is a
supervision loop around the user's train function.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..utils.logging import logger


class ElasticAgent:
    """Run ``train_fn(restart_count)`` with up to ``max_restarts`` retries.

    ``train_fn`` should build its engine fresh (re-reading the device world)
    and ``load_checkpoint`` from its save dir if present — the agent itself
    is state-free. ``on_failure(exc, restart_count)`` may veto the restart
    by returning False (e.g. for config errors that will never succeed).

    Backoff is exponential from ``backoff_s`` up to the ``max_backoff_s``
    ceiling. When an attempt ran healthy for at least ``healthy_reset_s``
    before failing, ``restart_count`` resets first — a long job's restart
    budget guards against crash *loops*, not against unrelated failures
    days apart. Restart events are emitted to ``monitor`` (a
    ``MonitorMaster`` or anything with ``write_events``) under
    ``resilience/restarts``.
    """

    def __init__(self, max_restarts: int = 3, backoff_s: float = 2.0,
                 on_failure: Optional[Callable] = None,
                 max_backoff_s: float = 60.0,
                 healthy_reset_s: Optional[float] = None,
                 monitor=None):
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.healthy_reset_s = healthy_reset_s
        self.on_failure = on_failure
        self.monitor = monitor
        self.restart_count = 0
        self.total_restarts = 0

    def _emit_restart(self) -> None:
        if self.monitor is None:
            return
        try:
            self.monitor.write_events([
                ("resilience/restarts", self.total_restarts, self.total_restarts)])
        except Exception:
            logger.exception("elastic agent: monitor write failed")

    def run(self, train_fn: Callable):
        while True:
            started = time.monotonic()
            try:
                return train_fn(self.restart_count)
            except KeyboardInterrupt:
                raise
            except Exception as e:
                healthy_for = time.monotonic() - started
                if (self.healthy_reset_s is not None and self.restart_count
                        and healthy_for >= self.healthy_reset_s):
                    logger.info(
                        f"elastic agent: attempt ran healthy for "
                        f"{healthy_for:.0f}s (>= {self.healthy_reset_s:.0f}s); "
                        f"resetting restart budget ({self.restart_count} -> 0)")
                    self.restart_count = 0
                if self.on_failure is not None and self.on_failure(e, self.restart_count) is False:
                    raise
                if self.restart_count >= self.max_restarts:
                    logger.error(f"elastic agent: giving up after {self.restart_count} restarts")
                    raise
                self.restart_count += 1
                self.total_restarts += 1
                self._emit_restart()
                delay = min(self.max_backoff_s, self.backoff_s * (2.0 ** (self.restart_count - 1)))
                logger.warning(f"elastic agent: worker failed ({type(e).__name__}: {e}); "
                               f"restart {self.restart_count}/{self.max_restarts} in {delay:.0f}s")
                time.sleep(delay)


class AutoscalePolicy:
    """Queue-depth-driven replica-count policy for the serving front
    (ISSUE 7; the serving-side counterpart of the reference ElasticAgent's
    scale-against-load loop, SURVEY §5.3).

    ``desired(current, queue_depth_per_replica)`` returns the replica
    count the fleet should run: above ``scale_up_queue_depth`` mean queued
    requests per ACTIVE replica it grows by one, below
    ``scale_down_queue_depth`` it shrinks by one, clamped to
    [min_replicas, max_replicas]. ``patience`` consecutive observations on
    the same side of a threshold are required before a move (hysteresis —
    a Poisson burst should not thrash drain/spawn cycles, each of which
    costs a full KV-pool requeue on the drained replica). The policy is
    deliberately engine-agnostic: the router feeds it numbers and applies
    its verdict (``serving/lifecycle.py``)."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 8,
                 scale_up_queue_depth: float = 8.0,
                 scale_down_queue_depth: float = 1.0,
                 patience: int = 2):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}")
        if scale_down_queue_depth >= scale_up_queue_depth:
            raise ValueError(
                f"scale_down_queue_depth ({scale_down_queue_depth}) must be "
                f"below scale_up_queue_depth ({scale_up_queue_depth})")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.scale_up_queue_depth = scale_up_queue_depth
        self.scale_down_queue_depth = scale_down_queue_depth
        self.patience = patience
        self._streak = 0          # +n consecutive over, -n consecutive under

    @classmethod
    def from_router_config(cls, rcfg, patience: int = 2) -> "AutoscalePolicy":
        """Build from an ``inference.config.RouterConfig`` section."""
        return cls(min_replicas=rcfg.min_replicas,
                   max_replicas=rcfg.max_replicas,
                   scale_up_queue_depth=rcfg.scale_up_queue_depth,
                   scale_down_queue_depth=rcfg.scale_down_queue_depth,
                   patience=patience)

    def desired(self, current: int, queue_depth_per_replica: float) -> int:
        if queue_depth_per_replica > self.scale_up_queue_depth:
            self._streak = max(1, self._streak + 1)
        elif queue_depth_per_replica < self.scale_down_queue_depth:
            self._streak = min(-1, self._streak - 1)
        else:
            self._streak = 0
        target = current
        if self._streak >= self.patience:
            target, self._streak = current + 1, 0
        elif self._streak <= -self.patience:
            target, self._streak = current - 1, 0
        return max(self.min_replicas, min(self.max_replicas, target))


def run_elastic(train_fn: Callable, max_restarts: int = 3, backoff_s: float = 2.0,
                on_failure: Optional[Callable] = None, max_backoff_s: float = 60.0,
                healthy_reset_s: Optional[float] = None, monitor=None):
    """Functional entry: supervise ``train_fn`` (see ElasticAgent)."""
    return ElasticAgent(max_restarts=max_restarts, backoff_s=backoff_s,
                        on_failure=on_failure, max_backoff_s=max_backoff_s,
                        healthy_reset_s=healthy_reset_s, monitor=monitor).run(train_fn)
