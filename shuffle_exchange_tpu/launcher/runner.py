"""Multi-host launcher CLI.

Capability parity with the reference's ``deepspeed`` runner
(``launcher/runner.py:48,409``, SURVEY.md §1 CLI layer): hostfile parsing
("host slots=N"), ``--include``/``--exclude`` node filters,
``--num_nodes``/``--num_gpus``, master addr/port selection, per-job env
propagation (``.sxt_env``, the ``.deepspeed_env`` analog), elastic restart
(``--elastic_training`` → supervised relaunch), and per-node process
launch.

TPU-native shape: instead of one process per GPU wired into
torch.distributed/NCCL, one process per *host* joins
``jax.distributed.initialize`` via COORDINATOR_ADDRESS / NUM_PROCESSES /
PROCESS_ID (consumed by ``parallel/comm.init_distributed``); each host's
process sees its local chips and the XLA runtime forms the pod. Multinode
transport is ssh command generation (pdsh-style fan-out without the pdsh
dependency).

Usage:  python -m shuffle_exchange_tpu.launcher [options] script.py [args]
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger

ENV_FILE = ".sxt_env"


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="shuffle_exchange_tpu.launcher",
                                description="Multi-host launcher (reference `deepspeed` runner parity)")
    p.add_argument("-H", "--hostfile", default="/job/hostfile",
                   help="path to a hostfile: lines of '<host> slots=<n>'")
    p.add_argument("-i", "--include", default="",
                   help="host filter, e.g. 'worker-0@worker-1' or 'worker-0:0,1'")
    p.add_argument("-e", "--exclude", default="", help="hosts to exclude")
    p.add_argument("--num_nodes", type=int, default=-1, help="use first N hosts")
    p.add_argument("--num_gpus", "--num_accelerators", type=int, default=-1, dest="num_gpus",
                   help="processes per node (TPU: usually 1 per host)")
    p.add_argument("--master_addr", default=None)
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--launcher", default="ssh", choices=["ssh", "local"],
                   help="multinode transport")
    p.add_argument("--ssh_port", type=int, default=None)
    p.add_argument("--force_multi", action="store_true")
    p.add_argument("--elastic_training", action="store_true",
                   help="restart the job on failure (reference DSElasticAgent)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--env", action="append", default=[],
                   help="extra KEY=VALUE env entries to propagate")
    p.add_argument("user_script", help="training script to launch")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def parse_hostfile(path_or_lines) -> Dict[str, int]:
    """'host slots=N' lines -> ordered {host: slots} (reference
    launcher/runner.py hostfile format)."""
    if isinstance(path_or_lines, str):
        if not os.path.isfile(path_or_lines):
            return {}
        with open(path_or_lines) as f:
            lines = f.readlines()
    else:
        lines = list(path_or_lines)
    out: Dict[str, int] = {}
    for line in lines:
        line = line.split("#")[0].strip()
        if not line:
            continue
        parts = line.split()
        host = parts[0]
        slots = 1
        for tok in parts[1:]:
            if tok.startswith("slots="):
                slots = int(tok.split("=", 1)[1])
        if host in out:
            raise ValueError(f"Duplicate host {host!r} in hostfile")
        out[host] = slots
    return out


def filter_hosts(hosts: Dict[str, int], include: str = "", exclude: str = "",
                 num_nodes: int = -1) -> Dict[str, int]:
    """Apply --include/--exclude ('h1@h2' separated) and --num_nodes."""
    def names(spec: str) -> List[str]:
        return [s.split(":")[0] for s in spec.split("@") if s]

    out = dict(hosts)
    if include:
        keep = names(include)
        missing = [h for h in keep if h not in out]
        if missing:
            raise ValueError(f"--include hosts not in hostfile: {missing}")
        out = {h: out[h] for h in keep}
    for h in names(exclude):
        out.pop(h, None)
    if num_nodes > 0:
        out = dict(list(out.items())[:num_nodes])
    if not out:
        raise ValueError("No hosts left after include/exclude filtering")
    return out


def collect_env(extra: List[str]) -> Dict[str, str]:
    """Env to propagate: .sxt_env file (reference .deepspeed_env) + --env."""
    env: Dict[str, str] = {}
    for candidate in (os.path.join(os.path.expanduser("~"), ENV_FILE), ENV_FILE):
        if os.path.isfile(candidate):
            with open(candidate) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#") and "=" in line:
                        k, v = line.split("=", 1)
                        env[k] = v
    for kv in extra:
        if "=" not in kv:
            raise ValueError(f"--env expects KEY=VALUE, got {kv!r}")
        k, v = kv.split("=", 1)
        env[k] = v
    return env


def build_commands(hosts: Dict[str, int], args, extra_env: Optional[Dict[str, str]] = None
                   ) -> List[Tuple[str, List[str]]]:
    """[(host, argv)] — one launch command per host. PROCESS_ID is the host
    index; NUM_PROCESSES the host count (jax.distributed convention)."""
    host_list = list(hosts)
    master = args.master_addr or host_list[0]
    coordinator = f"{master}:{args.master_port}"
    cmds = []
    env = {"COORDINATOR_ADDRESS": coordinator, "NUM_PROCESSES": str(len(host_list))}
    env.update(extra_env or {})
    for idx, host in enumerate(host_list):
        cmd_env = dict(env, PROCESS_ID=str(idx))
        envs = [f"{k}={shlex.quote(v)}" for k, v in cmd_env.items()]
        inner = ["env"] + envs + [sys.executable, args.user_script] + list(args.user_args)
        if len(host_list) == 1 and not args.force_multi:
            cmds.append((host, inner))
        else:
            ssh = ["ssh"] + (["-p", str(args.ssh_port)] if args.ssh_port else []) + [host]
            cmds.append((host, ssh + [" ".join(shlex.quote(c) if i > 0 else c
                                               for i, c in enumerate(inner))]))
    return cmds


def run_commands(cmds: List[Tuple[str, List[str]]]) -> int:
    """Launch every per-host command; wait; first nonzero exit wins."""
    procs = [(host, subprocess.Popen(argv)) for host, argv in cmds]
    code = 0
    for host, proc in procs:
        rc = proc.wait()
        if rc != 0 and code == 0:
            logger.error(f"host {host} exited with {rc}")
            code = rc
    return code


def main(argv=None) -> int:
    args = parse_args(argv)
    hosts = parse_hostfile(args.hostfile)
    if not hosts:
        hosts = {"localhost": max(args.num_gpus, 1)}
    hosts = filter_hosts(hosts, args.include, args.exclude, args.num_nodes)
    env = collect_env(args.env)

    attempts = args.max_restarts + 1 if args.elastic_training else 1
    code = 0
    for attempt in range(attempts):
        if attempt:
            logger.warning(f"elastic restart {attempt}/{args.max_restarts}")
            time.sleep(min(10.0, 2.0 ** attempt))
        cmds = build_commands(hosts, args, env)
        for host, argv_ in cmds:
            logger.info(f"launch [{host}]: {' '.join(map(str, argv_))}")
        code = run_commands(cmds)
        if code == 0:
            break
    return code


if __name__ == "__main__":
    sys.exit(main())
