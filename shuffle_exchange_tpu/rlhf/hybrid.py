"""HybridEngine v2: one process flipping between training and serving.

Reference: ``DeepSpeedHybridEngine`` (SURVEY §2.3, ``runtime/
hybrid_engine.py:30``, 577 LoC) — train+generate in one engine, inference
containers swapped in during ``generate()``, ZeRO-3 params gathered and
LoRA fused/unfused around the rollout, per-phase latencies metered.

v2 collapse: the training half is the full ZeRO :class:`runtime.engine.
Engine` (host-offload tier included) and the serving half is the PAGED
fleet — a :class:`serving.router.ReplicaRouter` of ``InferenceEngineV2`` +
``ContinuousBatchingScheduler`` replicas — so every serving-perf lever the
repo built (continuous batching, prefix-cached quantized paged KV,
speculative drafters, placement/drain) is live for rollout generation.
Shared-prompt rollout batches are the prefix cache's best case, and
speculative drafters amortize the decode steps the reference pays one by
one. The flip itself is ``WeightPublisher``: one jitted gather (ZeRO-3
allgather + LoRA fuse + host-offload join) and a two-phase fleet publish
that never tears down KV pools or compiled programs — a warmed fleet
stays zero-recompile across any number of flips.

Every rollout is recorded ``(prompt, sampled tokens, weight_version,
sampling)`` in a :class:`rlhf.loop.ReplayLog`; greedy scheduling is
deterministic and sampled scheduling is seeded (the fused in-dispatch
Gumbel chain is a pure function of seed and position), so the replay is
bit-exact at the recorded version either way (the drain-replay
discipline applied to RLHF debugging).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..monitor.monitor import InMemoryMonitor, Monitor
from ..utils.logging import log_dist
from .loop import ReplayLog, RolloutRecord
from .publish import WeightPublisher


def _serving_dtype(engine) -> str:
    if engine.bfloat16_enabled:
        return "bfloat16"
    if engine.fp16_enabled:
        return "float16"
    return "float32"


def _auto_block_size(max_seq_len: int) -> int:
    """Largest power-of-two KV block <= 64 dividing max_seq_len (tiny test
    models have short sequences; production configs override)."""
    bs = 64
    while bs > 1 and max_seq_len % bs:
        bs //= 2
    return bs


class HybridEngineV2:
    """Owns one training :class:`Engine` and one serving fleet; flips
    between them sharing a single weight-layout contract.

    ``engine``: the training engine (from ``sxt.initialize``). ``model``:
    the model-zoo Transformer both halves run. ``inference_config``:
    overrides for the fleet's :class:`InferenceConfig` (merged over the
    ``hybrid_engine.inference_config`` config section). ``n_replicas``:
    fleet width (default: ``hybrid_engine.num_replicas`` or 1).

    The fleet is built lazily at the first generate (the reference swaps
    containers in lazily too) from a fresh gather; later flips go through
    ``publish_weights`` — stage on every replica, then commit, zero
    recompiles, KV pools intact. ``release_inference_cache`` (reference
    flag) drops the whole fleet on ``train()`` so HBM returns to training
    between rollout phases."""

    def __init__(self, engine, model, inference_config: Optional[dict] = None,
                 n_replicas: Optional[int] = None,
                 monitor: Optional[Monitor] = None,
                 drafter_factory=None,
                 replay_log: Optional[ReplayLog] = None,
                 clock=time.perf_counter):
        if not hasattr(model, "head"):
            raise TypeError("HybridEngineV2 needs a model-zoo Transformer "
                            "(rollouts drive its serving path)")
        self.engine = engine
        self.model = model
        self.clock = clock
        hcfg: Dict[str, Any] = dict(engine.config.hybrid_engine or {})
        self._hcfg = hcfg
        self._release_cache = bool(hcfg.get("release_inference_cache", False))
        self._icfg_overrides = dict(hcfg.get("inference_config", {}) or {})
        self._icfg_overrides.update(inference_config or {})
        self.n_replicas = int(n_replicas if n_replicas is not None
                              else hcfg.get("num_replicas", 1))
        if self.n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {self.n_replicas}")
        self.drafter_factory = drafter_factory
        self.memory_monitor = InMemoryMonitor(maxlen=2048)
        self._sinks: List[Monitor] = [monitor] if monitor is not None else []
        self.publisher = WeightPublisher(engine, monitor=self._tap(),
                                         clock=clock)
        self.replay_log = replay_log if replay_log is not None else ReplayLog()
        self._training = True
        self._lora_fused = False
        self._router = None
        self._icfg_cache = None
        self._published_at = None      # (global_steps, micro_steps) watermark
        self._version: Optional[int] = None
        # meters (reference _generate_latency/_training_latency parity,
        # same keys as the v1 wrapper's latency_report)
        self.generate_calls = 0
        self.generate_tokens = 0
        self.generate_latency_s = 0.0
        self.training_latency_s = 0.0
        self.training_iters = 0
        self.flips_to_serve = 0
        self.flips_to_train = 0
        self.lora_fuses = 0
        self.lora_unfuses = 0

    # -- plumbing ------------------------------------------------------

    def _tap(self) -> Monitor:
        hybrid = self

        class _Tap(InMemoryMonitor):
            def write_events(self, event_list):
                hybrid._emit(event_list)

        return _Tap(maxlen=1)

    def _emit(self, events) -> None:
        self.memory_monitor.write_events(events)
        for s in self._sinks:
            s.write_events(events)

    def __getattr__(self, name):
        # full training-engine API delegation (train_batch/forward are
        # wrapped below; everything else — checkpointing, lr, zero —
        # passes through). The "engine" guard keeps a half-constructed
        # instance from recursing.
        if name == "engine":
            raise AttributeError(name)
        return getattr(self.engine, name)

    @property
    def gather_latency_s(self) -> float:
        return self.publisher.gather_latency_s

    @property
    def weight_version(self) -> Optional[int]:
        """The fleet's published weight version (None before the first
        fleet build)."""
        return self._version

    # -- serving fleet -------------------------------------------------

    def _inference_config(self):
        if self._icfg_cache is not None:
            return self._icfg_cache
        from ..inference.config import InferenceConfig

        mcfg = self.model.config
        S = int(self._icfg_overrides.get("max_seq_len", mcfg.max_seq_len))
        bs = int(self._icfg_overrides.get("kv_block_size",
                                          _auto_block_size(S)))
        max_running = int((self._icfg_overrides.get("serving") or {})
                          .get("max_running", 8))
        kw: Dict[str, Any] = {
            "dtype": _serving_dtype(self.engine),
            "max_seq_len": S,
            "max_new_tokens": int(self._hcfg.get("max_out_tokens", 256)),
            "tensor_parallel": int(self._hcfg.get("inference_tp_size", 1)),
            "kv_block_size": bs,
            # default pool: every running sequence at full length, plus
            # scratch + headroom
            "num_kv_blocks": max_running * max(1, S // bs) + 8,
        }
        kw.update(self._icfg_overrides)
        self._icfg_cache = InferenceConfig.from_dict(kw)
        return self._icfg_cache

    @property
    def router(self):
        """The serving fleet, built lazily from a fresh gather. Replicas
        share the published weights but own their KV pools, schedulers,
        and drafters (the PR 7 fleet contract)."""
        if self._router is None:
            from ..inference.engine_v2 import InferenceEngineV2
            from ..serving.router import ReplicaRouter

            icfg = self._inference_config()
            weights = self.publisher.gather()
            version = int(self.engine.global_steps)
            engines = []
            for _ in range(self.n_replicas):
                eng = InferenceEngineV2(self.model, weights, icfg)
                eng.weight_version = version
                engines.append(eng)
            self._router = ReplicaRouter(engines,
                                         drafter_factory=self.drafter_factory)
            self._published_at = (self.engine.global_steps,
                                  self.engine.micro_steps)
            self._version = version
            self.publisher.last_version = version
            self._emit([("flip/fleet_builds", 1, self.flips_to_serve),
                        ("flip/weight_version", version,
                         self.flips_to_serve)])
        return self._router

    def publish_weights(self, force: bool = False) -> int:
        """Flip train->serve: gather the CURRENT training weights (ZeRO-3
        allgather, LoRA fuse, host-offload join — one jitted program) and
        deliver them to every replica, two-phase, without tearing down
        paged KV or compiled programs. No-op when no optimizer step ran
        since the last publish (the v1 freshness contract). Returns the
        fleet's weight version."""
        fresh_at = (self.engine.global_steps, self.engine.micro_steps)
        if self._router is None:
            _ = self.router            # first build IS the publish
            return self._version
        if self._published_at == fresh_at and not force:
            return self._version
        t0 = self.clock()
        version = self.publisher.publish(self._router)
        self._published_at = fresh_at
        self._version = version
        self._emit([("flip/publish_s", self.clock() - t0,
                     self.flips_to_serve),
                    ("flip/weight_version", version, self.flips_to_serve)])
        return version

    # -- mode flips (reference module.eval()/train() container swap) ----

    def eval(self):
        """Enter generation mode. LoRA is fused for the serving side
        (reference ``fuse_lora``-before-generate; see :meth:`fuse_lora`
        for why the fuse costs nothing extra here). The weight publish
        itself stays lazy — it happens at the next generate, so a
        train->eval->train bounce without rollouts never pays a gather."""
        if self._training:
            self.fuse_lora()
            self._training = False
            self.flips_to_serve += 1
            self._emit([("flip/to_serve", self.flips_to_serve,
                         self.flips_to_serve)])
        return self

    def train(self, mode: bool = True):
        """Back to training mode. With ``release_inference_cache`` the
        whole fleet (compiled programs + KV pools) is dropped so HBM
        returns to training between rollout phases (the reference flag's
        semantics); without it the warmed fleet persists for the next
        flip — the zero-recompile fast path."""
        if mode and not self._training:
            self.unfuse_lora()
            self.flips_to_train += 1
            self._emit([("flip/to_train", self.flips_to_train,
                         self.flips_to_train)])
            if self._release_cache:
                self._router = None
                self._published_at = None
        self._training = bool(mode)
        return self

    @property
    def in_training_mode(self) -> bool:
        return self._training

    def fuse_lora(self) -> None:
        """Reference-parity seam (SURVEY §2.3 ``fuse_lora``): the
        reference materializes base + B@A into the live weights before
        generation and subtracts it back after. Here the fuse lives
        INSIDE the jitted gather — ``module_weights`` materializes the
        fused model-structured tree without ever mutating training state
        — so the marker flips bookkeeping and meters the call, and the
        training tree needs no unfuse-subtraction (bit-exact by
        construction, not by inverse arithmetic)."""
        if not self._lora_fused:
            self._lora_fused = True
            self.lora_fuses += 1
            self._emit([("flip/lora_fuse", self.lora_fuses,
                         self.lora_fuses)])

    def unfuse_lora(self) -> None:
        """Inverse marker (reference ``unfuse_lora``): a no-op on the
        training tree — the gather never mutated it — kept for call-site
        parity and metering."""
        if self._lora_fused:
            self._lora_fused = False
            self.lora_unfuses += 1
            self._emit([("flip/lora_unfuse", self.lora_unfuses,
                         self.lora_unfuses)])

    # -- training side -------------------------------------------------

    def train_batch(self, *args, **kwargs):
        t0 = self.clock()
        out = self.engine.train_batch(*args, **kwargs)
        self.training_latency_s += self.clock() - t0
        self.training_iters += 1
        return out

    def forward(self, batch, **kwargs):
        """Training mode: engine loss forward. Eval mode: full-sequence
        logits from replica 0's serving engine (the reference's
        swapped-container forward)."""
        if self._training:
            return self.engine.forward(batch, **kwargs)
        self.publish_weights()
        ids = batch["input_ids"] if isinstance(batch, dict) else batch
        return self.router.replicas[0].engine.forward(ids)

    # -- rollouts (the serving fast path) ------------------------------

    @staticmethod
    def _normalize_prompts(prompts, prompt_lengths=None) -> List[List[int]]:
        if isinstance(prompts, np.ndarray) or (
                prompts and isinstance(prompts[0], np.ndarray)):
            ids = np.asarray(prompts)
            if ids.ndim != 2:
                raise ValueError(f"prompt array must be [B, T], got "
                                 f"{ids.shape}")
            B, T = ids.shape
            if prompt_lengths is None:
                prompt_lengths = [T] * B
            return [[int(t) for t in ids[i, :int(prompt_lengths[i])]]
                    for i in range(B)]
        if prompt_lengths is not None:
            raise ValueError("prompt_lengths only applies to a padded "
                             "[B, T] prompt array")
        return [[int(t) for t in p] for p in prompts]

    @staticmethod
    def _normalize_sampling(sampling, n: int) -> List[Optional[object]]:
        """One SamplingParams broadcast to every prompt, or a per-prompt
        sequence (None entries = greedy); length-checked."""
        from ..inference.config import SamplingParams

        if sampling is None:
            return [None] * n
        if isinstance(sampling, SamplingParams):
            return [sampling] * n
        sps = list(sampling)
        if len(sps) != n:
            raise ValueError(f"sampling sequence has {len(sps)} entries "
                             f"for {n} prompts")
        for sp in sps:
            if sp is not None and not isinstance(sp, SamplingParams):
                raise TypeError(f"sampling entries must be SamplingParams "
                                f"or None, got {type(sp).__name__}")
        return sps

    def rollout(self, prompts, max_new_tokens: Optional[int] = None,
                prompt_lengths=None, session_ids=None,
                record: bool = True, sampling=None) -> List[RolloutRecord]:
        """Generate rollouts with the CURRENT training weights through the
        scheduler-driven fleet (continuous batching; shared-prompt batches
        hit the prefix cache, speculative drafters ride the serving
        config). Publishes first if an optimizer step ran since the last
        flip. ``sampling`` is one :class:`SamplingParams` for every
        prompt or a per-prompt sequence (None = greedy); the request's
        ``to_wire()`` dict (seed included) rides each record so sampled
        rollouts replay bit-exactly. Every rollout is recorded
        ``(prompt, tokens, weight_version, sampling)`` in the replay log
        (``record=False`` skips the log, not the metering). Returns the
        records in submission order."""
        t0 = self.clock()
        version = self.publish_weights()
        plist = self._normalize_prompts(prompts, prompt_lengths)
        sps = self._normalize_sampling(sampling, len(plist))
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self._inference_config().max_new_tokens)
        out = self.router.serve(plist, max_new_tokens=max_new,
                                session_ids=session_ids, sampling=sps)

        def served_version(uid):
            # honest stamping (ISSUE 20): under async sync a replica may
            # answer from a version behind the newest publish — record
            # the version its scheduler stamped at finish, not the one
            # the trainer just minted. Barrier fleets stamp identically.
            r = self.router.requests.get(uid)
            if r is not None and r.weight_version is not None:
                return int(r.weight_version)
            return version

        records = [RolloutRecord(prompt=p, tokens=list(toks),
                                 weight_version=served_version(uid),
                                 uid=uid,
                                 sampling=None if sp is None
                                 else sp.to_wire())
                   for (uid, toks), p, sp in zip(out.items(), plist, sps)]
        if record:
            self.replay_log.extend(records)
        dt = self.clock() - t0
        self.generate_latency_s += dt
        self.generate_calls += 1
        self.generate_tokens += sum(len(r.tokens) for r in records)
        self._emit([("flip/generate_s", dt, self.generate_calls),
                    ("flip/rollout_tokens", self.generate_tokens,
                     self.generate_calls)])
        return records

    def _generate_seed(self, seed, rng) -> int:
        """Base seed for a generate() call: explicit ``seed`` wins, then
        a value drawn from ``rng`` (numpy Generator/RandomState or a JAX
        PRNG key), then the serving config's ``sampling.seed``."""
        if seed is not None:
            return int(seed)
        if rng is not None:
            if hasattr(rng, "integers"):          # np.random.Generator
                return int(rng.integers(0, 2**31 - 1))
            if hasattr(rng, "randint"):           # np.random.RandomState
                return int(rng.randint(0, 2**31 - 1))
            import jax

            return int(np.asarray(
                jax.random.randint(rng, (), 0, 2**31 - 1)))
        return int(self._inference_config().sampling.seed)

    def generate(self, input_ids, prompt_lengths=None,
                 max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 eos_token_id: Optional[int] = None,
                 seed: Optional[int] = None, stop=None, rng=None, **kwargs):
        """v1-shaped rollout API: right-padded int32 [B, T] prompts in,
        int32 [B, max_new_tokens] tokens out — served by the fleet
        scheduler instead of the v1 whole-batch generate loop.

        The v1 sampling kwargs map onto per-request
        :class:`SamplingParams` (ISSUE 16's fused in-dispatch sampler):
        ``temperature``/``top_k``/``top_p`` shape the distribution,
        ``eos_token_id``/``stop`` enable early termination, and each row
        ``i`` samples under seed ``base + i`` (``base`` = explicit
        ``seed``, else drawn from ``rng``, else the serving config's
        ``sampling.seed``) so the whole batch replays bit-exactly from
        the recorded per-row seeds. Rows that stop early are right-padded
        with ``eos_token_id`` (0 when no EOS is set) to keep the fixed
        [B, max_new_tokens] shape."""
        if kwargs:
            raise TypeError(f"HybridEngineV2.generate: unsupported kwargs "
                            f"{sorted(kwargs)}")
        from ..inference.config import SamplingParams

        temp = float(temperature) if temperature is not None else 0.0
        tk = int(top_k) if top_k is not None else 0
        tp = float(top_p) if top_p is not None else 1.0
        eos = int(eos_token_id) if eos_token_id is not None else -1
        stops = tuple(tuple(int(t) for t in s) for s in (stop or ()))
        plist = self._normalize_prompts(input_ids, prompt_lengths)
        sampled = (temp > 0.0 or tk > 0 or tp < 1.0 or eos >= 0 or stops
                   or seed is not None or rng is not None)
        sps = None
        if sampled:
            base = self._generate_seed(seed, rng)
            sps = [SamplingParams(temperature=temp, top_k=tk, top_p=tp,
                                  seed=base + i, eos_token_id=eos,
                                  stop=stops)
                   for i in range(len(plist))]
        records = self.rollout(plist, max_new_tokens=max_new_tokens,
                               sampling=sps)
        width = int(max_new_tokens if max_new_tokens is not None
                    else self._inference_config().max_new_tokens)
        pad = eos if eos >= 0 else 0
        return np.asarray([list(r.tokens) + [pad] * (width - len(r.tokens))
                           for r in records], dtype=np.int32)

    def replay(self, rec: RolloutRecord) -> List[int]:
        """Bit-exact replay of a recorded rollout: re-serve its prompt at
        the SAME weight version under the record's ``sampling`` wire dict
        (None = greedy) and return the tokens (the drain-replay
        discipline — greedy scheduling is deterministic and the sampled
        chain is a pure function of the recorded seed and position, so
        the replay reproduces the recording token for token). Refuses
        when the fleet has moved past the record's version — replaying
        old rollouts on new weights would silently "reproduce" different
        tokens."""
        version = self.publish_weights() if self._router is None \
            else self._version
        if rec.weight_version != version:
            raise RuntimeError(
                f"cannot replay rollout recorded at weight version "
                f"{rec.weight_version}: the fleet serves version {version} "
                "(replay before training past the recording, or keep a "
                "checkpoint of that version)")
        sp = None
        if rec.sampling is not None:
            from ..inference.config import SamplingParams

            sp = SamplingParams.from_wire(rec.sampling)
        out = self.router.serve([rec.prompt],
                                max_new_tokens=max(1, len(rec.tokens)),
                                sampling=sp)
        return next(iter(out.values()))

    # -- meters --------------------------------------------------------

    def latency_report(self) -> Dict[str, float]:
        """Aggregate meters (reference prints per-phase latencies); the
        v1 wrapper's keys plus the flip counters."""
        return {
            "generate_calls": self.generate_calls,
            "generate_tokens": self.generate_tokens,
            "generate_latency_s": round(self.generate_latency_s, 4),
            "gather_latency_s": round(self.gather_latency_s, 4),
            "tokens_per_sec": round(
                self.generate_tokens / self.generate_latency_s, 2)
            if self.generate_latency_s else 0.0,
            "training_iters": self.training_iters,
            "training_latency_s": round(self.training_latency_s, 4),
            "publishes": self.publisher.publishes,
            "publish_latency_s": round(self.publisher.publish_latency_s, 4),
            "weight_version": self._version,
            "flips_to_serve": self.flips_to_serve,
            "flips_to_train": self.flips_to_train,
            "rollouts_logged": len(self.replay_log),
        }

    def log_latency(self) -> None:
        log_dist(f"hybrid engine v2: {self.latency_report()}", ranks=[0])

    def fleet_stats(self) -> Dict[str, object]:
        """The router's fleet summary (None before the first rollout)."""
        return self._router.stats() if self._router is not None else None
