"""RLHF subsystem (ISSUE 11): one process flipping between the ZeRO
training engine and the paged serving fleet, sharing one weight-layout
contract.

Reference surface: ``DeepSpeedHybridEngine`` (SURVEY §2.3). The pieces:

- ``publish.py`` — the train->serve weight flip: ``WeightPublisher``
  (jitted ZeRO-3 gather + LoRA fuse + host-offload join, versioned,
  metered) delivering through ``InferenceEngineV2.publish_weights`` or
  the router's two-phase fleet publish, and ``WeightWire`` for
  cross-process delivery over the disagg pinned-staging substrate.
- ``hybrid.py`` — ``HybridEngineV2``: owns one training ``Engine`` and
  one ``ReplicaRouter`` fleet; eval/train mode flips with LoRA
  fuse/unfuse parity, scheduler-driven rollouts (prefix cache +
  speculative drafters live), flip/* meters through the monitor.
- ``loop.py`` — the generate->score->train driver: ``RolloutRecord`` /
  ``ReplayLog`` (token-identical replay at the recorded weight version),
  ``pg_loss_fn`` / ``dpo_loss_fn`` over the existing jitted train step,
  and ``RLHFLoop`` tying them together.

``runtime/hybrid_engine.py``'s v1 ``HybridEngine`` is a deprecation shim
over ``HybridEngineV2``.
"""

from .hybrid import HybridEngineV2
from .loop import (ReplayLog, RLHFLoop, RolloutRecord, dpo_loss_fn,
                   pg_loss_fn, sequence_logprob)
from .publish import WeightPublisher, WeightWire, publish_over_wire

__all__ = [
    "HybridEngineV2",
    "ReplayLog",
    "RLHFLoop",
    "RolloutRecord",
    "dpo_loss_fn",
    "pg_loss_fn",
    "sequence_logprob",
    "WeightPublisher",
    "WeightWire",
    "publish_over_wire",
]
