"""The generate->score->train driver: rollout records, replay log, losses.

The workload class HybridEngine v2 exists for (ROADMAP item 2): RLHF-style
loops where one process alternates between fleet-served rollout generation
and ZeRO training steps on the same weights. Two concrete trainers ride
the EXISTING jitted train step (the engine's ``train_batch`` machinery is
reused verbatim — only the loss function differs, passed to
``sxt.initialize(model=..., loss_fn=...)``):

- :func:`pg_loss_fn` — reward-weighted policy gradient: maximize the
  log-probability of sampled rollout tokens weighted by their
  (advantage-normalized) reward. Online distillation is this loss with
  the teacher's preference as the reward — including distilling the
  draft models the speculative decoder wants (ROADMAP item 1).
- :func:`dpo_loss_fn` — Direct Preference Optimization over
  (chosen, rejected) pairs, with the frozen reference policy's sequence
  log-probs precomputed OUTSIDE the step (the reference policy never
  trains, so its term is data, not graph).

Replay discipline: every rollout is a :class:`RolloutRecord`
``(prompt, sampled tokens, weight_version, sampling)`` in a
:class:`ReplayLog`. Greedy fleet scheduling is deterministic, and
sampled scheduling is seeded (ISSUE 16's per-request Gumbel chain is a
pure function of ``(seed, position, distribution)``), so any record can
be replayed bit-exactly at its recorded weight version
(``HybridEngineV2.replay`` / ``ReplayLog.verify``) — the same
token-identical contract the serving drain/requeue path keeps, applied
to RLHF debugging ("which weights sampled this token, and can I
reproduce it?").
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class RolloutRecord:
    """One rollout: the prompt, what the policy sampled, and the exact
    weight version it sampled under. ``reward`` is filled by the scorer;
    ``uid`` is the fleet uid that served it (debugging breadcrumb).
    ``sampling`` is the request's ``SamplingParams.to_wire()`` dict
    (None = greedy) — together with ``weight_version`` it is everything
    replay needs to reproduce a SAMPLED chain bit-exactly, because the
    seed rides in the wire dict and the engine's per-token Gumbel noise
    is keyed only on ``(seed, absolute position)``."""

    prompt: List[int]
    tokens: List[int]
    weight_version: int
    reward: Optional[float] = None
    uid: Optional[int] = None
    sampling: Optional[dict] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "RolloutRecord":
        return cls(**{k: d.get(k) for k in
                      ("prompt", "tokens", "weight_version", "reward",
                       "uid", "sampling")})


class ReplayLog:
    """Append-only token-identical replay log (JSONL-serializable).

    ``verify(hybrid)`` replays every record at the fleet's CURRENT weight
    version and asserts bit-exact token equality — sampled records
    replay under their recorded ``sampling`` wire dict (seed included),
    so stochastic rollouts verify exactly like greedy ones; records from
    other versions are skipped (they need that version's weights), so
    the return value distinguishes verified from unverifiable."""

    def __init__(self, records: Optional[Sequence[RolloutRecord]] = None):
        self.records: List[RolloutRecord] = list(records or [])

    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def append(self, rec: RolloutRecord) -> None:
        self.records.append(rec)

    def extend(self, recs: Sequence[RolloutRecord]) -> None:
        self.records.extend(recs)

    def at_version(self, version: int) -> List[RolloutRecord]:
        return [r for r in self.records if r.weight_version == version]

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for r in self.records:
                f.write(json.dumps(r.to_json()) + "\n")

    @classmethod
    def load(cls, path: str) -> "ReplayLog":
        out = cls()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(RolloutRecord.from_json(json.loads(line)))
        return out

    def verify(self, hybrid, records: Optional[Sequence[RolloutRecord]] = None
               ) -> Tuple[int, int]:
        """Replay each record through the fleet and require bit-exact
        tokens. Returns ``(verified, skipped)``; raises on the first
        divergence, naming the record."""
        verified = skipped = 0
        for rec in (self.records if records is None else records):
            if rec.weight_version != hybrid.weight_version:
                skipped += 1
                continue
            got = hybrid.replay(rec)
            if got != rec.tokens:
                raise AssertionError(
                    f"replay diverged for uid {rec.uid} at weight version "
                    f"{rec.weight_version}: recorded {rec.tokens}, "
                    f"replayed {got}")
            verified += 1
        return verified, skipped


# -- losses over the existing train step ------------------------------


def pg_loss_fn(model) -> Callable:
    """Reward-weighted policy-gradient loss for ``sxt.initialize(model=m,
    loss_fn=pg_loss_fn(m))``.

    Batch: ``{"input_ids": [B, T] int32 (prompt + rollout, right-padded),
    "weights": [B, T] float32}`` — ``weights[b, j]`` is the (normalized)
    advantage for the token at absolute position ``j`` and 0 on prompt /
    pad positions, so the loss scores exactly the sampled tokens:
    ``-(sum_j w_j * log p(ids_j | ids_<j)) / count(w != 0)``."""

    def loss_fn(params, batch, rng=None):
        import jax
        import jax.numpy as jnp

        ids = batch["input_ids"]
        w = batch["weights"].astype(jnp.float32)
        logits = model.apply(params, ids[:, :-1])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tgt = ids[:, 1:]
        lp = jnp.take_along_axis(logp, tgt[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
        wt = w[:, 1:]
        denom = jnp.maximum(jnp.sum(wt != 0), 1)
        return -(lp * wt).sum() / denom

    return loss_fn


def dpo_loss_fn(model, beta: float = 0.1) -> Callable:
    """Direct Preference Optimization loss for ``sxt.initialize``.

    Batch: ``{"chosen_ids"/"rejected_ids": [B, T] int32,
    "chosen_mask"/"rejected_mask": [B, T] float32 (1 on completion
    tokens), "ref_chosen_lp"/"ref_rejected_lp": [B] float32}`` — the
    reference policy's sequence log-probs are precomputed data
    (:meth:`RLHFLoop.dpo_batch` computes them with the frozen snapshot),
    so the jitted step only runs the live policy:
    ``-mean log sigmoid(beta * ((lp_c - ref_c) - (lp_r - ref_r)))``."""

    def seq_lp(params, ids, mask):
        import jax
        import jax.numpy as jnp

        logits = model.apply(params, ids[:, :-1])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lp = jnp.take_along_axis(logp, ids[:, 1:, None].astype(jnp.int32),
                                 axis=-1)[..., 0]
        return (lp * mask[:, 1:].astype(jnp.float32)).sum(axis=-1)

    def loss_fn(params, batch, rng=None):
        import jax
        import jax.numpy as jnp

        lc = seq_lp(params, batch["chosen_ids"], batch["chosen_mask"])
        lr = seq_lp(params, batch["rejected_ids"], batch["rejected_mask"])
        margin = (lc - batch["ref_chosen_lp"]) - (lr - batch["ref_rejected_lp"])
        return -jnp.mean(jax.nn.log_sigmoid(jnp.float32(beta) * margin))

    return loss_fn


def sequence_logprob(logits: np.ndarray, ids: np.ndarray,
                     mask: np.ndarray) -> np.ndarray:
    """Host-side masked sequence log-prob from full-sequence logits —
    the scoring path (ref policy / reward models), not the train step.
    ``logits`` [B, T, V] for inputs ``ids[:, :-1]`` is the usual shifted
    layout handled here: pass logits for the FULL ids and the first
    position is simply never scored (mask[:, 0] is ignored)."""
    logits = np.asarray(logits, np.float64)
    x = logits[:, :-1]
    x = x - x.max(axis=-1, keepdims=True)
    logp = x - np.log(np.exp(x).sum(axis=-1, keepdims=True))
    tgt = np.asarray(ids)[:, 1:]
    lp = np.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return (lp * np.asarray(mask, np.float64)[:, 1:]).sum(axis=-1)


class RLHFLoop:
    """generate -> score -> train, end to end.

    ``hybrid`` is a :class:`HybridEngineV2` whose training engine was
    built with :func:`pg_loss_fn` (``mode="pg"``) or :func:`dpo_loss_fn`
    (``mode="dpo"``). ``reward_fn(prompt, tokens) -> float`` scores
    rollouts for the PG path. The loop owns the batch construction (token
    layouts the losses expect) and feeds the engine's EXISTING jitted
    train step through ``hybrid.train_batch``; padding is fixed at
    ``seq_len`` so every step hits the same compiled program."""

    def __init__(self, hybrid,
                 reward_fn: Optional[Callable[[List[int], List[int]],
                                              float]] = None,
                 seq_len: Optional[int] = None,
                 normalize_advantages: bool = True):
        self.hybrid = hybrid
        self.reward_fn = reward_fn
        self.seq_len = int(seq_len if seq_len is not None
                           else hybrid.model.config.max_seq_len)
        self.normalize_advantages = normalize_advantages
        self.log = hybrid.replay_log
        self._ref = None     # frozen DPO reference, snapshotted lazily

    # -- generate + score ----------------------------------------------

    def rollout(self, prompts, max_new_tokens: int = 16
                ) -> List[RolloutRecord]:
        """Flip to serve, generate through the fleet, score. The records
        land in the hybrid's replay log with their weight version."""
        self.hybrid.eval()
        records = self.hybrid.rollout(prompts,
                                      max_new_tokens=max_new_tokens)
        if self.reward_fn is not None:
            for r in records:
                r.reward = float(self.reward_fn(r.prompt, r.tokens))
        return records

    # -- PG path --------------------------------------------------------

    def pg_batch(self, records: Sequence[RolloutRecord]) -> Dict[str, np.ndarray]:
        """``{"input_ids", "weights"}`` for :func:`pg_loss_fn`: rollouts
        right-padded to ``seq_len``, advantages = rewards normalized
        across the batch (mean 0, unit variance when it exists), written
        at the sampled tokens' absolute positions."""
        B, T = len(records), self.seq_len
        rewards = np.asarray([r.reward or 0.0 for r in records], np.float64)
        adv = rewards - rewards.mean()
        if self.normalize_advantages and adv.std() > 1e-8:
            adv = adv / adv.std()
        ids = np.zeros((B, T), np.int32)
        w = np.zeros((B, T), np.float32)
        for i, r in enumerate(records):
            seq = (list(r.prompt) + list(r.tokens))[:T]
            ids[i, :len(seq)] = seq
            lo = min(len(r.prompt), T)
            hi = min(len(seq), T)
            w[i, lo:hi] = adv[i]
        return {"input_ids": ids, "weights": w}

    def pg_step(self, records: Sequence[RolloutRecord]) -> float:
        """One reward-weighted policy-gradient optimizer step over
        ``records`` through the engine's jitted train step."""
        self.hybrid.train()
        return float(self.hybrid.train_batch(self.pg_batch(records)))

    # -- DPO path -------------------------------------------------------

    def _ref_logits(self, ids: np.ndarray) -> np.ndarray:
        """Full-sequence logits from the FROZEN reference policy — a
        snapshot of the weights at the loop's first DPO batch (the
        reference never trains; DPO's KL anchor)."""
        if self._ref is None:
            from ..inference.config import InferenceConfig
            from ..inference.engine import InferenceEngine

            self._ref = InferenceEngine(
                self.hybrid.model,
                self.hybrid.engine.module_weights(consensus=True),
                InferenceConfig(dtype="float32", max_seq_len=self.seq_len))
        return np.asarray(self._ref.forward(ids))

    def dpo_batch(self, pairs: Sequence[Tuple[List[int], List[int],
                                              List[int]]]
                  ) -> Dict[str, np.ndarray]:
        """``{"chosen_ids", "rejected_ids", masks, ref log-probs}`` for
        :func:`dpo_loss_fn` from ``(prompt, chosen, rejected)`` token
        triples; the frozen reference's sequence log-probs are computed
        here, outside the jitted step."""
        B, T = len(pairs), self.seq_len

        def pack(prompt, completion):
            seq = (list(prompt) + list(completion))[:T]
            row = np.zeros((T,), np.int32)
            row[:len(seq)] = seq
            m = np.zeros((T,), np.float32)
            m[min(len(prompt), T):min(len(seq), T)] = 1.0
            return row, m

        cids = np.zeros((B, T), np.int32)
        rids = np.zeros((B, T), np.int32)
        cm = np.zeros((B, T), np.float32)
        rm = np.zeros((B, T), np.float32)
        for i, (prompt, chosen, rejected) in enumerate(pairs):
            cids[i], cm[i] = pack(prompt, chosen)
            rids[i], rm[i] = pack(prompt, rejected)
        ref_c = sequence_logprob(self._ref_logits(cids), cids, cm)
        ref_r = sequence_logprob(self._ref_logits(rids), rids, rm)
        return {"chosen_ids": cids, "rejected_ids": rids,
                "chosen_mask": cm, "rejected_mask": rm,
                "ref_chosen_lp": ref_c.astype(np.float32),
                "ref_rejected_lp": ref_r.astype(np.float32)}

    def dpo_step(self, pairs) -> float:
        """One DPO optimizer step over ``(prompt, chosen, rejected)``
        triples through the engine's jitted train step."""
        self.hybrid.train()
        return float(self.hybrid.train_batch(self.dpo_batch(pairs)))

    # -- the driver -----------------------------------------------------

    def run(self, prompt_batches: Sequence[Sequence[Sequence[int]]],
            max_new_tokens: int = 16) -> Dict[str, object]:
        """generate -> score -> train over ``prompt_batches`` (each batch
        sized to the engine's ``train_batch_size``), PG mode. Returns the
        loop summary (losses, reward trajectory, weight versions)."""
        losses, mean_rewards, versions = [], [], []
        for prompts in prompt_batches:
            records = self.rollout(prompts, max_new_tokens=max_new_tokens)
            mean_rewards.append(
                float(np.mean([r.reward or 0.0 for r in records])))
            versions.append(records[0].weight_version)
            losses.append(self.pg_step(records))
        return {"steps": len(losses), "losses": losses,
                "mean_rewards": mean_rewards, "weight_versions": versions,
                "rollouts_logged": len(self.log),
                "latency": self.hybrid.latency_report()}

    def run_overlapped(self, prompt_batches: Sequence[Sequence[Sequence[int]]],
                       max_new_tokens: int = 16) -> Dict[str, object]:
        """Continuous RLHF over the async weight-sync fleet (ISSUE 20):
        rollouts, scoring, and publishes OVERLAP instead of alternating
        behind the eval()/train() flip barrier.

        The shape: batch ``i+1`` is submitted to the started fleet (its
        replica threads decode in the background) BEFORE batch ``i`` is
        scored and trained on; each optimizer step's publish is the
        async retain-and-kick (O(tree bytes) + first gossip hop), so the
        in-flight batch never stalls on a fleet-wide stage/commit —
        deliveries land at tick boundaries via the deferred staged swap.
        Records are stamped with the weight version that ACTUALLY served
        them (a replica mid-gossip answers from its previous committed
        version — stale-but-honest, bounded by the staleness window), so
        ``weight_versions`` here is a per-batch ``{version: count}``
        census rather than the serial loop's single stamp. Requires
        ``router.sync.enabled``; the serial :meth:`run` drives barrier
        fleets."""
        import time as _time

        hybrid = self.hybrid
        hybrid.eval()
        router = hybrid.router
        if getattr(router, "_async_sync", None) is None:
            raise RuntimeError(
                "run_overlapped needs the async weight-sync fleet "
                "(router.sync.enabled); use run() for barrier publishes")
        batches = [list(b) for b in prompt_batches]
        if not batches:
            return {"steps": 0, "losses": [], "mean_rewards": [],
                    "weight_versions": [], "rollouts_logged": len(self.log),
                    "latency": hybrid.latency_report()}
        # the lazy fleet build above already gathered CURRENT training
        # weights onto every replica (first build IS the publish), so the
        # first batch needs no barrier — decoding starts immediately
        router.start()

        def _submit(prompts):
            return [(list(p), router.submit(list(p),
                                            max_new_tokens=max_new_tokens))
                    for p in prompts]

        def _collect(submitted):
            uids = [u for _, u in submitted]
            while not all(router.requests[u].state in ("finished", "failed")
                          for u in uids):
                _time.sleep(0.002)
            records = []
            for p, u in submitted:
                r = router.requests[u]
                wv = (r.weight_version if r.weight_version is not None
                      else (hybrid.weight_version or 0))
                rec = RolloutRecord(prompt=p, tokens=list(r.generated),
                                    weight_version=int(wv), uid=u)
                if self.reward_fn is not None:
                    rec.reward = float(self.reward_fn(rec.prompt, rec.tokens))
                records.append(rec)
            self.log.extend(records)
            return records

        losses, mean_rewards, versions = [], [], []
        try:
            submitted = _submit(batches[0])
            for nxt in batches[1:] + [None]:
                records = _collect(submitted)
                # the NEXT batch starts decoding now — scoring, the
                # train step, and the publish below all overlap with it
                submitted = _submit(nxt) if nxt is not None else None
                mean_rewards.append(
                    float(np.mean([r.reward or 0.0 for r in records])))
                census: Dict[int, int] = {}
                for r in records:
                    census[r.weight_version] = \
                        census.get(r.weight_version, 0) + 1
                versions.append(census)
                losses.append(float(hybrid.train_batch(
                    self.pg_batch(records))))
                hybrid.publish_weights()
        finally:
            router.stop()
        return {"steps": len(losses), "losses": losses,
                "mean_rewards": mean_rewards, "weight_versions": versions,
                "rollouts_logged": len(self.log),
                "staleness": router._async_sync.staleness(),
                "latency": hybrid.latency_report()}
