"""Train->serve weight publication — the flip at the heart of HybridEngine v2.

Reference: ``DeepSpeedHybridEngine`` (SURVEY §2.3) swaps kernel-injected
inference containers in during ``generate()``, gathering ZeRO-3 shards and
fusing LoRA around the rollout. The TPU-native collapse: the training
engine's ``module_weights(consensus=True)`` is ONE jitted program that
all-gathers ZeRO-3 shards, fuses LoRA factor pairs into dense weights, and
(on the host-offload tier) joins the overlapped optimizer pipeline and
hands back its bf16 mirrors — so "swapping the containers in" is gathering
that model-structured tree and flipping each serving engine's params
pointer (``InferenceEngineV2.publish_weights`` / the router's two-phase
``publish_weights``). Paged KV pools, the block allocator, and every
compiled serving program survive the flip untouched; the prefix-cache
content registry is invalidated (its keys hash token history, not
weights).

Delivery tiers:

- **in-process** (``WeightPublisher.publish``): gather -> stage -> commit
  on an engine or a ``ReplicaRouter`` fleet (two-phase, per-replica
  atomic — the ``weight_publish`` fault site drills a crash mid-stage
  leaving the whole fleet on the old version).
- **cross-process** (``WeightWire``): the gathered tree's leaves ride the
  SAME pinned-staging substrate the disaggregated KV transfer uses
  (``ops/native/aio.PinnedBufferPool``, optional ``AsyncIOEngine`` file
  spill) — byte-exact on the wire, ``send``/``recv`` split so a real
  deployment can put a fabric between trainer and fleet.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..monitor.monitor import InMemoryMonitor, Monitor
from ..testing import sanitizer
from ..utils.invariants import locked_by, requires_lock


class WeightPublisher:
    """Gathers the training engine's current weights into the serving
    layout and delivers them to a serving target, versioned and metered.

    ``engine`` is the training :class:`runtime.engine.Engine`. ``gather()``
    runs the jitted ZeRO-gather/LoRA-fuse (``module_weights``) and blocks
    until the tree is materialized so ``gather_latency_s`` is honest — the
    analog of the reference's ZeRO-3 allgather-before-generate latency
    meter. ``publish(target)`` delivers to an ``InferenceEngineV2`` or a
    ``ReplicaRouter`` (two-phase fleet flip), stamping the version with
    the engine's ``global_steps`` by default so a rollout replay log can
    name the exact weights a token was sampled under."""

    def __init__(self, engine, monitor: Optional[Monitor] = None,
                 clock=time.perf_counter):
        self.engine = engine
        self.clock = clock
        self.memory_monitor = InMemoryMonitor(maxlen=1024)
        self._sinks: List[Monitor] = [monitor] if monitor is not None else []
        self.publishes = 0
        self.adapter_publishes = 0
        self.gather_latency_s = 0.0
        self.publish_latency_s = 0.0
        self.last_version: Optional[int] = None

    def _emit(self, events) -> None:
        self.memory_monitor.write_events(events)
        for s in self._sinks:
            s.write_events(events)

    def gather(self):
        """The ZeRO-3 gather + LoRA fuse: one jitted program from the
        sharded training pytree (or the host-offload tier's joined bf16
        mirrors) to the model-structured serving tree. Metered as
        ``gather_latency_s`` (the reference ``_generate_latency``'s
        gather half)."""
        import jax

        t0 = self.clock()
        weights = self.engine.module_weights(consensus=True)
        jax.block_until_ready(weights)
        dt = self.clock() - t0
        self.gather_latency_s += dt
        self._emit([("weights/gather_s", dt, self.publishes)])
        return weights

    def publish(self, target, version: Optional[int] = None,
                weights=None, **commit_kw) -> int:
        """Gather (unless ``weights`` is passed) and deliver to ``target``
        — an ``InferenceEngineV2`` or a ``ReplicaRouter``; both expose
        ``publish_weights``. ``commit_kw`` (``force=``/``defer=``) applies
        to single-engine targets only; the router always defers per
        replica. Returns the published version (default: the engine's
        ``global_steps``, so the version IS the optimizer-step watermark).
        Raises when a single-engine target refuses the swap under live KV
        — the fleet path never refuses, it defers."""
        t0 = self.clock()
        if weights is None:
            weights = self.gather()
        version = (int(self.engine.global_steps) if version is None
                   else int(version))
        ok = target.publish_weights(weights, version=version, **commit_kw)
        if ok is False:
            raise RuntimeError(
                "publish refused: the target engine holds live sequences "
                "(pass force=True or defer=True, or drain it first)")
        self.publishes += 1
        self.last_version = version
        dt = self.clock() - t0
        self.publish_latency_s += dt
        self._emit([("weights/publish_s", dt, self.publishes),
                    ("weights/version", version, self.publishes)])
        return version

    def publish_adapter(self, target, adapter_id: str, factors,
                        alpha=None, version: Optional[int] = None) -> int:
        """Deliver ONE tenant's LoRA factor pairs to a serving target
        (ISSUE 18) — the factors-only analog of :meth:`publish`. Where
        the dense flip gathers and fuses the whole model, a tenant flip
        ships kilobytes per layer and fuses NOTHING: the serving pool
        applies the low-rank delta per row at decode time, so base
        weights, paged KV pools, and every compiled serving program are
        untouched. ``target`` is a ``ReplicaRouter`` (fleet-wide
        registration) or an ``InferenceEngineV2`` (its own pool).
        ``factors`` maps target name -> (A, B) as
        ``inference.adapters.AdapterPool.register`` takes them. The
        version defaults to the training engine's ``global_steps`` —
        the same optimizer-step watermark dense publishes stamp, so a
        rollout log can name the adapter version a token decoded under."""
        t0 = self.clock()
        version = (int(self.engine.global_steps) if version is None
                   else int(version))
        if hasattr(target, "publish_adapter"):
            got = target.publish_adapter(adapter_id, factors, alpha=alpha,
                                         version=version)
        else:
            pool = getattr(target, "adapters", None)
            if pool is None:
                raise ValueError(
                    "publish_adapter: target has no adapter pool — enable "
                    "config.adapters on the serving engine")
            got = pool.register(adapter_id, factors, alpha=alpha,
                                version=version)
        self.adapter_publishes += 1
        dt = self.clock() - t0
        self._emit([
            ("weights/adapter_publish_s", dt, self.adapter_publishes),
            ("weights/adapter_version", got, self.adapter_publishes)])
        return int(got)


@locked_by("_mu", "_inflight", "_ticket", "_slots_in_use")
class WeightWire:
    """Cross-process weight delivery over the disagg transfer substrate.

    The gathered serving tree's leaves are staged through the process-wide
    AIO pinned-buffer pool exactly like KV blocks are
    (``serving/disagg.py KVTransferChannel`` — aligned, long-lived,
    O_DIRECT-capable buffers reused across publishes), with an optional
    ``AsyncIOEngine`` file spill as the simplest cross-host wire.
    ``send``/``recv`` are split so a fabric can sit between them;
    in-process they hand over the same staged buffers, and the received
    tree is byte-identical to the sent one (tests/test_rlhf.py pins it).
    Dense-array trees only — quantized-matrix leaves are a serving-side
    transform and should be published pre-quantization."""

    _next_channel_id = itertools.count()

    def __init__(self, spill_dir: Optional[str] = None):
        from ..ops.native.aio import get_buffer_pool

        self.pool = get_buffer_pool()
        self._chan = next(WeightWire._next_channel_id)
        # rank 20 (utils.invariants.LOCK_ORDER), like the KV channel it
        # mirrors; instrumented under SXT_SANITIZE
        self._mu = sanitizer.wrap(threading.Lock(), "WeightWire._mu")
        self.spill_dir = spill_dir
        self.sends = 0
        self.bytes_moved = 0
        self._inflight: Dict[int, Tuple[object, List[np.ndarray],
                                        Optional[str], int]] = {}
        self._ticket = 0
        self._slots_in_use: set = set()

    @requires_lock("_mu")
    def _alloc_slot(self) -> int:
        slot = 0
        while slot in self._slots_in_use:
            slot += 1
        self._slots_in_use.add(slot)
        return slot

    def send(self, params) -> int:
        """Stage a weight tree for transfer; returns a ticket for
        ``recv``. Leaves are pulled to host and copied into pinned
        staging buffers keyed (channel, slot, leaf) — steady-state
        sequential publishes reuse one set of allocations."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(params)
        arrays = []
        for i, leaf in enumerate(leaves):
            try:
                arrays.append(np.asarray(leaf))
            except Exception as e:
                raise TypeError(
                    f"WeightWire: leaf {i} ({type(leaf).__name__}) is not a "
                    f"dense array ({e}); publish pre-quantization weights "
                    "over the wire") from e
        with self._mu:
            slot = self._alloc_slot()
            self._ticket += 1
            ticket = self._ticket
        path = None
        try:
            staged: List[np.ndarray] = []
            for i, arr in enumerate(arrays):
                buf = self.pool.staging(("weight_wire", self._chan, slot, i),
                                        arr.shape, arr.dtype)
                np.copyto(buf, arr)
                staged.append(buf)
            if self.spill_dir is not None:
                import os

                from ..ops.native.aio import get_io_engine

                path = os.path.join(self.spill_dir,
                                    f"weight_wire_{self._chan}_{ticket}.bin")
                io = get_io_engine()
                off, reqs = 0, []
                for buf in staged:
                    reqs.append(io.submit_write(path, buf, offset=off))
                    off += buf.nbytes
                for r in reqs:
                    io.wait(r)
        except BaseException:
            # a failed send must not strand its slot: later sends would
            # walk past it forever, allocating fresh pinned buffers per
            # publish instead of reusing slot 0's
            with self._mu:
                self._slots_in_use.discard(slot)
            if path is not None:
                self._unlink(path)
            raise
        with self._mu:
            self._inflight[ticket] = (treedef, staged, path, slot)
        self.sends += 1
        self.bytes_moved += sum(b.nbytes for b in staged)
        return ticket

    def recv(self, ticket: int):
        """Take delivery: rebuild the tree from the staged (or
        spill-read-back) bytes. The returned leaves own their bytes, so
        the staging slot is immediately reusable."""
        with self._mu:
            treedef, staged, path, slot = self._inflight.pop(ticket)
        if path is not None:
            from ..ops.native.aio import get_io_engine

            io = get_io_engine()
            off, reqs = 0, []
            for buf in staged:
                reqs.append(io.submit_read(path, buf, offset=off))
                off += buf.nbytes
            for r in reqs:
                io.wait(r)
            self._unlink(path)
        leaves = [np.array(b) for b in staged]
        with self._mu:
            self._slots_in_use.discard(slot)
        import jax

        return jax.tree_util.tree_unflatten(treedef, leaves)

    @staticmethod
    def _unlink(path: str) -> None:
        import os

        try:
            os.remove(path)
        except OSError:
            pass

    def cancel(self, ticket: int) -> None:
        """Drop a staged publish that will never be received (slot +
        spill file released). Safe for unknown tickets."""
        with self._mu:
            entry = self._inflight.pop(ticket, None)
            if entry is None:
                return
            _, _, path, slot = entry
            self._slots_in_use.discard(slot)
        if path is not None:
            self._unlink(path)

    def stats(self) -> Dict[str, object]:
        return {
            "sends": self.sends,
            "bytes": self.bytes_moved,
            "in_flight": len(self._inflight),
            "pinned_staging": self.pool.native,
            "spill_dir": self.spill_dir,
        }


def publish_over_wire(publisher: WeightPublisher, wire: WeightWire, target,
                      version: Optional[int] = None, **commit_kw) -> int:
    """Gather -> wire roundtrip -> publish: the cross-process delivery
    path composed from the pieces above. In a split deployment the
    trainer runs ``wire.send(publisher.gather())`` and the serving host
    runs ``target.publish_weights(wire.recv(ticket))``; in-process this
    helper proves the whole path byte-exactly."""
    weights = publisher.gather()
    ticket = wire.send(weights)
    try:
        delivered = wire.recv(ticket)
    except BaseException:
        wire.cancel(ticket)
        raise
    return publisher.publish(target, version=version, weights=delivered,
                             **commit_kw)
