"""``zero.*`` API surface (reference ``deepspeed.zero``).

The reference's construct-time machinery does not exist here because the
engine gets it structurally: model init is traced under jit with the ZeRO
sharding policy as ``out_shardings`` (each device materializes only its
shard — the ``zero.Init`` capability, see runtime/engine.py params_init_fn),
and inside jit every array is LOGICALLY full while XLA schedules the
all-gathers (the ``GatheredParameters`` capability). These shims keep
reference-shaped user code working unchanged.
"""

from __future__ import annotations

import contextlib

from .utils.logging import log_dist


class Init:
    """Reference ``deepspeed.zero.Init`` (partition_parameters.py:879)
    context manager. Construct-time partitioning is AUTOMATIC here — pass a
    model with ``init()`` to :func:`shuffle_exchange_tpu.initialize` and the
    engine traces it straight into sharded buffers; this context is accepted
    (with the reference's kwargs) so reference-shaped code runs unchanged.
    """

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True,
                 remote_device=None, pin_memory=False, config_dict_or_path=None,
                 config=None, enabled=True, dtype=None, mpu=None, sequence_data_parallel_group=None,
                 param_swapper=None):
        self.enabled = enabled

    def __enter__(self):
        if self.enabled:
            log_dist("zero.Init: construct-time partitioning is automatic on "
                     "this engine (deferred jit init with sharded outputs); "
                     "context accepted for API compatibility", ranks=[0])
        return self

    def __exit__(self, *exc):
        return False


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank=None, fwd_module=None, enabled=True):
    """Reference ``deepspeed.zero.GatheredParameters``
    (partition_parameters.py:2193): materialize partitioned params inside
    the context. Our param trees are logically full jax.Arrays whose
    sharding is a placement detail — read access works anywhere, and XLA
    inserts the gather if a host transfer or computation needs the full
    value — so the context simply yields the tree."""
    yield params
