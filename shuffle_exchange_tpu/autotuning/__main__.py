"""CLI: ``python -m shuffle_exchange_tpu.autotuning --config ds.json
--model gpt2_small`` (reference workflow: ``deepspeed --autotuning tune``,
autotuning/README.md). The serving half of the subsystem is
``scripts/autotune_serving.py`` (ISSUE 14) — same journal/runner
machinery, so one results dir retunes training AND serving."""

import argparse
import json
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="shuffle_exchange_tpu.autotuning")
    ap.add_argument("--config", required=True, help="base DS-style JSON config path")
    ap.add_argument("--model", default="gpt2_small",
                    help="model-zoo preset name (models/__init__) or 'tiny'")
    ap.add_argument("--seq", type=int, default=None, help="profile sequence length")
    ap.add_argument("--steps", type=int, default=3, help="measured steps per candidate")
    args = ap.parse_args(argv)

    import numpy as np

    from shuffle_exchange_tpu import models as zoo
    from shuffle_exchange_tpu.autotuning import autotune

    with open(args.config) as f:
        base = json.load(f)
    preset = getattr(zoo, args.model)
    model = zoo.Transformer(preset())
    seq = args.seq or min(model.config.max_seq_len, 1024)
    vocab = model.config.vocab_size
    rng = np.random.default_rng(0)

    def batch_fn(global_bs):
        return {"input_ids": rng.integers(0, vocab, size=(global_bs, seq)).astype(np.int32)}

    tuned, best = autotune(model, base, batch_fn, seq_len=seq, profile_steps=args.steps)
    print(json.dumps({"best": best.name, "tuned": tuned}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
