"""Grid + successive-halving search over serving candidates.

ISSUE 14 tentpole, part 2a. Exhaustively measuring every grid point at
full fidelity is what makes autotuning expensive (the reference's own
README measures 2.5x throughput left on the table by configs nobody had
the budget to search). Successive halving spends the budget where it
ranks: every feasible candidate is screened on a SHORT prefix of the
paired Poisson trace, survivors (the top ``1/eta`` per round) are
promoted to higher fidelity, and only finalists see the full trace.
Because every round's candidates face the exact same trace object
(:class:`~.trace.PoissonTrace` — same seed, same prompts, same arrival
offsets), candidate comparisons are paired: workload variance cancels
out of the ranking, which is what lets short screening traces rank
reliably at all.

Trials ride :class:`~.runner.ExperimentRunner`, so a search given a
journal is crash-safe: kill it mid-round and the rerun re-measures
nothing that already committed. Statically-pruned candidates
(``status="pruned_static"`` from the space) are recorded in the trial
log but NEVER measured — the runner's ``executed`` list is the proof.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence

from ..config.config_utils import ConfigError
from ..utils.logging import logger
from .runner import ExperimentRunner, Trial, TrialJournal
from .space import ServingCandidate, ServingSearchSpace, SpaceContext
from .trace import PoissonTrace

__all__ = ["SuccessiveHalving", "SearchResult", "halving_schedule",
           "run_serving_search", "default_serving_axes",
           "ServingSearchOutcome"]

#: an objective maps (candidate, trace) -> a JSON-serializable dict with
#: at least {"metric": float, "feasible": bool}; extra keys ride into
#: the trial log's ``detail``
Objective = Callable[[ServingCandidate, PoissonTrace], Dict[str, object]]


def halving_schedule(n_candidates: int, n_requests: int, *, rounds: int = 2,
                     eta: int = 2, min_screen: int = 4) -> List[Dict[str, int]]:
    """The per-round plan: how many candidates survive INTO each round
    and the trace-prefix fidelity (request count) each round measures at.
    Fidelity grows by ``eta`` per round up to the full trace; survivors
    shrink by ``eta`` per round down to a single finalist pool."""
    if rounds < 1:
        raise ConfigError(f"rounds must be >= 1, got {rounds}")
    if eta < 2:
        raise ConfigError(f"eta must be >= 2, got {eta}")
    plan = []
    alive = n_candidates
    for r in range(rounds):
        frac = eta ** (rounds - 1 - r)
        fidelity = n_requests if r == rounds - 1 else max(
            min(min_screen, n_requests), math.ceil(n_requests / frac))
        plan.append({"round": r, "candidates": alive, "fidelity": fidelity})
        alive = max(1, math.ceil(alive / eta))
    return plan


@dataclasses.dataclass
class SearchResult:
    best: Optional[ServingCandidate]
    best_trial: Optional[Trial]
    trials: List[Trial]                  # every trial incl. pruned records
    executed: List[str]                  # keys measured THIS process
    resumed: int                         # trials satisfied from the journal
    schedule: List[Dict[str, int]]

    def ranked(self, final_only: bool = False) -> List[Trial]:
        """Measured trials, best first (feasible before infeasible,
        higher metric first, name as the deterministic tiebreak)."""
        pool = [t for t in self.trials if t.status == "ok"
                and t.metric is not None]
        if final_only:
            last = max((t.round for t in pool), default=0)
            pool = [t for t in pool if t.round == last]
        return sorted(pool, key=lambda t: (
            not bool(t.detail.get("feasible", True)), -t.metric,
            t.candidate_name))

    def log(self) -> Dict[str, object]:
        """The machine-readable search record the CLI writes."""
        return {
            "best": self.best.name if self.best else None,
            "best_overlay": self.best.overlay() if self.best else None,
            "best_metric": self.best_trial.metric if self.best_trial else None,
            "schedule": self.schedule,
            "trials_measured": len([t for t in self.trials
                                    if t.status == "ok"]),
            "trials_error": len([t for t in self.trials
                                 if t.status == "error"]),
            "pruned_static": [
                {"candidate": t.candidate_name,
                 "reason": t.detail.get("prune_reason", "")}
                for t in self.trials if t.status == "pruned_static"],
            "executed_this_run": list(self.executed),
            "resumed_from_journal": self.resumed,
            "ranked": [t.payload() for t in self.ranked()],
        }


class SuccessiveHalving:
    """Screen → promote → finals over a fixed candidate grid.

    ``rounds=1`` degenerates to plain paired grid search at full
    fidelity; ``rounds=2, eta=2`` is the ci_full smoke's shape (screen
    everything on half the trace, final the top half on all of it)."""

    def __init__(self, objective: Objective, trace: PoissonTrace, *,
                 rounds: int = 2, eta: int = 2, min_screen: int = 4,
                 journal: Optional[TrialJournal] = None,
                 runner: Optional[ExperimentRunner] = None,
                 key_ns: str = ""):
        if trace.arrivals is None:
            raise ConfigError(
                "SuccessiveHalving needs a calibrated trace "
                "(PoissonTrace.with_load) — uncalibrated all-at-once "
                "serving measures capacity, not goodput under load")
        self.objective = objective
        self.trace = trace
        self.rounds = int(rounds)
        self.eta = int(eta)
        self.min_screen = int(min_screen)
        self.runner = runner if runner is not None else ExperimentRunner(journal)
        # journal-key namespace: candidate names only identify a point in
        # the KNOB space — a shared journal dir must miss when the model,
        # engine config, or workload differ (run_serving_search passes a
        # fingerprint of all three)
        self.key_ns = key_ns

    # -- one trial ------------------------------------------------------

    def _measure(self, cand: ServingCandidate, rnd: int,
                 fid_trace: PoissonTrace) -> Trial:
        key = f"{self.key_ns}{cand.name}@r{rnd}n{len(fid_trace)}"
        t = Trial(key=key, candidate_name=cand.name, round=rnd,
                  fidelity=len(fid_trace))

        def run() -> Dict[str, object]:
            try:
                detail = self.objective(cand, fid_trace)
            except Exception as e:   # a broken candidate costs one trial
                logger.warning(
                    f"autotuning: trial {key} failed: {str(e)[:200]}")
                return dict(t.payload(), status="error",
                            detail={"error": str(e)[:500]})
            metric = float(detail.pop("metric"))
            return dict(t.payload(), status="ok", metric=metric,
                        detail=detail)

        payload, cached = self.runner.run_one(key, run)
        got = Trial.from_payload(payload)
        got.from_journal = cached
        return got

    # -- the search -----------------------------------------------------

    def run(self, candidates: Sequence[ServingCandidate]) -> SearchResult:
        trials: List[Trial] = []
        by_name = {c.name: c for c in candidates}
        feasible = []
        for c in candidates:
            if c.status == "pruned_static":
                # recorded, never measured: the static-prune contract
                trials.append(Trial(
                    key=f"{c.name}@pruned", candidate_name=c.name,
                    status="pruned_static",
                    detail={"prune_reason": c.prune_reason}))
                logger.info(f"autotuning: pruned {c.name} statically "
                            f"({c.prune_reason})")
            else:
                feasible.append(c)
        if not feasible:
            raise ConfigError(
                "autotuning: every candidate was statically pruned — "
                "widen the space or raise the SpaceContext budgets; "
                "reasons: " + "; ".join(
                    f"{c.name}: {c.prune_reason}"
                    for c in candidates[:8] if c.status == "pruned_static"))

        schedule = halving_schedule(len(feasible), len(self.trace),
                                    rounds=self.rounds, eta=self.eta,
                                    min_screen=self.min_screen)
        survivors = list(feasible)
        last_round: List[Trial] = []
        for step in schedule:
            rnd = step["round"]
            fid_trace = self.trace.head(step["fidelity"])
            round_trials = [self._measure(c, rnd, fid_trace)
                            for c in survivors]
            trials.extend(round_trials)
            ranked = sorted(
                [t for t in round_trials if t.status == "ok"
                 and t.metric is not None],
                key=lambda t: (not bool(t.detail.get("feasible", True)),
                               -t.metric, t.candidate_name))
            if not ranked:
                raise ConfigError(
                    f"autotuning: round {rnd} measured no successful "
                    f"trial ({len(round_trials)} attempted)")
            keep = (len(ranked) if rnd == self.rounds - 1
                    else max(1, math.ceil(len(ranked) / self.eta)))
            survivors = [by_name[t.candidate_name] for t in ranked[:keep]]
            for c in survivors:
                c.status = "final" if rnd == self.rounds - 1 else "promoted"
            last_round = ranked
            logger.info(
                f"autotuning: round {rnd} (fidelity {step['fidelity']}) "
                f"measured {len(ranked)}, promoted {len(survivors)}; best "
                f"{ranked[0].candidate_name} = {ranked[0].metric:.1f}")

        best_trial = last_round[0]
        best = by_name[best_trial.candidate_name]
        best.status = "best"
        return SearchResult(
            best=best, best_trial=best_trial, trials=trials,
            executed=list(self.runner.executed),
            resumed=sum(1 for t in trials if t.from_journal),
            schedule=schedule)


# ---------------------------------------------------------------------------
# The serving-search driver (bench row + scripts/autotune_serving.py)
# ---------------------------------------------------------------------------


def default_serving_axes(icfg) -> Dict[str, list]:
    """The default grid around a base config: the ``max_running`` packing
    ladder (halved / as-is / doubled / quadrupled, clamped to the
    token-budget invariant) plus a deliberately ladder-blown
    ``chunk_bins`` axis whose candidates the static compile-budget
    constraint must prune unmeasured — every search therefore exercises
    the prune path, and the trial log proves it ran."""
    sv = icfg.serving
    mr = sv.max_running
    running = sorted({v for v in (max(1, mr // 2), mr, mr * 2, mr * 4)
                      if v <= sv.token_budget} | {mr})
    # 256 declared chunk bins: a ladder no warmed-server compile budget
    # tolerates at ANY row count (the static-prune demonstration
    # candidates — bound > 512 even at max_running=1)
    insane = tuple(sv.chunk_min + i for i in range(256))
    return {"max_running": running, "chunk_bins": [None, insane]}


@dataclasses.dataclass
class ServingSearchOutcome:
    """Everything the bench row / CLI publishes: the search result, the
    default-config baseline measured on the SAME full-fidelity paired
    trace, and the trace itself."""

    result: SearchResult
    default_candidate: ServingCandidate
    default_trial: Trial
    trace: PoissonTrace
    objective: object                      # the ServingObjective (counters)

    @property
    def goodput_default(self) -> float:
        return float(self.default_trial.metric or 0.0)

    @property
    def goodput_tuned(self) -> float:
        return float(self.result.best_trial.metric or 0.0)

    @property
    def delta_pct(self) -> float:
        base = self.goodput_default
        return 100.0 * (self.goodput_tuned / base - 1.0) if base else 0.0

    def knob_effects(self) -> Dict[str, Dict[str, float]]:
        """Best SCREENING-round metric per knob value, per searched axis
        — the knob ranking BASELINE.md records (which lever moved
        goodput, and by how much). Round 0 is the one round where EVERY
        measured candidate faced the same trace prefix, so these numbers
        are like-for-like; mixing in finals metrics would compare
        goodput across different trace lengths."""
        by_cand: Dict[str, float] = {}
        for t in self.result.trials:
            if t.status == "ok" and t.metric is not None and t.round == 0:
                cur = by_cand.get(t.candidate_name)
                by_cand[t.candidate_name] = max(
                    cur, t.metric) if cur is not None else t.metric
        effects: Dict[str, Dict[str, float]] = {}
        for c in self._measured_candidates():
            for axis in ("token_budget", "max_running", "chunk_min", "k",
                         "kv_cache_dtype", "decode_kernel"):
                val = str(getattr(c, axis))
                best = by_cand.get(c.name)
                if best is None:
                    continue
                slot = effects.setdefault(axis, {})
                slot[val] = max(slot.get(val, float("-inf")), best)
        # drop axes that never varied — they rank nothing
        return {a: vs for a, vs in effects.items() if len(vs) > 1}

    def _measured_candidates(self) -> List[ServingCandidate]:
        return [c for c in self._candidates
                if c.status not in ("pruned_static",)]

    _candidates: List[ServingCandidate] = dataclasses.field(default_factory=list)

    def summary(self) -> Dict[str, object]:
        measured = [t for t in self.result.trials if t.status == "ok"]
        pruned = [t for t in self.result.trials
                  if t.status == "pruned_static"]
        pruned_names = {t.candidate_name for t in pruned}
        return {
            "winner": self.result.best.name,
            "winner_overlay": self.result.best.overlay(),
            "trials_measured": len(measured),
            "trials_error": len([t for t in self.result.trials
                                 if t.status == "error"]),
            "pruned_static": len(pruned),
            # the static-prune contract: no pruned candidate's key was
            # ever executed (measured) by the runner (keys are
            # "<ns:>name@r..." — candidate names carry no ':' or '@')
            "pruned_never_measured": not any(
                k.split("@")[0].split(":")[-1] in pruned_names
                for k in self.result.executed),
            # per-trial zero-recompile contract: every measured trial
            # warms to fixpoint and a measured-pass compile marks it
            # infeasible (never promoted over a feasible one). The
            # all-trials flag can legitimately go false — a candidate
            # whose shape space does not converge under warming is
            # exactly what the gate exists to disqualify — but the
            # winner and the default baseline must be clean.
            "zero_recompile_all_trials": all(
                t.detail.get("recompiles_measured_pass", 0) == 0
                for t in measured),
            "winner_zero_recompile": (
                self.result.best_trial.detail.get(
                    "recompiles_measured_pass", 0) == 0),
            "default_zero_recompile": (
                self.default_trial.detail.get(
                    "recompiles_measured_pass", 0) == 0),
            "goodput_default_tokens_per_sec": round(self.goodput_default, 2),
            "goodput_tuned_tokens_per_sec": round(self.goodput_tuned, 2),
            "goodput_delta_pct": round(self.delta_pct, 1),
            "default_candidate": self.default_candidate.name,
            "ttft_p95_s_default": self.default_trial.detail.get("ttft_p95_s"),
            "ttft_p95_s_tuned": self.result.best_trial.detail.get(
                "ttft_p95_s"),
            "tpot_p95_s_default": self.default_trial.detail.get("tpot_p95_s"),
            "tpot_p95_s_tuned": self.result.best_trial.detail.get(
                "tpot_p95_s"),
            "knob_effects": self.knob_effects(),
            "schedule": self.result.schedule,
            "resumed_from_journal": self.result.resumed,
            "trace": self.trace.describe(),
        }


def run_serving_search(model, params, icfg, *, trace: PoissonTrace,
                       axes: Optional[Dict[str, list]] = None,
                       context: Optional[SpaceContext] = None,
                       rounds: int = 2, eta: int = 2, min_screen: int = 4,
                       load: float = 2.0, max_programs: int = 512,
                       journal_dir: Optional[str] = None,
                       ttft_p95_limit_s: Optional[float] = None,
                       tpot_p95_limit_s: Optional[float] = None
                       ) -> ServingSearchOutcome:
    """The whole serving autotune, end to end: calibrate the paired trace
    on the DEFAULT config (one capacity pass — every candidate then faces
    identical arrival offsets), enumerate + statically prune the space,
    run successive halving, and measure the default baseline on the same
    full-fidelity trace for the tuned-vs-default delta. Crash-safe when
    ``journal_dir`` is given (every trial commits tmp+rename; a rerun
    resumes)."""
    from ..inference import ContinuousBatchingScheduler, InferenceEngineV2
    from .objectives import ServingObjective

    default_cand = ServingCandidate.from_config(icfg)
    journal = TrialJournal(journal_dir) if journal_dir else None
    # journal-key namespace (the training Autotuner's fingerprint
    # discipline): everything the measurement depends on beyond the
    # candidate's own knobs — model geometry, engine config, workload
    # shape, backend — so a reused journal dir restores only trials of
    # the SAME setup and misses (re-measures) anything else
    import hashlib
    import json as _json

    import jax as _jax

    mcfg = getattr(model, "config", None)
    ns = hashlib.blake2b(_json.dumps(
        [repr(mcfg) if mcfg is not None else type(model).__name__,
         icfg.serving_overlay(), icfg.dtype, icfg.max_seq_len,
         icfg.kv_block_size, icfg.num_kv_blocks,
         trace.seed, [len(p) for p in trace.prompts], trace.max_new, load,
         _jax.default_backend(), _jax.__version__],
        sort_keys=True, default=repr).encode(), digest_size=6).hexdigest()
    key_ns = f"s{ns}:"
    if trace.arrivals is None:
        # capacity calibration: all-at-once on the default config (the
        # goodput row's discipline — a warm pass, then the measured
        # capacity pass the arrivals are scaled from). The calibration
        # is ITSELF a journaled measurement: capacity is wall-clock and
        # differs run to run, so a resumed search must restore the
        # original arrivals rather than re-calibrate — otherwise its
        # fresh trials would face a different workload than the cached
        # ones they are ranked against, breaking the paired-trace
        # contract (journal keys assume one trace per results dir).
        cal_key = (f"{key_ns}calibration@s{trace.seed}n{len(trace)}"
                   f"mn{trace.max_new}x{load}")
        cached = journal.get(cal_key) if journal is not None else None
        if cached is not None:
            cal = cached["detail"]
            trace = dataclasses.replace(
                trace, arrivals=tuple(cal["arrivals_s"]), load=float(load),
                capacity_tokens_per_sec=float(cal["capacity_tokens_per_sec"]))
        else:
            eng = InferenceEngineV2(model, params, icfg)
            prompts = trace.prompt_lists()
            ContinuousBatchingScheduler(eng).serve(
                prompts, max_new_tokens=trace.max_new)
            cap_sched = ContinuousBatchingScheduler(eng)
            cap_sched.serve(prompts, max_new_tokens=trace.max_new)
            cap = cap_sched.stats()["sustained_tokens_per_sec"]
            if not cap or cap <= 0:
                raise ConfigError(
                    "autotuning: capacity calibration measured no goodput "
                    "on the default config — the trace cannot rank "
                    "candidates")
            trace = trace.with_load(cap, load)
            del eng
            if journal is not None:
                # full-precision arrivals (describe() rounds for humans;
                # the restore must be bit-exact)
                journal.record(cal_key, {
                    "key": cal_key, "status": "ok",
                    "detail": {
                        "arrivals_s": list(trace.arrivals),
                        "capacity_tokens_per_sec":
                            trace.capacity_tokens_per_sec,
                        "offered_load_x": load,
                    }})

    if context is None:
        context = SpaceContext(
            max_seq_len=icfg.max_seq_len, kv_block_size=icfg.kv_block_size,
            num_kv_blocks=icfg.num_kv_blocks, max_programs=max_programs,
            request_tokens_hi=trace.request_tokens_hi())
    space = ServingSearchSpace(axes or default_serving_axes(icfg), context,
                               base=default_cand)
    candidates = space.enumerate()
    ok, why = space.check(default_cand)
    if not ok:
        raise ConfigError(
            f"autotuning: the BASE config fails its own search "
            f"constraints ({why}) — fix the config before tuning around it")

    objective = ServingObjective(
        model, params, icfg, ttft_p95_limit_s=ttft_p95_limit_s,
        tpot_p95_limit_s=tpot_p95_limit_s)
    search = SuccessiveHalving(objective, trace, rounds=rounds, eta=eta,
                               min_screen=min_screen, journal=journal,
                               key_ns=key_ns)
    result = search.run(candidates)

    # the baseline at full fidelity: if the default survived to the
    # finals its trial already exists — reuse it (in-memory first, so
    # journal-less bench runs do not re-serve the full trace; then the
    # journal for resumed runs); only a default screened out early pays
    # a fresh measurement
    base_key = f"{key_ns}{default_cand.name}@r{rounds - 1}n{len(trace)}"

    def measure_default(key: str):
        existing = next((t for t in result.trials
                         if t.key == key and t.status == "ok"), None)
        if existing is not None:
            return existing

        def fn() -> Dict[str, object]:
            return dict(
                Trial(key=key, candidate_name=default_cand.name,
                      round=rounds - 1, fidelity=len(trace)).payload(),
                status="ok", **_metric_split(objective(default_cand, trace)))
        payload, _ = search.runner.run_one(key, fn)
        return Trial.from_payload(payload)

    default_trial = measure_default(base_key)
    if default_trial.detail.get("recompiles_measured_pass", 0):
        # the delta headline divides by the baseline — one unlucky warm
        # on the DEFAULT (possibly journaled from its finals trial)
        # poisons the whole row in the tuned config's favor, so the
        # baseline alone gets one clean-measurement retry under its own
        # journal key; keep whichever measured clean (or the faster)
        logger.warning(
            "autotuning: default baseline recompiled during its measured "
            "pass; re-measuring once for an honest delta")
        retry = measure_default(base_key + "+baseline-retry")
        clean = retry.detail.get("recompiles_measured_pass", 0) == 0
        if clean or (retry.metric or 0) > (default_trial.metric or 0):
            default_trial = retry
    result.executed = list(search.runner.executed)

    out = ServingSearchOutcome(
        result=result, default_candidate=default_cand,
        default_trial=default_trial, trace=trace, objective=objective)
    out._candidates = candidates
    return out


def _metric_split(detail: Dict[str, object]) -> Dict[str, object]:
    metric = float(detail.pop("metric"))
    return {"metric": metric, "detail": detail}
