"""The training autotuner: search training configs, measure, emit the best.

Capability analog of the reference autotuner (``autotuning/autotuner.py``,
2,722 LoC; workflow in ``autotuning/README.md``): given a model and a base
DS-style config, it explores micro-batch size, gradient-accumulation steps,
ZeRO stage, and remat policy, prunes candidates with a first-principles
HBM-memory model (the reference prunes with its ``model_info`` param-count
estimate), then short-profiles the survivors through the real engine and
returns/writes the measured-best config (reference result tables:
``autotuning/README.md:240-245``).

TPU-native differences: no multi-process experiment launcher is needed —
candidates compile+run in-process through jit; memory pruning uses the known
HBM capacity per device instead of CUDA allocator probing; "mp_size" maps to
the mesh's tensor axis.

Since ISSUE 14 this class is a thin driver over the shared subsystem
machinery: measurement lives in :class:`~.objectives.TrainingObjective`,
execution rides :class:`~.runner.ExperimentRunner` (pass ``journal_dir``
to make a tune crash-safe — completed trials journal tmp+rename and a
restarted tune re-runs nothing), and result files commit atomically. The
serving half of the subsystem (``space.py``/``search.py``/
``objectives.ServingObjective``) shares the same runner/journal, so one
results dir (and one tunnel window) retunes training AND serving.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..config.config_utils import ConfigError
from ..utils.logging import log_dist, logger
from .runner import ExperimentRunner, TrialJournal, atomic_write_json, \
    sweep_stale_tmp

# bytes per element
_F32 = 4
_BF16 = 2


def _hbm_bytes_per_device(default: int = 16 * 1024**3) -> int:
    """Best-effort per-device memory budget (HBM on TPU, heap on CPU)."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return default


def estimate_step_memory(n_params: int, *, mbs: int, seq_len: int,
                         d_model: int, n_layers: int, vocab_size: int,
                         zero_stage: int, world: int, remat: bool,
                         loss_chunk: int = 256, tensor: int = 1,
                         seq_par: int = 1,
                         offload: Optional[str] = None) -> int:
    """First-principles peak-HBM estimate (bytes) for one fused train step.

    Mirrors the reference autotuner's memory-per-GPU estimate
    (``autotuning/autotuner.py`` model_info path) with TPU specifics: bf16
    forward weights + fp32 master/m/v (ZeRO-sharded over ``world`` when
    stage >= 1), activations ~ per-layer residual+ffn working set (halved
    by remat to the saved-dots set), chunked-CE logits block. ``tensor``
    divides param/activation terms (mp_size); ``seq_par`` divides only the
    token-dependent terms (activations/logits — params replicate across the
    seq axis); ``offload`` = "cpu"/"nvme" moves master+moments off device
    entirely (host-optimizer tier).
    """
    shard = world if zero_stage >= 1 else 1
    p_shard = world if zero_stage >= 3 else 1
    master_opt = 3 * n_params * _F32 // (shard * tensor)   # master + m + v
    if offload in ("cpu", "nvme"):
        master_opt = 0
    fwd_params = n_params * _BF16 // (p_shard * tensor)    # bf16 forward copy
    grads = n_params * _F32 // max(1, (shard if zero_stage >= 2 else 1) * tensor)
    tokens = mbs * seq_len // seq_par
    # activation working set per layer: attn qkv+out (4d) + ffn (~8d) in bf16
    act_per_layer = tokens * d_model * 12 * _BF16 // tensor
    acts = act_per_layer * (2 if remat else n_layers)
    logits = tokens * vocab_size * _F32 if not loss_chunk else mbs * loss_chunk * vocab_size * _F32
    return master_opt + fwd_params + grads + acts + logits


@dataclasses.dataclass
class Candidate:
    micro_batch_size: int
    gradient_accumulation_steps: int
    zero_stage: int
    remat: Optional[bool]          # None = leave the model as built
    tensor: int = 1                # mesh tensor split (reference mp_size)
    seq_par: int = 1               # mesh seq split (Ulysses sequence parallel)
    offload: Optional[str] = None  # optimizer offload tier: None | cpu | nvme
    seq_len: Optional[int] = None  # None = the tuner's base sequence length
    bucket_mb: Optional[int] = None  # zeropp.bucket_mb (quantized-wire
                                     # launch coalescing); None = config default
    est_bytes: int = 0
    metric_val: float = float("nan")
    status: str = "pending"        # pending | pruned | ok | oom | error

    @property
    def name(self) -> str:
        r = {None: "asis", True: "remat", False: "noremat"}[self.remat]
        n = f"z{self.zero_stage}_mbs{self.micro_batch_size}_gas{self.gradient_accumulation_steps}_{r}"
        if self.tensor > 1:
            n += f"_tp{self.tensor}"
        if self.seq_par > 1:
            n += f"_sp{self.seq_par}"
        if self.offload:
            n += f"_off{self.offload}"
        if self.seq_len:
            n += f"_sl{self.seq_len}"
        if self.bucket_mb is not None:
            n += f"_bkt{self.bucket_mb}"
        return n

    def as_config_patch(self) -> Dict[str, Any]:
        patch: Dict[str, Any] = {
            "train_micro_batch_size_per_gpu": self.micro_batch_size,
            "gradient_accumulation_steps": self.gradient_accumulation_steps,
            "zero_optimization": {"stage": self.zero_stage},
        }
        # Always emit the tuned mesh axes (with explicit 1s) AND the
        # size-style knobs: _merge must OVERRIDE any parallelism settings
        # lingering in the base config (e.g. a previously written
        # optimal-config file), not inherit them. The batch wildcard axis
        # is placed by the runner (base configs may use fsdp=-1).
        patch["mesh"] = {"data": -1, "tensor": self.tensor, "seq": self.seq_par}
        patch["sequence_parallel_size"] = self.seq_par
        patch["tensor_parallel"] = {"tp_size": self.tensor}
        if self.offload:
            patch["zero_optimization"]["offload_optimizer"] = {"device": self.offload}
        if self.bucket_mb is not None:
            patch["zeropp"] = {"bucket_mb": self.bucket_mb}
        return patch


def _merge(base: Dict[str, Any], patch: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


class Autotuner:
    """Searches (mbs, gas, zero stage, remat) for a model + base config.

    ``model`` is a model-zoo Transformer (or any object with ``init``/
    ``loss`` and a dataclass ``config`` carrying ``remat``); ``batch_fn``
    makes a host batch for a global batch size: ``batch_fn(global_bs) ->
    dict``. Candidates that do not fit the per-device memory budget are
    pruned before compiling anything (reference: experiment pruning by
    model_info); survivors run ``profile_steps`` measured steps.
    """

    def __init__(self, model, base_config: Dict[str, Any],
                 batch_fn: Callable[[int], Dict[str, Any]],
                 tuning_config=None, world_size: Optional[int] = None,
                 profile_steps: int = 3, seq_len: Optional[int] = None,
                 journal_dir: Optional[str] = None):
        import jax

        self.model = model
        self.base = dict(base_config)
        self.base.pop("autotuning", None)
        self.batch_fn = batch_fn
        self.at = tuning_config
        self.world = world_size if world_size is not None else len(jax.devices())
        self.profile_steps = profile_steps
        mcfg = getattr(model, "config", None)
        self.seq_len = seq_len or getattr(mcfg, "max_seq_len", 1024)
        self.results: List[Candidate] = []
        # crash-safe tuning (ISSUE 14): with a journal_dir every measured
        # trial commits tmp+rename and a restarted tune resumes without
        # re-running it; None keeps the historical in-memory behavior.
        # Keys are namespaced by a fingerprint of everything the metric
        # depends on (base config, model geometry, world/seq/profile
        # setup) — a journal from a tune of a DIFFERENT config or model
        # must miss, not restore stale measurements under the same
        # candidate names.
        self.runner = ExperimentRunner(
            TrialJournal(journal_dir) if journal_dir else None)
        import hashlib
        import json as _json

        mdesc = repr(mcfg) if mcfg is not None else type(model).__name__
        self._journal_ns = hashlib.blake2b(
            _json.dumps([self.base, mdesc, self.world, self.seq_len,
                         self.profile_steps,
                         self.at.metric if self.at else "throughput",
                         # environment: a CPU-box journal must never
                         # satisfy the TPU-window tune (or survive a jax
                         # upgrade) — throughput is a property of the
                         # backend, not just the config
                         jax.default_backend(), jax.__version__,
                         getattr(jax.devices()[0], "device_kind", "")],
                        sort_keys=True, default=repr).encode(),
            digest_size=6).hexdigest()
        from .objectives import TrainingObjective

        self._objective = TrainingObjective(
            model, self.base, batch_fn, profile_steps=profile_steps,
            seq_len=self.seq_len,
            metric=(self.at.metric if self.at else "throughput"))

    # -- search space --------------------------------------------------

    def candidates(self, mbs_list: Optional[Sequence[int]] = None,
                   gas_list: Sequence[int] = (1, 2),
                   stages: Sequence[int] = (1, 3),
                   remat_opts: Sequence[Optional[bool]] = (False, True),
                   tensor_list: Optional[Sequence[int]] = None,
                   offload_opts: Sequence[Optional[str]] = (None,),
                   seq_lens: Sequence[Optional[int]] = (None,),
                   seq_par_list: Sequence[int] = (1,),
                   bucket_mb_list: Sequence[Optional[int]] = (None,)) -> List[Candidate]:
        if mbs_list is None:
            lo = self.at.min_train_micro_batch_size_per_gpu if self.at else 1
            hi = self.at.max_train_micro_batch_size_per_gpu if self.at and \
                self.at.max_train_micro_batch_size_per_gpu else lo * 8
            n = self.at.num_tuning_micro_batch_sizes if self.at else 3
            mbs_list, m = [], lo
            while m <= hi and len(mbs_list) < n:
                mbs_list.append(m)
                m *= 2
        if tensor_list is None:
            # mp_size from the autotuning section (the reference tunes it,
            # autotuning/README.md); only splits that divide the device
            # count AND the head count are runnable
            mp = self.at.mp_size if self.at else 1
            tensor_list = [1] if mp <= 1 else [1, mp]
        heads = getattr(getattr(self.model, "config", None), "n_heads", None)
        tensor_list = [t for t in tensor_list
                       if self.world % t == 0 and (heads is None or heads % t == 0)]
        # tp x sp combos must jointly divide the device count (batch
        # shards over the remaining data extent)
        out = []
        for mbs, gas, z, r, t, off, sl, sp_, bkt in itertools.product(
                mbs_list, gas_list, stages, remat_opts, tensor_list,
                offload_opts, seq_lens, seq_par_list, bucket_mb_list):
            if self.world % (t * sp_):
                continue
            if self.at and self.at.max_train_batch_size and \
                    mbs * gas * (self.world // (t * sp_)) > self.at.max_train_batch_size:
                continue
            out.append(Candidate(mbs, gas, z, r, tensor=t, seq_par=sp_,
                                 offload=off, seq_len=sl, bucket_mb=bkt))
        return out

    # -- memory pruning ------------------------------------------------

    def _estimate(self, c: Candidate) -> int:
        import jax

        import numpy as np

        mcfg = getattr(self.model, "config", None)
        if mcfg is None:
            return 0  # no model info — skip pruning
        abstract = jax.eval_shape(self.model.init, jax.random.PRNGKey(0))
        n_params = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(abstract))
        remat = mcfg.remat if c.remat is None else c.remat
        return estimate_step_memory(
            n_params, mbs=c.micro_batch_size, seq_len=c.seq_len or self.seq_len,
            d_model=mcfg.d_model, n_layers=mcfg.n_layers, vocab_size=mcfg.vocab_size,
            zero_stage=c.zero_stage, world=self.world // (c.tensor * c.seq_par),
            remat=remat, tensor=c.tensor, seq_par=c.seq_par, offload=c.offload)

    # -- measurement ---------------------------------------------------

    def _run_one(self, c: Candidate) -> float:
        """One measured trial through the shared TrainingObjective
        (kept for API compatibility; tune() journals via the runner)."""
        return float(self._objective(c)["metric"])

    def _trial(self, c: Candidate) -> Dict[str, Any]:
        """Journal-shaped payload for one candidate: errors are recorded
        (and resumed) exactly like successes — a deterministic rerun
        must not re-pay a failed compile either."""
        try:
            detail = self._objective(c)
            return {"status": "ok", "metric": float(detail["metric"]),
                    "detail": {k: v for k, v in detail.items()
                               if k != "metric"}}
        except Exception as e:  # OOM or compile failure: record, move on
            status = "oom" if "memory" in str(e).lower() else "error"
            logger.warning(
                f"autotuning: {c.name} failed ({status}): {str(e)[:200]}")
            return {"status": status, "metric": None,
                    "detail": {"error": str(e)[:500]}}

    # -- main loop -----------------------------------------------------

    def tune(self, cands: Optional[List[Candidate]] = None) -> Tuple[Candidate, List[Candidate]]:
        budget = _hbm_bytes_per_device()
        cands = list(cands if cands is not None else self.candidates())
        if not cands:
            raise ConfigError("autotuning: empty candidate set")
        early_stop = self.at.tuner_early_stopping if self.at else 0
        best: Optional[Candidate] = None
        since_best = 0
        for c in cands:
            c.est_bytes = self._estimate(c)
            if c.est_bytes > budget:
                c.status = "pruned"
                log_dist(f"autotuning: {c.name} pruned "
                         f"({c.est_bytes/1e9:.1f}GB est > {budget/1e9:.1f}GB)", ranks=[0])
                continue
            payload, cached = self.runner.run_one(
                f"train:{self._journal_ns}:{c.name}",
                lambda c=c: self._trial(c))
            c.status = str(payload["status"])
            if cached:
                log_dist(f"autotuning: {c.name} restored from journal "
                         f"({c.status})", ranks=[0])
            if payload["metric"] is None:
                continue
            c.metric_val = float(payload["metric"])
            if best is None or c.metric_val > best.metric_val:
                best, since_best = c, 0
            else:
                since_best += 1
                if early_stop and since_best >= early_stop:
                    log_dist(f"autotuning: early stop after {since_best} non-improving", ranks=[0])
                    break
        self.results = cands
        if best is None:
            raise ConfigError("autotuning: no candidate ran successfully")
        return best, cands

    # -- output --------------------------------------------------------

    def write_results(self, best: Candidate, results_dir: Optional[str] = None) -> str:
        """Commit the results table and the tuned config atomically
        (tmp+rename — a kill mid-write leaves the previous files intact,
        ISSUE 14 satellite), sweeping any stale partials a previously
        killed writer left in the results dir."""
        results_dir = results_dir or (self.at.results_dir if self.at else "autotuning_results")
        os.makedirs(results_dir, exist_ok=True)
        sweep_stale_tmp(results_dir)
        table = [{
            "name": c.name, "status": c.status, "metric": None if c.metric_val != c.metric_val
            else c.metric_val, "est_gb": round(c.est_bytes / 1e9, 2),
            **c.as_config_patch(),
        } for c in self.results]
        atomic_write_json(
            os.path.join(results_dir, "autotuning_results.json"), table)
        tuned = _merge(self.base, best.as_config_patch())
        tuned.pop("train_batch_size", None)
        path = atomic_write_json(
            os.path.join(results_dir, "ds_config_optimal.json"), tuned)
        log_dist(f"autotuning: best = {best.name}; tuned config at {path}", ranks=[0])
        return path


def autotune(model, base_config: Dict[str, Any], batch_fn, **kw) -> Tuple[Dict[str, Any], Candidate]:
    """One-call API: returns (tuned_config_dict, best_candidate) and writes
    the results dir per the config's ``autotuning`` section. Trials journal
    into the results dir, so a killed tune rerun with the same config
    resumes instead of re-measuring (ISSUE 14)."""
    from ..config import SXConfig

    import jax

    world = kw.pop("world_size", len(jax.devices()))
    at = SXConfig.load(_merge(base_config, {"train_batch_size": base_config.get(
        "train_batch_size", world)}), world).autotuning
    kw.setdefault("journal_dir", at.results_dir)
    tuner = Autotuner(model, base_config, batch_fn, tuning_config=at,
                      world_size=world, **kw)
    best, _ = tuner.tune()
    tuner.write_results(best)
    return _merge(tuner.base, best.as_config_patch()), best
