"""The declared serving search space: typed knobs, hard constraints,
static pruning.

ISSUE 14 tentpole, part 1. The serving stack has grown five orthogonal
knob families — the scheduler's packing shape (``token_budget``,
``max_running``, ``chunk_min``/``chunk_bins``), the speculative lane
(``k``/``k_bins``/``drafter``), and the engine storage/kernel modes
(``kv_cache_dtype``, ``decode_kernel``, ``prefix_caching``) — whose
interactions nobody has searched. This module declares the space those
candidates live in and rejects the statically-impossible ones BEFORE any
engine is built or any trace is served:

- hard config constraints (the same invariants ``ServingConfig``
  enforces at construction — ``token_budget >= max_running * (k + 1)``
  with speculation on, ``chunk_min <= token_budget``, ...) so an invalid
  combination is a pruned candidate with a named reason, not a
  mid-search ``ConfigError``;
- the compile-shape-ladder bound: a warmed server's zero-recompile
  contract means every program a candidate can ever dispatch comes off
  its shape-bin ladder (``engine.program_shapes`` keys — decode row
  counts and table widths power-of-two binned, chunk sizes from
  ``chunk_bins``, verify widths from ``k_bins``).
  :meth:`ServingCandidate.program_ladder_bound` computes the
  width-invariant upper bound of that set from the declared ladders
  alone; candidates whose bound blows the ``SpaceContext.max_programs``
  budget are pruned statically — they would either recompile mid-trace
  or hold an unbounded executable cache, and measuring them wastes a
  trial either way (the objective asserts the runtime
  ``engine.program_shapes`` stays within this bound);
- optionally, KV arithmetic: a candidate whose running set cannot hold
  even ``1 / kv_overcommit`` of its worst-case KV footprint permanently
  thrashes the preemption path.

Every candidate serializes to a ``ServingConfig`` overlay dict
(:meth:`ServingCandidate.overlay`) loadable through
``InferenceConfig.from_dict`` / ``with_overlay`` — the artifact
``scripts/autotune_serving.py`` emits.
"""

from __future__ import annotations

import dataclasses
import itertools
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..config.config_utils import ConfigError

__all__ = ["ServingCandidate", "ServingSearchSpace", "SpaceContext",
           "pow2_bin_count"]

_KV_DTYPES = ("bf16", "int8", "fp8")
_DECODE_KERNELS = ("auto", "pallas", "xla")
_DRAFTERS = ("ngram", "model")
_MOE_IMPLS = ("auto", "capacity", "capacity_einsum", "ragged")

#: the axes ServingSearchSpace accepts, i.e. the tunable knob families
KNOWN_AXES = ("token_budget", "max_running", "chunk_min", "chunk_bins",
              "k", "drafter", "k_bins", "decode_kernel", "kv_cache_dtype",
              "prefix_caching",
              # tiered paged KV (ISSUE 15): park-instead-of-preempt
              # spill to the host tier, its hot-tail size, and how many
              # parked sequences prefetch-stage one tick ahead
              "spill_enabled", "hot_block_fraction", "prefetch_depth",
              # multi-tenant LoRA (ISSUE 18): resident adapter-pool slots
              # (0 = adapters off, None = inherit the base config's pool)
              # and how many queued-but-non-resident adapters stage into
              # pinned buffers one tick ahead of their expected acquire
              "adapter_slots", "adapter_prefetch_depth",
              # expert-parallel MoE serving (ISSUE 19): the routed-FFN
              # capacity factor (headroom over balanced expert load) and
              # the routing implementation the serving engines pin
              "moe_capacity_factor", "moe_impl")


def pow2_bin_count(n: int) -> int:
    """Number of power-of-two bins covering row counts 1..n — the
    engine's ``_bucket`` binning (1, 2, 4, ... up to the covering power
    of two), so the per-axis factor of the program-ladder bound."""
    n = max(1, int(n))
    count, b = 1, 1
    while b < n:
        b *= 2
        count += 1
    return count


def _bins_tag(bins: Sequence[int]) -> str:
    """Compact, distinct rendering of a declared bin ladder for candidate
    names (and journal filenames — a 256-entry ladder spelled out would
    blow the 255-byte filename limit): short ladders list their entries,
    long ones carry count+range+checksum."""
    bins = tuple(int(b) for b in bins)
    if len(bins) <= 6:
        return "-".join(map(str, bins))
    return (f"{len(bins)}x{bins[0]}-{bins[-1]}h"
            f"{zlib.crc32(repr(bins).encode()) & 0xFFFF:04x}")


def _ladder(lo: int, hi: int,
            declared: Optional[Sequence[int]]) -> Tuple[int, ...]:
    """The doubling ladder ``ServingConfig.bins()`` / ``SpeculativeConfig
    .bins()`` derive (declared bins win) — replicated here so pruning
    never needs to construct a config object for an invalid candidate."""
    if declared:
        return tuple(sorted({int(b) for b in declared}))
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(dict.fromkeys(out))


@dataclasses.dataclass
class SpaceContext:
    """Everything a static constraint needs to know about the engine and
    workload the candidates will be measured against — pool geometry for
    the KV arithmetic, the compile budget for the ladder bound, and the
    trace's worst-case request footprint."""

    max_seq_len: int
    kv_block_size: int
    num_kv_blocks: int
    #: warmed-server zero-recompile budget: candidates whose static
    #: program-ladder bound exceeds this are pruned unmeasured
    max_programs: int = 256
    #: longest prompt + max_new the trace offers (None = unknown)
    request_tokens_hi: Optional[int] = None
    #: None disables the KV-thrash constraint; a float f prunes
    #: candidates whose max_running * worst-case blocks > f * usable
    kv_overcommit: Optional[float] = None
    #: multi-tenant LoRA pool geometry (ISSUE 18): bytes ONE padded
    #: adapter slot spends in HBM at the pool's rank ceiling — i.e.
    #: ``inference.adapters.pool_bytes(tcfg, 0, max_rank)``, which is
    #: exactly one slot's worth since the device pool carries slots+1.
    #: None disables the pool-footprint constraint.
    adapter_slot_bytes: Optional[int] = None
    #: HBM bytes a candidate's adapter pool may spend (slots+1 slots x
    #: adapter_slot_bytes must fit). None disables the constraint.
    adapter_hbm_budget: Optional[int] = None
    #: expert-pool geometry (ISSUE 19): expert count of the model the
    #: candidates will serve (None/0 = dense — moe axes are inert and
    #: non-default moe knobs prune statically), the router's top-k, and
    #: the gating floor ``compute_capacity`` clamps to
    moe_experts: Optional[int] = None
    moe_top_k: int = 2
    moe_min_capacity: int = 4

    @property
    def usable_blocks(self) -> int:
        return max(1, self.num_kv_blocks - 1)   # block 0 is scratch

    def blocks_for(self, tokens: int) -> int:
        return -(-max(1, int(tokens)) // self.kv_block_size)


@dataclasses.dataclass
class ServingCandidate:
    """One point in the serving knob space. Field defaults mirror the
    ``ServingConfig``/``InferenceConfig`` defaults, so
    ``ServingCandidate()`` IS the default config the tuned winner must
    beat. ``k = 0`` means speculation off (``k >= 1`` enables it at that
    draft width)."""

    token_budget: int = 256
    max_running: int = 8
    chunk_min: int = 16
    chunk_bins: Optional[Tuple[int, ...]] = None
    k: int = 0
    drafter: str = "ngram"
    k_bins: Optional[Tuple[int, ...]] = None
    decode_kernel: str = "auto"
    kv_cache_dtype: str = "bf16"
    prefix_caching: Optional[bool] = None   # None = keep the base config's
    # tiered paged KV (ISSUE 15): None keeps the base config's tier;
    # True/False toggles park-instead-of-preempt spill explicitly
    spill_enabled: Optional[bool] = None
    hot_block_fraction: float = 0.0
    prefetch_depth: int = 1
    # multi-tenant LoRA (ISSUE 18): None keeps the base config's pool;
    # an int >= 1 sets the resident slot count (enabling adapters);
    # 0 disables adapters explicitly
    adapter_slots: Optional[int] = None
    adapter_prefetch_depth: int = 1
    # expert-parallel MoE serving (ISSUE 19): None / "auto" keep the base
    # config's ``serving.moe`` section; a float capacity factor or a
    # pinned impl overlays it (only meaningful on expert-routed models —
    # check() prunes them as inert on dense ones)
    moe_capacity_factor: Optional[float] = None
    moe_impl: str = "auto"
    # search bookkeeping (mutated by the space/search, not identity)
    status: str = "pending"      # pending | pruned_static | ...
    prune_reason: str = ""

    @property
    def name(self) -> str:
        n = f"tb{self.token_budget}_mr{self.max_running}_cm{self.chunk_min}"
        if self.chunk_bins:
            n += "_cb" + _bins_tag(self.chunk_bins)
        if self.k:
            n += f"_k{self.k}_{self.drafter}"
            if self.k_bins:
                n += "_kb" + _bins_tag(self.k_bins)
        if self.decode_kernel != "auto":
            n += f"_{self.decode_kernel}"
        if self.kv_cache_dtype != "bf16":
            n += f"_{self.kv_cache_dtype}"
        if self.prefix_caching is not None:
            n += "_pc1" if self.prefix_caching else "_pc0"
        if self.spill_enabled is not None:
            n += "_sp1" if self.spill_enabled else "_sp0"
        if self.spill_enabled is not False and (
                self.hot_block_fraction != 0.0 or self.prefetch_depth != 1):
            # live under True AND None (inherit — the base config's tier
            # may be on): a name that omitted them would let enumerate()'s
            # dedup collapse the whole hf/pd grid to one point. Under an
            # EXPLICIT False the knobs are inert, so the suffix is
            # dropped and dedup collapses the duplicates instead of the
            # search burning a measured trial per identical config
            n += f"_hf{self.hot_block_fraction:g}_pd{self.prefetch_depth}"
        if self.adapter_slots is not None:
            n += f"_as{self.adapter_slots}"
        if self.adapter_slots != 0 and self.adapter_prefetch_depth != 1:
            # same dedup discipline as the kv_tier knobs: the depth is
            # live under any slot count >= 1 AND under None (inherit —
            # the base config's pool may be on), but inert under an
            # EXPLICIT 0, where omitting the suffix lets enumerate()'s
            # dedup collapse the identical configs
            n += f"_apd{self.adapter_prefetch_depth}"
        # moe knobs: defaults (None / "auto") inherit the base config's
        # serving.moe section, so they get no suffix and enumerate()'s
        # dedup collapses the axes' inherit points into one candidate
        if self.moe_capacity_factor is not None:
            n += f"_mcf{self.moe_capacity_factor:g}"
        if self.moe_impl != "auto":
            n += f"_moe-{self.moe_impl}"
        return n

    # -- ladders (static; no config construction) -----------------------

    def chunk_ladder(self) -> Tuple[int, ...]:
        return _ladder(self.chunk_min, self.token_budget, self.chunk_bins)

    def k_ladder(self) -> Tuple[int, ...]:
        if not self.k:
            return ()
        return _ladder(1, self.k, self.k_bins)

    def program_ladder_bound(self) -> int:
        """Width-invariant upper bound on the warmed server's compiled
        program set (``engine.program_shapes`` keys): ``decode`` keys bin
        row counts to powers of two, ``extend`` multiplies by the chunk
        ladder, ``mixed`` by decode×prefill row bins, and the ``spec``
        lane by verify-row bins × the k ladder. Block-table width adds a
        sequence-length-dependent factor identical across candidates of
        one search (same engine geometry), so comparing this bound
        against ``SpaceContext.max_programs`` ranks candidates by the
        only thing they control: their declared ladders."""
        nb = pow2_bin_count(self.max_running)
        nc = len(self.chunk_ladder())
        decode = nb
        extend = nb * nc
        mixed = nb * nb * nc
        spec = 0
        if self.k:
            nk = len(self.k_ladder())
            # spec keys carry decode, prefill AND verify row-count bins
            # plus the chunk and verify-width ladders
            spec = nb * nb * nb * nc * nk
        return decode + extend + mixed + spec

    # -- serialization / application ------------------------------------

    def overlay(self) -> Dict[str, object]:
        """The candidate as a loadable config overlay: merge into a
        DS-style inference-config dict (or apply with
        ``InferenceConfig.with_overlay``) to serve at this point."""
        sv: Dict[str, object] = {
            "token_budget": self.token_budget,
            "max_running": self.max_running,
            "chunk_min": self.chunk_min,
        }
        if self.chunk_bins:
            sv["chunk_bins"] = list(self.chunk_bins)
        if self.k:
            spec: Dict[str, object] = {"enabled": True, "k": self.k,
                                       "drafter": self.drafter}
            if self.k_bins:
                spec["k_bins"] = list(self.k_bins)
            sv["speculative"] = spec
        else:
            sv["speculative"] = {"enabled": False}
        out: Dict[str, object] = {
            "serving": sv,
            "decode_kernel": self.decode_kernel,
            "kv_cache_dtype": self.kv_cache_dtype,
        }
        if self.prefix_caching is not None:
            out["prefix_caching"] = self.prefix_caching
        if self.spill_enabled is not None:
            out["kv_tier"] = {
                "enabled": self.spill_enabled,
                "hot_block_fraction": self.hot_block_fraction,
                "prefetch_depth": self.prefetch_depth,
            }
        elif self.hot_block_fraction != 0.0 or self.prefetch_depth != 1:
            # spill inherits the base config's tier, but the searched
            # knobs must still land — with_overlay merges this partial
            # section over the base's, keeping its enabled flag
            out["kv_tier"] = {
                "hot_block_fraction": self.hot_block_fraction,
                "prefetch_depth": self.prefetch_depth,
            }
        if self.adapter_slots is not None:
            if self.adapter_slots:
                out["adapters"] = {
                    "enabled": True,
                    "slots": self.adapter_slots,
                    "prefetch_depth": self.adapter_prefetch_depth,
                }
            else:
                out["adapters"] = {"enabled": False}
        elif self.adapter_prefetch_depth != 1:
            # slot count inherits the base config's pool, but the
            # searched prefetch depth must still land — with_overlay
            # merges this partial section over the base's, keeping its
            # enabled flag and slot/rank geometry
            out["adapters"] = {
                "prefetch_depth": self.adapter_prefetch_depth,
            }
        # moe: a partial serving.moe section — with_overlay merges it
        # over the base's, keeping the knobs the candidate didn't search
        # (overload policy/threshold)
        moe: Dict[str, object] = {}
        if self.moe_capacity_factor is not None:
            moe["capacity_factor"] = self.moe_capacity_factor
        if self.moe_impl != "auto":
            moe["moe_impl"] = self.moe_impl
        if moe:
            sv["moe"] = moe
        return out

    def apply(self, base_icfg):
        """A new ``InferenceConfig`` = ``base_icfg`` with this candidate's
        knobs applied (validated by the config's own invariants — a
        candidate that passed :meth:`ServingSearchSpace.check` cannot
        raise here, which is the point of checking statically first)."""
        return base_icfg.with_overlay(self.overlay())

    @classmethod
    def from_config(cls, icfg) -> "ServingCandidate":
        """The candidate occupying ``icfg``'s point in the space — the
        baseline every search measures its winner against."""
        sv = icfg.serving
        spec = sv.speculative
        # the serving.moe section always exists (with defaults), so map
        # section-default values back to the candidate's inherit point —
        # otherwise every dense-model baseline would read as "moe-tuned"
        # and check()'s inert-axis prune would reject the whole search
        moe_default = type(sv.moe)()
        return cls(
            token_budget=sv.token_budget, max_running=sv.max_running,
            chunk_min=sv.chunk_min, chunk_bins=sv.chunk_bins,
            k=spec.k if spec.enabled else 0, drafter=spec.drafter,
            k_bins=spec.k_bins if spec.enabled else None,
            decode_kernel=icfg.decode_kernel,
            kv_cache_dtype=icfg.kv_cache_dtype,
            prefix_caching=icfg.prefix_caching,
            spill_enabled=icfg.kv_tier.enabled,
            hot_block_fraction=icfg.kv_tier.hot_block_fraction,
            prefetch_depth=icfg.kv_tier.prefetch_depth,
            adapter_slots=(icfg.adapters.slots
                           if icfg.adapters.enabled else 0),
            adapter_prefetch_depth=icfg.adapters.prefetch_depth,
            moe_capacity_factor=(
                None if sv.moe.capacity_factor == moe_default.capacity_factor
                else sv.moe.capacity_factor),
            moe_impl=sv.moe.moe_impl)


class ServingSearchSpace:
    """A grid of :class:`ServingCandidate` points: per-knob value axes
    applied over a base candidate, statically checked against a
    :class:`SpaceContext`. ``enumerate()`` returns EVERY grid point —
    infeasible ones carry ``status="pruned_static"`` and a named
    ``prune_reason``, and the runner refuses to measure them."""

    def __init__(self, axes: Dict[str, Sequence], context: SpaceContext,
                 base: Optional[ServingCandidate] = None):
        unknown = set(axes) - set(KNOWN_AXES)
        if unknown:
            raise ConfigError(
                f"unknown serving search axes {sorted(unknown)} "
                f"(known: {sorted(KNOWN_AXES)})")
        for name, vals in axes.items():
            if not isinstance(vals, (list, tuple)) or not len(vals):
                raise ConfigError(
                    f"axis {name!r} must be a non-empty list of values, "
                    f"got {vals!r}")
        self.axes = {k: list(v) for k, v in axes.items()}
        self.context = context
        self.base = base if base is not None else ServingCandidate()

    def __len__(self) -> int:
        n = 1
        for vals in self.axes.values():
            n *= len(vals)
        return n

    def enumerate(self) -> List[ServingCandidate]:
        names = sorted(self.axes)   # deterministic candidate order
        out: List[ServingCandidate] = []
        seen = set()
        for combo in itertools.product(*(self.axes[n] for n in names)):
            patch = dict(zip(names, combo))
            for key in ("chunk_bins", "k_bins"):
                if patch.get(key) is not None:
                    patch[key] = tuple(patch[key])
            cand = dataclasses.replace(self.base, status="pending",
                                       prune_reason="", **patch)
            if cand.name in seen:   # axes can alias (e.g. k=0 x drafter)
                continue
            seen.add(cand.name)
            ok, why = self.check(cand)
            if not ok:
                cand.status = "pruned_static"
                cand.prune_reason = why
            out.append(cand)
        return out

    # -- the hard constraints -------------------------------------------

    def check(self, c: ServingCandidate) -> Tuple[bool, str]:
        """(feasible, reason-if-not). Mirrors every ``ServingConfig``
        construction invariant plus the search-only bounds (compile
        budget, KV arithmetic), so a candidate passing here can always be
        applied to the base config without raising."""
        ctx = self.context
        if c.token_budget < 1:
            return False, f"token_budget {c.token_budget} < 1"
        if not 1 <= c.max_running <= c.token_budget:
            return False, (f"max_running {c.max_running} outside "
                           f"[1, token_budget={c.token_budget}]")
        if not 1 <= c.chunk_min <= c.token_budget:
            return False, (f"chunk_min {c.chunk_min} outside "
                           f"[1, token_budget={c.token_budget}]")
        if c.chunk_bins is not None and (not c.chunk_bins
                                         or min(c.chunk_bins) < 1):
            return False, f"chunk_bins {c.chunk_bins!r} must be positive"
        if c.k < 0:
            return False, f"k {c.k} < 0"
        if c.k:
            if c.drafter not in _DRAFTERS:
                return False, f"drafter {c.drafter!r} not in {_DRAFTERS}"
            if c.token_budget < c.max_running * (c.k + 1):
                return False, (
                    f"token_budget {c.token_budget} < max_running * (k+1) "
                    f"= {c.max_running} * {c.k + 1} — every running "
                    f"sequence may submit k drafts plus its pending token")
            if c.k_bins is not None and (not c.k_bins or min(c.k_bins) < 1
                                         or max(c.k_bins) < c.k):
                return False, (f"k_bins {c.k_bins!r} must be positive and "
                               f"cover k={c.k}")
        if c.decode_kernel not in _DECODE_KERNELS:
            return False, (f"decode_kernel {c.decode_kernel!r} not in "
                           f"{_DECODE_KERNELS}")
        if c.kv_cache_dtype not in _KV_DTYPES:
            return False, (f"kv_cache_dtype {c.kv_cache_dtype!r} not in "
                           f"{_KV_DTYPES}")
        if c.token_budget > ctx.max_seq_len * ctx.usable_blocks:
            return False, (f"token_budget {c.token_budget} exceeds the "
                           f"pool's total token capacity")
        # compile-shape-ladder budget: the zero-recompile contract's cost
        bound = c.program_ladder_bound()
        if bound > ctx.max_programs:
            return False, (
                f"program ladder bound {bound} exceeds the warmed-server "
                f"compile budget {ctx.max_programs} (chunk ladder "
                f"{len(c.chunk_ladder())} bins x row bins "
                f"{pow2_bin_count(c.max_running)}"
                + (f" x k ladder {len(c.k_ladder())} bins" if c.k else "")
                + ")")
        # tiered paged KV (ISSUE 15): knob validity, then geometry — the
        # tier changes what KV pressure MEANS (reclaimable-not-free), but
        # a single request must still fit the resident pool at dispatch
        if not 0.0 <= float(c.hot_block_fraction) <= 1.0:
            return False, (f"hot_block_fraction {c.hot_block_fraction} "
                           f"outside [0, 1]")
        if not isinstance(c.prefetch_depth, int) or c.prefetch_depth < 0:
            return False, f"prefetch_depth {c.prefetch_depth!r} must be >= 0"
        # one request must fit max_seq_len no matter what the tier does —
        # the engine rejects longer requests at submit, so a too-long
        # trace footprint is infeasible for EVERY candidate
        if (ctx.request_tokens_hi
                and ctx.request_tokens_hi > ctx.max_seq_len):
            return False, (
                f"trace request footprint {ctx.request_tokens_hi} "
                f"tokens exceeds max_seq_len {ctx.max_seq_len}")
        if c.spill_enabled and ctx.request_tokens_hi:
            worst = ctx.blocks_for(ctx.request_tokens_hi)
            if worst > ctx.usable_blocks:
                return False, (
                    f"spill cannot help: one request's {worst} worst-case "
                    f"blocks exceed the {ctx.usable_blocks}-block pool — "
                    f"dispatch needs FULL residency, so the tier only "
                    f"rotates sequences, never splits one past the pool")
            import math

            hot = int(math.ceil(c.hot_block_fraction * worst))
            if worst - hot < 1:
                return False, (
                    f"hot_block_fraction {c.hot_block_fraction} keeps all "
                    f"{worst} worst-case blocks hot — nothing is ever "
                    f"spillable, the tier is a no-op with bookkeeping cost "
                    f"(lower it or disable spill)")
        # multi-tenant LoRA (ISSUE 18): knob validity, then the static
        # pool-geometry bound — the device pool carries slots+1 padded
        # factor-pair slots (slot 0 is the null adapter), each costing a
        # fixed byte count at the rank ceiling, so a pool that blows the
        # HBM budget is known infeasible before any engine is built
        if c.adapter_slots is not None and (
                not isinstance(c.adapter_slots, int)
                or isinstance(c.adapter_slots, bool)
                or c.adapter_slots < 0):
            return False, (f"adapter_slots {c.adapter_slots!r} must be an "
                           f"int >= 0 (0 = adapters off) or None (inherit)")
        if not isinstance(c.adapter_prefetch_depth, int) \
                or c.adapter_prefetch_depth < 0:
            return False, (f"adapter_prefetch_depth "
                           f"{c.adapter_prefetch_depth!r} must be >= 0")
        if (c.adapter_slots and ctx.adapter_slot_bytes
                and ctx.adapter_hbm_budget is not None):
            need = (c.adapter_slots + 1) * ctx.adapter_slot_bytes
            if need > ctx.adapter_hbm_budget:
                return False, (
                    f"adapter pool geometry: {c.adapter_slots}+1 slots x "
                    f"{ctx.adapter_slot_bytes} padded-factor bytes = "
                    f"{need} exceeds the {ctx.adapter_hbm_budget}-byte "
                    f"adapter HBM budget")
        # expert-parallel MoE serving (ISSUE 19): knob validity, then
        # expert-pool geometry — on a dense model the moe axes are inert
        # (the engine never reads serving.moe), so non-default values
        # prune: they would burn a measured trial per point on configs
        # identical to the baseline
        if c.moe_impl not in _MOE_IMPLS:
            return False, f"moe_impl {c.moe_impl!r} not in {_MOE_IMPLS}"
        if c.moe_capacity_factor is not None \
                and not float(c.moe_capacity_factor) > 0:
            return False, (f"moe_capacity_factor {c.moe_capacity_factor} "
                           f"must be > 0")
        moe_tuned = (c.moe_capacity_factor is not None
                     or c.moe_impl != "auto")
        if moe_tuned and not ctx.moe_experts:
            return False, (
                "moe axes are inert on a dense model (SpaceContext."
                "moe_experts unset) — the candidate is config-identical "
                "to its moe-default twin")
        if (c.moe_capacity_factor is not None and ctx.moe_experts
                and c.moe_capacity_factor * ctx.moe_top_k
                > ctx.moe_experts):
            # capacity = ceil(S*k/E * cf) >= S once cf*k > E: no expert
            # can ever drop a token (each receives at most S), so the
            # capacity impl degenerates to dropless at strictly more
            # padded compute than impl="ragged" — over-provisioned
            return False, (
                f"moe_capacity_factor {c.moe_capacity_factor:g} x top_k "
                f"{ctx.moe_top_k} > {ctx.moe_experts} experts — per-expert "
                f"capacity covers every token, a dropless config at padded "
                f"cost (use moe_impl='ragged' instead)")
        # KV arithmetic: a running set that cannot hold 1/overcommit of
        # its worst case permanently lives in the preemption path —
        # UNLESS the tier is on, where overflow parks host-ward instead
        # of thrashing the preemption/replay path. Only a KNOWN-off tier
        # prunes: spill_enabled=None inherits the base config's tier at
        # apply time, which may be enabled — a static prune must never
        # drop a candidate that could be feasible (it can lose on merit,
        # it cannot lose unmeasured)
        if (ctx.kv_overcommit is not None and ctx.request_tokens_hi
                and c.spill_enabled is False):
            worst = c.max_running * ctx.blocks_for(ctx.request_tokens_hi)
            budget = ctx.kv_overcommit * ctx.usable_blocks
            if worst > budget:
                return False, (
                    f"max_running {c.max_running} x "
                    f"{ctx.blocks_for(ctx.request_tokens_hi)} worst-case "
                    f"blocks = {worst} exceeds {ctx.kv_overcommit}x the "
                    f"{ctx.usable_blocks}-block pool — permanent KV thrash "
                    f"(spill_enabled=True would park instead)")
        return True, ""
