"""Seeded, paired Poisson request traces for serving measurement.

Every serving perf number this repo publishes — the bench config-5
``serving_*`` rows and every autotuner trial — scores a scheduler against
a Poisson arrival trace. Candidate comparisons are only meaningful when
the candidates face the SAME trace: same prompts, same arrival offsets,
same per-request token budgets. This module makes that pairing explicit:
a :class:`PoissonTrace` is generated from one RNG seed, carries its seed
in every serialization, and every derived view (``head`` screening
subsets, ``with_load`` arrival calibration) is a pure function of the
parent — so two processes holding the same seed measure against
bit-identical workloads (the variance-control half of the ISSUE 14
successive-halving design, and the reproducibility half of the bench
rows' ``trace`` field).

Arrival offsets reproduce the bench rows' historical construction
exactly (``np.cumsum(rng.exponential(span / n, size=n))`` — a Poisson
process whose EXPECTED span offers ``load``× the measured capacity), so
routing the rows through :func:`poisson_arrivals` changed no published
number.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["PoissonTrace", "poisson_arrivals"]


def poisson_arrivals(rng: np.random.Generator, n: int, span: float) -> List[float]:
    """Cumulative Poisson-process arrival offsets: ``n`` exponential
    interarrivals with mean ``span / n`` (expected total span ``span``).
    The bench rows' historical construction, extracted verbatim so the
    autotuner's paired traces and the published rows draw from one
    implementation."""
    if n < 1:
        raise ValueError(f"need n >= 1 arrivals, got {n}")
    if span < 0:
        raise ValueError(f"span must be >= 0, got {span}")
    return np.cumsum(rng.exponential(span / n, size=n)).tolist()


@dataclasses.dataclass(frozen=True)
class PoissonTrace:
    """One reproducible serving workload: prompts + per-request max_new
    (+ arrival offsets once calibrated). Frozen: every mutation-shaped
    operation returns a new trace, so a trace object handed to N
    candidate trials cannot drift between them."""

    seed: int
    prompts: tuple                      # tuple of tuple[int] token prompts
    max_new: int
    arrivals: Optional[tuple] = None    # seconds from t0; None = all-at-once
    #: offered-load multiple the arrivals were calibrated at (with_load)
    load: Optional[float] = None
    #: capacity (tokens/s) the calibration measured — recorded so a trial
    #: log can state the absolute rate the candidates were offered
    capacity_tokens_per_sec: Optional[float] = None

    @classmethod
    def generate(cls, seed: int, *, vocab: int, n_requests: int,
                 prompt_lo: int, prompt_hi: int, max_new: int,
                 period: Optional[int] = None) -> "PoissonTrace":
        """Random-token prompts with lengths uniform in [prompt_lo,
        prompt_hi] (the bench rows' construction). ``period`` makes the
        prompts cycle every ``period`` tokens — the repetitive-suffix
        regime the speculative row measures in."""
        if not 1 <= prompt_lo <= prompt_hi:
            raise ValueError(
                f"need 1 <= prompt_lo <= prompt_hi, got [{prompt_lo}, {prompt_hi}]")
        rng = np.random.default_rng(seed)
        prompts = []
        for n in rng.integers(prompt_lo, prompt_hi + 1, size=n_requests):
            if period:
                cyc = rng.integers(1, vocab, size=period).tolist()
                prompts.append(tuple((cyc * (int(n) // period + 1))[:int(n)]))
            else:
                prompts.append(tuple(rng.integers(1, vocab, size=int(n)).tolist()))
        return cls(seed=int(seed), prompts=tuple(prompts), max_new=int(max_new))

    # -- derived views (pure; pairing-preserving) -----------------------

    def with_load(self, capacity_tokens_per_sec: float,
                  load: float) -> "PoissonTrace":
        """Calibrate arrivals: a Poisson process offering ``load``× the
        measured ``capacity_tokens_per_sec``. Drawn from a fresh RNG at
        this trace's seed, so the SAME (seed, capacity, load) triple
        always yields the same offsets — the pairing contract."""
        if capacity_tokens_per_sec <= 0:
            raise ValueError(
                f"capacity must be > 0, got {capacity_tokens_per_sec}")
        n = len(self.prompts)
        span = n * self.max_new / capacity_tokens_per_sec / load
        rng = np.random.default_rng(self.seed)
        return dataclasses.replace(
            self, arrivals=tuple(poisson_arrivals(rng, n, span)),
            load=float(load),
            capacity_tokens_per_sec=float(capacity_tokens_per_sec))

    def head(self, n: int) -> "PoissonTrace":
        """The first ``n`` requests (and their arrival offsets): the
        screening-fidelity view. A prefix, never a resample — a candidate
        promoted from a screening round was measured on a strict subset
        of the workload its final sees."""
        n = max(1, min(int(n), len(self.prompts)))
        return dataclasses.replace(
            self, prompts=self.prompts[:n],
            arrivals=self.arrivals[:n] if self.arrivals is not None else None)

    # -- consumption ----------------------------------------------------

    def __len__(self) -> int:
        return len(self.prompts)

    @property
    def total_new_tokens(self) -> int:
        return len(self.prompts) * self.max_new

    def request_tokens_hi(self) -> int:
        """Longest request footprint (prompt + generation) in tokens —
        the number admission constraints size against."""
        return max(len(p) for p in self.prompts) + self.max_new

    def prompt_lists(self) -> List[List[int]]:
        return [list(p) for p in self.prompts]

    def arrival_list(self) -> Optional[List[float]]:
        return list(self.arrivals) if self.arrivals is not None else None

    def describe(self) -> dict:
        """Machine-readable trace record for bench rows / trial logs —
        enough to reproduce the exact workload (seed + shape) and to
        audit the offsets actually offered."""
        return {
            "seed": self.seed,
            "n_requests": len(self.prompts),
            "prompt_lens": [len(p) for p in self.prompts],
            "max_new_tokens": self.max_new,
            "offered_load_x": self.load,
            "capacity_tokens_per_sec": (
                round(self.capacity_tokens_per_sec, 1)
                if self.capacity_tokens_per_sec is not None else None),
            "arrivals_s": ([round(a, 6) for a in self.arrivals]
                           if self.arrivals is not None else None),
        }
