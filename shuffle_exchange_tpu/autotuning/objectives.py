"""Measurement objectives: what one autotune trial actually runs.

ISSUE 14 tentpole, part 3. Both objectives speak the same protocol the
search/runner machinery consumes — a JSON-serializable dict with
``metric`` (higher is better) and ``feasible`` — so one results dir and
one halving schedule retune training AND serving.

:class:`ServingObjective` scores a :class:`~.space.ServingCandidate` on
the ``serving_goodput_row`` contract: build a fresh
``InferenceEngineV2`` + ``ContinuousBatchingScheduler`` at the
candidate's config, warm the shape-bin ladder (an all-at-once pass
compiles the capacity shapes, a Poisson replay covers the
arrival-dependent mixed bins), then serve the paired trace and read
sustained tokens/s as the metric with TTFT/TPOT p95 as constraints.
The warmed measured pass must compile NOTHING (``engine.program_shapes``
unchanged — the zero-recompile contract every trial asserts); a
candidate that recompiles mid-trace is marked infeasible, never best.

:class:`TrainingObjective` is the existing training measurement
(short-profiled ``train_batch`` steps through the real engine) extracted
from ``Autotuner._run_one`` so the legacy ``Autotuner`` API and any new
search both ride it.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional

from ..utils.logging import log_dist
from .space import ServingCandidate
from .trace import PoissonTrace

__all__ = ["ServingObjective", "TrainingObjective"]


class ServingObjective:
    """Goodput-primary, tail-latency-constrained serving score.

    ``ttft_p95_limit_s`` / ``tpot_p95_limit_s``: optional hard SLO
    constraints — a candidate whose measured p95 exceeds a limit is
    recorded with its metric but marked infeasible (ranked behind every
    feasible candidate, never promoted over one). ``require_zero_
    recompile`` (default on) marks a trial infeasible when the measured
    pass compiled any program the warm passes had not — a warmed
    production server must never recompile, so a config that does is
    broken at any goodput."""

    def __init__(self, model, params, base_icfg, *,
                 ttft_p95_limit_s: Optional[float] = None,
                 tpot_p95_limit_s: Optional[float] = None,
                 require_zero_recompile: bool = True,
                 max_warm_iters: int = 8):
        self.model = model
        self.params = params
        self.base_icfg = base_icfg
        self.ttft_p95_limit_s = ttft_p95_limit_s
        self.tpot_p95_limit_s = tpot_p95_limit_s
        self.require_zero_recompile = require_zero_recompile
        self.max_warm_iters = max(1, int(max_warm_iters))
        #: engines built (observability: statically-pruned candidates
        #: must never appear here)
        self.engines_built = 0

    def __call__(self, cand: ServingCandidate,
                 trace: PoissonTrace) -> Dict[str, object]:
        from ..inference import ContinuousBatchingScheduler, InferenceEngineV2

        icfg = cand.apply(self.base_icfg)
        eng = InferenceEngineV2(self.model, self.params, icfg)
        self.engines_built += 1
        prompts = trace.prompt_lists()
        arrivals = trace.arrival_list()

        # warm pass (all-at-once) compiles the capacity shapes of the
        # candidate's ladder; then Poisson replays measure — ADAPTIVELY.
        # Packing under arrivals is timing-dependent: two replays of the
        # same offsets can mix decode rows and prefill chunks into
        # different (rows, chunk) bin combos, so any single replay can
        # hit a combo no warm pass visited and compile it mid-trace,
        # poisoning the timing by orders of magnitude. The engine's
        # program set grows monotonically and is bounded by the shape
        # ladder, so the discipline is: serve the schedule; if the pass
        # compiled anything it WAS a warm pass — serve again — until a
        # pass compiles nothing (that clean pass is the measurement) or
        # the attempt budget runs out (the candidate's shape space does
        # not converge under warming: infeasible, which is exactly what
        # the zero-recompile gate exists to disqualify).
        ContinuousBatchingScheduler(eng).serve(
            prompts, max_new_tokens=trace.max_new)
        attempts = 0
        while True:
            warmed = eng.program_shapes
            sched = ContinuousBatchingScheduler(eng)
            sched.serve(prompts, max_new_tokens=trace.max_new,
                        arrivals=list(arrivals))
            attempts += 1
            recompiles = len(eng.program_shapes - warmed)
            if recompiles == 0 or attempts >= self.max_warm_iters:
                break
        st = sched.stats()

        goodput = float(st["sustained_tokens_per_sec"] or 0.0)
        feasible, why = True, ""
        if self.require_zero_recompile and recompiles:
            feasible, why = False, (
                f"{recompiles} program(s) compiled during the measured "
                f"pass — the warmed server recompiled")
        if (feasible and self.ttft_p95_limit_s is not None
                and st["ttft_p95_s"] is not None
                and st["ttft_p95_s"] > self.ttft_p95_limit_s):
            feasible, why = False, (
                f"ttft_p95 {st['ttft_p95_s']:.4f}s > limit "
                f"{self.ttft_p95_limit_s}s")
        if (feasible and self.tpot_p95_limit_s is not None
                and st["tpot_p95_s"] is not None
                and st["tpot_p95_s"] > self.tpot_p95_limit_s):
            feasible, why = False, (
                f"tpot_p95 {st['tpot_p95_s']:.4f}s > limit "
                f"{self.tpot_p95_limit_s}s")
        return {
            "metric": goodput,
            "feasible": feasible,
            "infeasible_reason": why,
            "goodput_tokens_per_sec": round(goodput, 2),
            "ttft_p50_s": _r(st["ttft_p50_s"]),
            "ttft_p95_s": _r(st["ttft_p95_s"]),
            "tpot_p50_s": _r(st["tpot_p50_s"]),
            "tpot_p95_s": _r(st["tpot_p95_s"]),
            "ticks": st["ticks"],
            "preemptions": st["preemptions"],
            "compiled_programs": len(eng.program_shapes),
            "program_ladder_bound": cand.program_ladder_bound(),
            "recompiles_measured_pass": recompiles,
            "warm_iters": attempts - 1,
            "knobs": sched.knobs(),
        }


def _r(v, nd: int = 4):
    return None if v is None else round(float(v), nd)


class TrainingObjective:
    """The training measurement the legacy ``Autotuner`` always ran, as
    a shared-protocol objective: build the engine at the candidate's
    merged config, one compile step, then ``profile_steps`` measured
    steps; metric = tokens/s (or negated latency when the autotuning
    section asks for it)."""

    def __init__(self, model, base_config: Dict[str, Any],
                 batch_fn: Callable[..., Dict[str, Any]], *,
                 profile_steps: int = 3, seq_len: int = 1024,
                 metric: str = "throughput"):
        self.model = model
        self.base = base_config
        self.batch_fn = batch_fn
        self.profile_steps = profile_steps
        self.seq_len = seq_len
        self.metric = metric

    def __call__(self, c) -> Dict[str, object]:
        import shuffle_exchange_tpu as sxt

        from ..parallel import reset_topology
        from .autotuner import _merge

        model = self.model
        mcfg = getattr(model, "config", None)
        if c.remat is not None and mcfg is not None and mcfg.remat != c.remat:
            model = type(model)(dataclasses.replace(mcfg, remat=c.remat))
        # The schema permits the batch wildcard (-1) only on mesh.data, so
        # the candidate's data=-1 never collides with a base wildcard.
        cfg = _merge(self.base, c.as_config_patch())
        cfg.pop("train_batch_size", None)
        reset_topology()
        engine, *_ = sxt.initialize(model=model, config=cfg)
        global_bs = engine.config.train_batch_size
        if c.seq_len:
            # seq-length candidates need a batch_fn(global_bs, seq_len=...)
            batch = self.batch_fn(global_bs, seq_len=c.seq_len)
        else:
            batch = self.batch_fn(global_bs)
        t_first = time.time()
        loss = engine.train_batch(batch)
        float(loss)  # sync (compile included; excluded from the metric)
        compile_s = time.time() - t_first
        t0 = time.time()
        for _ in range(self.profile_steps):
            loss = engine.train_batch(batch)
        float(loss)
        dt = (time.time() - t0) / self.profile_steps
        tokens = global_bs * (c.seq_len or self.seq_len)
        log_dist(f"autotuning: {c.name} step={dt*1000:.0f}ms "
                 f"(compile {compile_s:.0f}s, global_bs={global_bs})", ranks=[0])
        metric = -dt if self.metric == "latency" else tokens / dt
        return {
            "metric": metric,
            "feasible": True,
            "step_s": round(dt, 6),
            "compile_s": round(compile_s, 3),
            "tokens_per_step": tokens,
            "global_batch_size": int(global_bs),
        }
