"""Crash-safe experiment running: the trial journal and the runner.

ISSUE 14 tentpole, part 2b. An autotune search is hours of measured
trials on a TPU window that can be preempted at any moment; the
reference autotuner survives this by journaling every experiment to its
results dir and resuming from what is already measured. Same discipline
here, with the repo's checkpoint idioms applied:

- every committed trial is ONE file written tmp+rename
  (:func:`atomic_write_json` — the ``write_latest_tag`` idiom), so a
  kill at any byte leaves either a committed trial or a stale ``.tmp-*``
  file, never a torn JSON;
- :meth:`TrialJournal.resume` sweeps stale ``.tmp-*`` partials from a
  killed run and loads every committed trial, and
  :meth:`ExperimentRunner.run_one` consults the journal BEFORE running,
  so a resumed search re-runs nothing it already measured;
- the kill itself is continuously exercised through the
  ``testing/faults`` seam (site ``autotune_trial``: crash between the
  tmp write and the rename — the exact window a preemption tears).

The runner is objective-agnostic: the training tuner
(``autotuner.Autotuner``) and the serving search (``search.py``) both
ride it, which is what makes one tunnel window able to retune training
AND serving from a shared results dir.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ..testing import faults
from ..utils.invariants import atomic_on_reject
from ..utils.logging import logger

__all__ = ["Trial", "TrialJournal", "ExperimentRunner", "atomic_write_json"]

_TMP_RE = re.compile(r"\.tmp-[0-9a-f-]+$")


def _fsync_dir(dirpath: str) -> None:
    """Make a rename durable: fsync the parent directory (the
    checkpoint ``write_latest_tag`` discipline — without it a power cut
    after os.replace can lose the committed entry)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_json(path: str, obj) -> str:
    """Write ``obj`` as JSON via tmp+rename(+dir fsync) in the target
    directory — atomic AND durable, so readers (and resumed runs after a
    power loss) only ever see a complete document. Returns ``path``."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp-{uuid.uuid4().hex[:12]}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(d)
    return path


def sweep_stale_tmp(dirpath: str) -> int:
    """Remove ``*.tmp-*`` partials a killed run left behind; returns how
    many were swept (logged — a nonzero count documents the crash)."""
    swept = 0
    if not os.path.isdir(dirpath):
        return 0
    for name in os.listdir(dirpath):
        if _TMP_RE.search(name):
            try:
                os.remove(os.path.join(dirpath, name))
                swept += 1
            except OSError:   # concurrent sweep / perms: not our crash
                pass
    if swept:
        logger.warning(
            f"autotuning: swept {swept} stale partial trial file(s) from "
            f"{dirpath} (a previous run was killed mid-commit)")
    return swept


@dataclasses.dataclass
class Trial:
    """One measured (or to-be-measured) experiment: a candidate at a
    fidelity. ``key`` is the journal identity — stable across process
    restarts as long as the search space and schedule are unchanged."""

    key: str
    candidate_name: str
    round: int = 0
    fidelity: int = 0            # e.g. trace length measured at
    status: str = "pending"      # pending | ok | error | pruned_static
    metric: Optional[float] = None
    detail: Dict[str, object] = dataclasses.field(default_factory=dict)
    from_journal: bool = False   # True when resume() satisfied this trial

    def payload(self) -> Dict[str, object]:
        return {
            "key": self.key,
            "candidate": self.candidate_name,
            "round": self.round,
            "fidelity": self.fidelity,
            "status": self.status,
            "metric": self.metric,
            "detail": self.detail,
        }

    @classmethod
    def from_payload(cls, p: Dict[str, object]) -> "Trial":
        return cls(key=str(p["key"]), candidate_name=str(p["candidate"]),
                   round=int(p.get("round", 0)),
                   fidelity=int(p.get("fidelity", 0)),
                   status=str(p.get("status", "ok")),
                   metric=p.get("metric"),
                   detail=dict(p.get("detail") or {}),
                   from_journal=True)


def _safe_name(key: str) -> str:
    safe = re.sub(r"[^A-Za-z0-9._@=-]+", "_", key)
    if len(safe) > 120:   # stay far under the 255-byte filename limit
        digest = hashlib.blake2b(key.encode(), digest_size=8).hexdigest()
        safe = f"{safe[:100]}-{digest}"
    return safe


class TrialJournal:
    """Per-trial results journal under ``<results_dir>/trials/``: one
    committed JSON file per trial key, written tmp+rename. ``resume()``
    (run at construction) sweeps stale partials and loads everything
    committed, so the runner can skip already-measured work."""

    def __init__(self, results_dir: str):
        self.dir = os.path.join(results_dir, "trials")
        os.makedirs(self.dir, exist_ok=True)
        self.swept_stale = 0
        self._committed: Dict[str, Dict[str, object]] = {}
        self.resume()

    def __len__(self) -> int:
        return len(self._committed)

    def keys(self) -> List[str]:
        return sorted(self._committed)

    def get(self, key: str) -> Optional[Dict[str, object]]:
        return self._committed.get(key)

    def resume(self) -> Dict[str, Dict[str, object]]:
        """Sweep stale ``.tmp-*`` partials, then (re)load every committed
        trial file. A file that fails to parse is impossible through this
        writer (rename is atomic) and is treated as foreign: skipped with
        a warning, never deleted."""
        self.swept_stale += sweep_stale_tmp(self.dir)
        self._committed = {}
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.dir, name)
            try:
                with open(path) as f:
                    p = json.load(f)
                self._committed[str(p["key"])] = p
            except (json.JSONDecodeError, KeyError, OSError) as e:
                logger.warning(
                    f"autotuning: ignoring unreadable trial file {path}: {e}")
        return dict(self._committed)

    @atomic_on_reject(check="validate")
    def record(self, key: str, payload: Dict[str, object]) -> str:
        """Commit one trial atomically. Validates serializability BEFORE
        touching the filesystem or journal state (a rejected record
        mutates nothing); the ``autotune_trial`` fault site sits between
        the tmp write and the rename-commit — the window a kill tears —
        so the crash→resume contract is continuously drilled."""
        if key in self._committed:
            raise ValueError(f"trial {key!r} is already journaled "
                             f"(keys are run-unique; resume skips them)")
        payload = dict(payload)
        payload.setdefault("key", key)   # files are self-describing
        body = json.dumps(payload)   # raises on non-serializable detail
        del body
        path = os.path.join(self.dir, _safe_name(key) + ".json")
        tmp = f"{path}.tmp-{uuid.uuid4().hex[:12]}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        # the preemption window: a kill here leaves the stale tmp a
        # resume must sweep, and NO committed trial — exactly what a real
        # mid-commit SIGKILL leaves behind
        if faults.ACTIVE:
            faults.maybe_crash("autotune_trial", index=0)
        os.replace(tmp, path)
        _fsync_dir(self.dir)
        self._committed[key] = payload
        return path


class ExperimentRunner:
    """Runs trials through an optional journal: a journaled key is
    restored without execution, anything else is measured, committed,
    and counted in ``executed`` — the list tests (and the ci_full smoke)
    use to prove a resumed search re-ran nothing and that
    statically-pruned candidates were never measured."""

    def __init__(self, journal: Optional[TrialJournal] = None):
        self.journal = journal
        self.executed: List[str] = []

    def run_one(self, key: str,
                fn: Callable[[], Dict[str, object]]
                ) -> Tuple[Dict[str, object], bool]:
        """(payload, from_journal). ``fn`` produces the trial payload —
        a JSON-serializable dict with at least ``status``."""
        if self.journal is not None:
            cached = self.journal.get(key)
            if cached is not None:
                return cached, True
        payload = fn()
        if self.journal is not None:
            self.journal.record(key, payload)
        self.executed.append(key)
        return payload, False
