"""Autotuning subsystem (reference: ``autotuning/autotuner.py``, README
workflow ``autotuning/README.md:240-245``)."""

from .autotuner import Autotuner, Candidate, autotune, estimate_step_memory

__all__ = ["Autotuner", "Candidate", "autotune", "estimate_step_memory"]
