"""Autotuning subsystem (reference: ``autotuning/autotuner.py`` — 2,722
LoC of search space + experiment runner + result tables, workflow
``autotuning/README.md:240-245``).

Layout (ISSUE 14):

- ``space.py``      — declared serving knob space: typed candidates,
  hard constraints, static compile-ladder pruning
- ``trace.py``      — seeded, paired Poisson request traces
- ``search.py``     — grid + successive-halving search
- ``runner.py``     — crash-safe trial journal (tmp+rename, resume)
- ``objectives.py`` — serving goodput objective + the training objective
- ``autotuner.py``  — the legacy training ``Autotuner``/``autotune()``
  API, now a driver over the shared machinery

CLI entry points: ``python -m shuffle_exchange_tpu.autotuning`` (training)
and ``scripts/autotune_serving.py`` (serving).
"""

from .autotuner import Autotuner, Candidate, autotune, estimate_step_memory
from .objectives import ServingObjective, TrainingObjective
from .runner import ExperimentRunner, Trial, TrialJournal, atomic_write_json
from .search import SearchResult, SuccessiveHalving, halving_schedule
from .space import ServingCandidate, ServingSearchSpace, SpaceContext
from .trace import PoissonTrace, poisson_arrivals

__all__ = [
    "Autotuner", "Candidate", "autotune", "estimate_step_memory",
    "ServingObjective", "TrainingObjective",
    "ExperimentRunner", "Trial", "TrialJournal", "atomic_write_json",
    "SearchResult", "SuccessiveHalving", "halving_schedule",
    "ServingCandidate", "ServingSearchSpace", "SpaceContext",
    "PoissonTrace", "poisson_arrivals",
]
