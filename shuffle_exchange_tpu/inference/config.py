"""Inference config (reference ``inference/config.py:118`` DeepSpeedInferenceConfig
and ``inference/v2/config_v2.py`` RaggedInferenceEngineConfig).

One typed config covers both engines; unknown reference keys that are
CUDA-specific (cuda_graph, triton) are accepted and ignored with a log line
so reference configs load cleanly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from ..config.config_utils import ConfigError
from ..utils.logging import logger

_DTYPES = {"bf16": "bfloat16", "bfloat16": "bfloat16", "fp16": "float16",
           "float16": "float16", "fp32": "float32", "float32": "float32"}

_KV_CACHE_DTYPES = {"bf16": "bf16", "bfloat16": "bf16", "int8": "int8",
                    "fp8": "fp8", "float8": "fp8", "e4m3": "fp8"}


def _normalize_kv_cache_dtype(value) -> str:
    key = str(value).strip().lower()
    if key not in _KV_CACHE_DTYPES:
        raise ConfigError(
            f'kv_cache_dtype must be "bf16", "int8" or "fp8", got {value!r}')
    return _KV_CACHE_DTYPES[key]


@dataclasses.dataclass
class SpeculativeConfig:
    """Speculative decoding inside the one-dispatch serving step (ISSUE 8).

    A running sequence submits up to ``k`` draft tokens per tick; the
    scheduler verifies them in the SAME compiled mixed-batch dispatch that
    handles prefill chunks (the ``_extend_layer`` path is the verifier).
    Greedy acceptance — accept the longest draft prefix matching the
    verifier's argmax chain, then take the verifier's first correction —
    keeps an exact-token-parity contract with sequential ``decode_loop``
    under bf16 KV.

    Drafts come from a pluggable source:
      - ``drafter="ngram"`` — self-speculation / prompt-lookup: match the
        trailing ``ngram`` tokens of the sequence's history against its own
        earlier tokens and propose what followed (zero extra weights; wins
        on repetitive suffixes — code, structured output, multi-turn).
      - ``drafter="model"`` — a small draft model (``draft_model`` = an HF
        path/dir loaded via ``models/hf.py:from_hf``, or pass a drafter
        instance to the scheduler directly) running its own paged cache.

    ``k_bins`` is the verify-width ladder the mixed step compiles against
    (row width = k+1 for a k-draft row): like ``chunk_bins``, it bounds
    the compiled program set so a warmed server never recompiles. None
    derives powers of two up to ``k``."""

    enabled: bool = False
    k: int = 4                    # max draft tokens per sequence per tick
    drafter: str = "ngram"        # "ngram" | "model"
    ngram: int = 2                # match length for the prompt-lookup drafter
    draft_model: Optional[str] = None   # HF model path for drafter="model"
    k_bins: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if not isinstance(self.enabled, bool):
            raise ConfigError(
                f"serving.speculative.enabled must be a bool, got "
                f"{self.enabled!r}")
        if not isinstance(self.k, int) or self.k < 1:
            raise ConfigError(
                f"serving.speculative.k must be an int >= 1 (draft tokens "
                f"per sequence per tick), got {self.k!r}")
        if self.drafter not in ("ngram", "model"):
            raise ConfigError(
                f'serving.speculative.drafter must be "ngram" or "model", '
                f"got {self.drafter!r}")
        if not isinstance(self.ngram, int) or self.ngram < 1:
            raise ConfigError(
                f"serving.speculative.ngram must be an int >= 1, got "
                f"{self.ngram!r}")
        if self.drafter == "model" and self.enabled and not self.draft_model:
            # a drafter INSTANCE passed to the scheduler overrides this,
            # but a bare config asking for a model drafter with no model
            # is a mistake worth naming at config time
            logger.info(
                "serving.speculative: drafter='model' with no draft_model "
                "path — the scheduler needs an explicit drafter instance")
        if self.k_bins is not None:
            try:
                bins = tuple(sorted({int(b) for b in self.k_bins}))
            except (TypeError, ValueError) as e:
                raise ConfigError(
                    f"serving.speculative.k_bins must be a list of ints: "
                    f"{e}") from e
            if not bins or bins[0] < 1 or bins[-1] < self.k:
                raise ConfigError(
                    f"serving.speculative.k_bins must be positive and cover "
                    f"k={self.k}, got {self.k_bins!r}")
            self.k_bins = bins

    def bins(self) -> Tuple[int, ...]:
        """The draft-count ladder (ascending, covers k)."""
        if self.k_bins:
            return self.k_bins
        out, b = [], 1
        while b < self.k:
            out.append(b)
            b *= 2
        out.append(self.k)
        return tuple(dict.fromkeys(out))

    def bin_k(self, j: int) -> int:
        """Smallest ladder bin >= j (verify rows are padded to bin+1
        tokens so the warmed server's program set stays bounded)."""
        for b in self.bins():
            if j <= b:
                return b
        out = self.bins()[-1]
        while out < j:
            out *= 2
        return out


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling spec for the one-dispatch serving step
    (ISSUE 16). The defaults are exactly greedy decoding with no stop
    condition, so a request without params is bit-identical to the
    historical greedy scheduler.

    Sampling happens ON DEVICE inside the fused serving step: the
    sampled token at absolute sequence index ``i`` is
    ``argmax(filtered_logits / T + gumbel(fold_in(PRNGKey(seed), i)))``
    — a pure function of ``(seed, position, distribution)``. That makes
    every sampled chain deterministic and bit-exactly replayable across
    preemption/drain replay, failover re-prefill, and speculative
    verification (which samples the SAME chain at the same positions),
    and temperature 0 degenerates to plain argmax (greedy).

    - ``temperature``: 0 = greedy (top_k/top_p then ignored).
    - ``top_k``: keep the k highest logits (0 = off).
    - ``top_p``: nucleus — keep the smallest probability mass >= top_p
      of the temperature-scaled distribution (1.0 = off).
    - ``seed``: per-request PRNG seed; recorded so replays reproduce the
      chain bit-exactly.
    - ``eos_token_id``: on-device early-stop token (-1 = never stop);
      the EOS token itself is emitted, then the request finishes and its
      KV blocks free at that tick.
    - ``stop``: stop token SEQUENCES, matched host-side as a suffix of
      the generated tokens (the multi-token analog of EOS).
    - ``logit_mask``: constrained-decoding hook — a host callable
      ``mask(history_tokens) -> bool[vocab]`` (True = allowed) computed
      per step and applied in-dispatch (greedy and sampled rows both
      respect it). Not serializable: it never rides wire records."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_token_id: int = -1
    stop: Tuple[Tuple[int, ...], ...] = ()
    logit_mask: Optional[Any] = None

    def __post_init__(self):
        if (not isinstance(self.temperature, (int, float))
                or self.temperature < 0):
            raise ConfigError(
                f"sampling.temperature must be >= 0 (0 = greedy), got "
                f"{self.temperature!r}")
        object.__setattr__(self, "temperature", float(self.temperature))
        if not isinstance(self.top_k, int) or self.top_k < 0:
            raise ConfigError(
                f"sampling.top_k must be an int >= 0 (0 = off), got "
                f"{self.top_k!r}")
        if (not isinstance(self.top_p, (int, float))
                or not 0.0 < float(self.top_p) <= 1.0):
            raise ConfigError(
                f"sampling.top_p must be in (0, 1] (1 = off), got "
                f"{self.top_p!r}")
        object.__setattr__(self, "top_p", float(self.top_p))
        if (not isinstance(self.seed, int) or isinstance(self.seed, bool)
                or not 0 <= self.seed < 2 ** 31):
            raise ConfigError(
                f"sampling.seed must be an int in [0, 2**31) (it rides as "
                f"an int32 device operand), got {self.seed!r}")
        if not isinstance(self.eos_token_id, int) or self.eos_token_id < -1:
            raise ConfigError(
                f"sampling.eos_token_id must be an int >= -1 (-1 = never "
                f"stop), got {self.eos_token_id!r}")
        try:
            stop = tuple(tuple(int(t) for t in s) for s in (self.stop or ()))
        except (TypeError, ValueError) as e:
            raise ConfigError(
                f"sampling.stop must be a list of token sequences: {e}"
            ) from e
        if any(not s for s in stop):
            raise ConfigError(
                "sampling.stop sequences must be non-empty (an empty stop "
                "sequence would stop every request at its first token)")
        object.__setattr__(self, "stop", stop)
        if self.logit_mask is not None and not callable(self.logit_mask):
            raise ConfigError(
                f"sampling.logit_mask must be a callable "
                f"mask(history) -> bool[vocab] or None, got "
                f"{type(self.logit_mask).__name__}")

    @property
    def greedy(self) -> bool:
        """True when decoding draws no randomness (temperature 0)."""
        return self.temperature == 0.0

    def to_wire(self) -> dict:
        """JSON-friendly dict for records/snapshots (RolloutRecord,
        replay logs). ``logit_mask`` is a host callable and deliberately
        does NOT ride: a replayed record re-attaches its own mask."""
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed,
                "eos_token_id": self.eos_token_id,
                "stop": [list(s) for s in self.stop]}

    @classmethod
    def from_wire(cls, d: Optional[dict]) -> "Optional[SamplingParams]":
        if d is None:
            return None
        allowed = {"temperature", "top_k", "top_p", "seed", "eos_token_id",
                   "stop"}
        unknown = set(d) - allowed
        if unknown:
            raise ConfigError(
                f"unknown sampling keys {sorted(unknown)} "
                f"(allowed: {sorted(allowed)})")
        return cls(**{k: (tuple(tuple(s) for s in v) if k == "stop" else v)
                      for k, v in d.items()})


@dataclasses.dataclass
class KVTierConfig:
    """Tiered paged-KV storage (ISSUE 15): serving contexts larger than
    resident KV by spilling COLD blocks host-ward through the AIO
    pinned-buffer substrate (the same ``PinnedBufferPool`` path the
    disaggregated prefill->decode transfer stages through — byte-exact
    payload + scale planes, never re-quantized).

    The scheduler PARKS a sequence under KV pressure instead of
    preempting it: its exclusive blocks move to the host tier (the pool
    slots free up), the request keeps its generated tokens and engine
    descriptor, and a later tick FETCHES the bytes back into fresh
    blocks — no re-prefill compute, token-identical under greedy
    decoding (bf16 exact; int8/fp8 deterministic, the PR 6 contract,
    because the quantized planes round-trip byte-exactly).

    - ``hot_block_fraction``: fraction of a parked sequence's blocks
      KEPT resident (the tail of the decode window — its most recently
      written, first re-read blocks), so un-parking fetches only the
      cold prefix. 0.0 spills everything spillable.
    - ``prefetch_depth``: parked sequences whose host bytes are staged
      into pinned buffers one tick AHEAD of their expected un-park (the
      double-buffer: assembly runs off the fetch critical path; a fetch
      that finds its staging ready is a prefetch hit).
    - ``spill_dir``: optional directory for AsyncIOEngine file spill
      (the NVMe tier below host RAM); None keeps spilled bytes in host
      memory."""

    enabled: bool = False
    hot_block_fraction: float = 0.0
    prefetch_depth: int = 1
    spill_dir: Optional[str] = None

    def __post_init__(self):
        if not isinstance(self.enabled, bool):
            raise ConfigError(
                f"kv_tier.enabled must be a bool, got {self.enabled!r}")
        if (not isinstance(self.hot_block_fraction, (int, float))
                or not 0.0 <= float(self.hot_block_fraction) <= 1.0):
            raise ConfigError(
                f"kv_tier.hot_block_fraction must be in [0, 1] (fraction of "
                f"a parked sequence's blocks kept resident), got "
                f"{self.hot_block_fraction!r}")
        self.hot_block_fraction = float(self.hot_block_fraction)
        if not isinstance(self.prefetch_depth, int) or self.prefetch_depth < 0:
            raise ConfigError(
                f"kv_tier.prefetch_depth must be an int >= 0 (0 disables "
                f"prefetch staging), got {self.prefetch_depth!r}")
        if self.spill_dir is not None and not isinstance(self.spill_dir, str):
            raise ConfigError(
                f"kv_tier.spill_dir must be a path or None, got "
                f"{self.spill_dir!r}")


@dataclasses.dataclass
class AdapterConfig:
    """Multi-tenant LoRA serving (ISSUE 18): a fixed-slot HBM pool of
    rank-padded adapter factor pairs (``inference/adapters.py``) that a
    mixed-adapter batch gathers from per row inside the one-dispatch
    serving step. Slot indices are descriptor DATA — the compiled
    program set is independent of which (or how many) adapters exist.

    - ``slots``: resident adapters (device array carries slots+1; slot 0
      is the reserved all-zeros null adapter no-adapter rows gather).
    - ``max_rank``: LoRA rank ceiling; factors are zero-padded to it so
      every adapter shares one device shape (padding contributes 0).
    - ``targets``: attention projections adapted (FFN out of scope —
      the delta seam lives in the engine's attention layer body).
    - ``prefetch_depth``: adapters staged into pinned buffers ahead of
      their expected acquire (kv_tier's double-buffer half)."""

    enabled: bool = False
    slots: int = 4
    max_rank: int = 8
    targets: Tuple[str, ...] = ("wq", "wk", "wv", "wo")
    prefetch_depth: int = 1

    def __post_init__(self):
        if not isinstance(self.enabled, bool):
            raise ConfigError(
                f"adapters.enabled must be a bool, got {self.enabled!r}")
        for name in ("slots", "max_rank"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ConfigError(
                    f"adapters.{name} must be an int >= 1, got {v!r}")
        if not isinstance(self.prefetch_depth, int) \
                or self.prefetch_depth < 0:
            raise ConfigError(
                f"adapters.prefetch_depth must be an int >= 0 (0 disables "
                f"prefetch staging), got {self.prefetch_depth!r}")
        self.targets = tuple(self.targets)
        supported = ("wq", "wk", "wv", "wo")
        bad = [t for t in self.targets if t not in supported]
        if bad or not self.targets:
            raise ConfigError(
                f"adapters.targets must be a non-empty subset of "
                f"{supported}, got {self.targets!r}")


@dataclasses.dataclass
class MoEServingConfig:
    """Expert-capacity serving knobs (ISSUE 19): how the one-dispatch
    serving step routes MoE FFNs and how the scheduler treats expert
    load as an admission resource (the next one after KV blocks, tier
    residency, and adapter slots).

    - ``capacity_factor``: per-expert buffer slack for the capacity
      dispatch paths AND the admission pressure bar — balanced routing
      loads each expert to ``1/capacity_factor`` of its capacity, so the
      default 1.25 keeps balanced traffic below the park threshold.
      Overrides the model config's training-time ``capacity_factor``
      inside the serving engine only.
    - ``moe_impl``: forwarded to ``moe/layer.py::moe_layer`` ("auto"
      resolves exactly as training does — capacity under a scanned
      stack or an expert axis > 1, dropless ragged grouped-GEMM
      otherwise). "ragged" is the batch-composition-independent route
      the exact-token parity tests pin.
    - ``overload_policy``: "park" holds queued requests at their FIFO
      seat while the previous tick's routing counts exceed the
      capacity bar (park-don't-preempt — running sequences are never
      preempted for expert pressure); "drop" disables the admission
      gate and relies on the capacity path's GShard drop semantics.
    - ``overload_threshold``: load_max/capacity ratio at which "park"
      engages (1.0 = park when any expert would exceed its capacity).
    """

    capacity_factor: float = 1.25
    moe_impl: str = "auto"
    overload_policy: str = "park"
    overload_threshold: float = 1.0

    def __post_init__(self):
        self.capacity_factor = float(self.capacity_factor)
        if not self.capacity_factor > 0:
            raise ConfigError(
                f"serving.moe.capacity_factor must be > 0, got "
                f"{self.capacity_factor!r}")
        allowed = ("auto", "capacity", "capacity_einsum", "ragged")
        if self.moe_impl not in allowed:
            raise ConfigError(
                f"serving.moe.moe_impl must be one of {allowed}, got "
                f"{self.moe_impl!r}")
        if self.overload_policy not in ("park", "drop"):
            raise ConfigError(
                f"serving.moe.overload_policy must be 'park' or 'drop', "
                f"got {self.overload_policy!r}")
        self.overload_threshold = float(self.overload_threshold)
        if not self.overload_threshold > 0:
            raise ConfigError(
                f"serving.moe.overload_threshold must be > 0, got "
                f"{self.overload_threshold!r}")


@dataclasses.dataclass
class ServingConfig:
    """Continuous-batching scheduler knobs (``inference/scheduler.py`` —
    the Dynamic-SplitFuse scheduler the reference FastGen engine runs,
    SURVEY §2.10: mix one decode token per running sequence with prefill
    chunks from queued sequences into uniform-size steps).

    ``token_budget`` is the per-tick token target the scheduler packs —
    every running sequence contributes one decode token, the remainder is
    filled with prefill chunks. ``chunk_bins`` is the padded chunk-size
    ladder the mixed step compiles against (None derives chunk_min·2^k
    capped at token_budget), which together with the power-of-two decode
    and block-table bins bounds the number of compiled programs a serving
    process can ever need."""

    token_budget: int = 256
    max_running: int = 8          # cap on concurrently-decoding sequences
    chunk_min: int = 16           # smallest partial prefill chunk worth a slot
    chunk_bins: Optional[Tuple[int, ...]] = None
    # speculative decoding (ISSUE 8): k draft tokens per running sequence
    # per tick, verified in the same one-dispatch mixed step
    speculative: SpeculativeConfig = dataclasses.field(
        default_factory=SpeculativeConfig)
    # expert-parallel MoE serving (ISSUE 19): capacity factor, dispatch
    # impl, and the park-vs-drop expert-overload admission policy
    moe: MoEServingConfig = dataclasses.field(
        default_factory=MoEServingConfig)

    def __post_init__(self):
        if self.speculative is None:
            self.speculative = SpeculativeConfig()
        elif isinstance(self.speculative, dict):
            allowed = {f.name for f in dataclasses.fields(SpeculativeConfig)}
            unknown = set(self.speculative) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown serving.speculative config keys "
                    f"{sorted(unknown)} (allowed: {sorted(allowed)})")
            self.speculative = SpeculativeConfig(**self.speculative)
        if self.moe is None:
            self.moe = MoEServingConfig()
        elif isinstance(self.moe, dict):
            allowed = {f.name for f in dataclasses.fields(MoEServingConfig)}
            unknown = set(self.moe) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown serving.moe config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            self.moe = MoEServingConfig(**self.moe)
        if self.token_budget < 1:
            raise ConfigError(f"serving.token_budget must be >= 1, got "
                              f"{self.token_budget}")
        if not 1 <= self.max_running <= self.token_budget:
            raise ConfigError(
                f"serving.max_running must be in [1, token_budget="
                f"{self.token_budget}] (every running sequence takes one "
                f"budget slot per tick), got {self.max_running}")
        if (self.speculative.enabled
                and self.token_budget
                < self.max_running * (self.speculative.k + 1)):
            raise ConfigError(
                f"serving.token_budget ({self.token_budget}) must cover "
                f"max_running * (speculative.k + 1) = "
                f"{self.max_running} * {self.speculative.k + 1} — every "
                f"running sequence may submit k drafts plus its pending "
                f"token per tick; raise token_budget or lower "
                f"max_running/k")
        if not 1 <= self.chunk_min <= self.token_budget:
            raise ConfigError(
                f"serving.chunk_min must be in [1, token_budget="
                f"{self.token_budget}], got {self.chunk_min}")
        if self.chunk_bins is not None:
            try:
                bins = tuple(sorted({int(c) for c in self.chunk_bins}))
            except (TypeError, ValueError) as e:
                raise ConfigError(f"serving.chunk_bins must be a list of "
                                  f"ints: {e}") from e
            if not bins or bins[0] < 1:
                raise ConfigError(
                    f"serving.chunk_bins must be positive ints, got "
                    f"{self.chunk_bins!r}")
            self.chunk_bins = bins

    def bins(self) -> Tuple[int, ...]:
        """The padded chunk-size ladder (ascending)."""
        if self.chunk_bins:
            return self.chunk_bins
        out, b = [], self.chunk_min
        while b < self.token_budget:
            out.append(b)
            b *= 2
        out.append(self.token_budget)
        return tuple(dict.fromkeys(out))

    def bin_chunk(self, c: int) -> int:
        """Smallest ladder bin >= c (chunks past the ladder round up to the
        next power of two so a direct step() caller can't unbound compiles)."""
        for b in self.bins():
            if c <= b:
                return b
        out = self.bins()[-1]
        while out < c:
            out *= 2
        return out

    def knob_values(self) -> Dict[str, Any]:
        """The EFFECTIVE tunable serving knobs (ISSUE 14 introspection):
        what the scheduler actually packs/compiles against — derived
        ladders included — keyed by the autotuner's knob-family names, so
        trial logs and fleet post-mortems record the searched point, not
        just the raw config fields."""
        spec = self.speculative
        return {
            "token_budget": self.token_budget,
            "max_running": self.max_running,
            "chunk_min": self.chunk_min,
            "chunk_bins": list(self.bins()),
            "speculative_k": spec.k if spec.enabled else 0,
            "k_bins": list(spec.bins()) if spec.enabled else [],
            "drafter": spec.drafter if spec.enabled else None,
            # MoE serving (ISSUE 19): live only when the model has
            # experts, but always recorded — the trial log's point must
            # name the knobs it was (not) searched over either way
            "moe_capacity_factor": self.moe.capacity_factor,
            "moe_impl": self.moe.moe_impl,
        }


@dataclasses.dataclass
class AsyncSyncConfig:
    """Asynchronous shuffle-exchange weight sync for the serving fleet
    (ISSUE 20, ``serving/async_sync.py``): trainer + N replicas as peers
    on the decentralized topology (``runtime/sync/decentralized.py`` —
    the repo's namesake RR / shuffle / H-RR / Gossip edge schedules,
    SURVEY §2.1), with newest-version-wins weight propagation along the
    schedule's edges instead of the O(fleet) two-phase publish barrier.

    ``staleness_window`` is the serving contract: no ACTIVE replica may
    answer from weights more than W versions behind the newest published
    — a replica about to exceed it gets a forced catch-up edge the next
    sync step, ahead of the schedule. ``converge()`` on the router
    reduces the fleet to the reference's ``synchronization()``
    full-average on demand (bit-equal across peers)."""

    enabled: bool = False
    method: str = "Gossip"        # RR | shuffle | H-RR | Gossip
    rings: int = 2                # ring count for RR/H-RR/shuffle
    shuffle_step: int = 50        # re-randomize ring assignment every N steps
    gossip_prob: float = 1.0      # per-step send probability (Gossip)
    staleness_window: int = 4     # max versions a replica may trail by
    sync_interval_s: float = 0.05  # background sync-loop cadence (threads)
    seed: int = 0                 # topology RNG seed (deterministic edges)

    def __post_init__(self):
        if self.method not in ("RR", "shuffle", "H-RR", "Gossip"):
            raise ConfigError(
                f"router.sync.method must be one of RR|shuffle|H-RR|Gossip, "
                f"got {self.method!r}")
        for name in ("rings", "shuffle_step", "staleness_window"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ConfigError(
                    f"router.sync.{name} must be an int >= 1, got {v!r}")
        if not isinstance(self.gossip_prob, (int, float)) \
                or not 0.0 <= self.gossip_prob <= 1.0:
            raise ConfigError(
                f"router.sync.gossip_prob must be in [0, 1], got "
                f"{self.gossip_prob!r}")
        if not isinstance(self.sync_interval_s, (int, float)) \
                or self.sync_interval_s <= 0:
            raise ConfigError(
                f"router.sync.sync_interval_s must be > 0, got "
                f"{self.sync_interval_s!r}")
        if not isinstance(self.seed, int) or self.seed < 0:
            raise ConfigError(
                f"router.sync.seed must be an int >= 0, got {self.seed!r}")


@dataclasses.dataclass
class RouterConfig:
    """Multi-replica serving-front knobs (``serving/router.py`` — the
    ISSUE 7 replica router: N engine+scheduler replicas behind a placement
    policy, the Splitwise/DistServe-style fleet layer over the launcher's
    hostfile fan-out, SURVEY §1/§5.3).

    Placement scores every ACTIVE replica and picks the max:
    ``prefix_affinity_weight * hit_fraction - queue_depth_weight *
    normalized_queue - kv_pressure_weight * pool_fill``. Sticky sessions
    pin a ``session_id``'s later turns to the replica already holding its
    KV (the multi-turn prefix-cache win); drained/stopped replicas lose
    their stickiness. The autoscale bounds feed
    ``launcher/elastic_agent.AutoscalePolicy``."""

    num_replicas: int = 1
    sticky_sessions: bool = True
    prefix_affinity: bool = True
    prefix_affinity_weight: float = 1.0
    queue_depth_weight: float = 1.0
    kv_pressure_weight: float = 1.0
    # adapter-affinity placement (ISSUE 18): bonus for replicas whose
    # AdapterPool already holds the request's adapter resident — a hit
    # skips the host->HBM factor install (and a possible park), the
    # prefix-affinity argument applied to adapter weights
    adapter_affinity: bool = True
    adapter_affinity_weight: float = 1.0
    min_replicas: int = 1
    max_replicas: int = 8
    scale_up_queue_depth: float = 8.0    # mean queued reqs/replica to grow
    scale_down_queue_depth: float = 1.0  # mean queued reqs/replica to shrink
    # long-lived-process bounds: finished requests retained for result
    # pickup (oldest evicted past the cap — keep it above any serve()
    # batch size; 0 = unbounded), and sticky-session pins kept
    # least-recently-used (0 = unbounded)
    retain_finished: int = 4096
    max_sessions: int = 65536
    # -- fleet fault tolerance (ISSUE 12, serving/health.py + failover) --
    # Health: every replica heartbeats at tick entry; the monitor marks a
    # replica SUSPECT after `suspect_after_misses` expected beat periods
    # without one and DEAD after `dead_after_misses` (hysteresis: a
    # SUSPECT replica that beats returns to ACTIVE; DEAD is terminal and
    # triggers failover). A tick still IN FLIGHT counts as missing beats,
    # so a hung dispatch and a dead process converge on the same
    # thresholds; `tick_timeout_s` > 0 additionally arms a per-tick
    # watchdog (runtime/resilience.py idiom) that logs + counts the hang
    # the moment it exceeds the timeout, without waiting for the miss
    # budget. `tick_exception_strikes` consecutive RAISED ticks escalate
    # a SUSPECT replica to DEAD (one success resets the streak).
    heartbeat_interval_s: float = 0.25
    suspect_after_misses: int = 2
    dead_after_misses: int = 8
    health_check_interval_s: float = 0.05
    tick_timeout_s: float = 0.0
    tick_exception_strikes: int = 3
    # Failover: a request whose replica died mid-execution is re-placed on
    # a survivor at most `max_retries` times, backed off exponentially
    # (`retry_backoff_s * 2**(retries-1)` before it may pack again); after
    # `poison_death_threshold` replica deaths mid-execution it is
    # QUARANTINED (failed with a typed error) so one pathological input
    # cannot serially take the fleet down. `kv_migration` moves a HUNG
    # (reachable) replica's committed KV blocks to the survivor over the
    # disagg transfer channel instead of re-prefilling.
    max_retries: int = 3
    retry_backoff_s: float = 0.05
    poison_death_threshold: int = 2
    kv_migration: bool = True
    # Load shedding: 0 = off; otherwise new admissions are rejected with
    # a typed LoadShedError once the fleet's total queued requests cross
    # the bound (the SLO guard: a queue past this depth means deadlines
    # are already lost — refusing loudly beats timing out silently).
    shed_queue_depth: int = 0
    # -- cross-process fleet (ISSUE 17, serving/rpc.py + procfleet.py) --
    # "threads" keeps N in-process replicas (the fast CPU-correctness
    # path); "process" lifts the router<->replica boundary onto the RPC
    # transport: one real worker process per replica
    # (serving/worker.py), typed RpcTimeout/RpcConnectionLost errors
    # feeding the SUSPECT/DEAD machine, and pushed load reports instead
    # of shared-memory load() calls. The RPC knobs: `rpc_call_timeout_s`
    # bounds ordinary calls (submit/poll/drain/stage); `rpc_ping_timeout_s`
    # bounds the liveness probe (short — a worker that cannot answer a
    # ping inside it is hung, not slow: pings never wait on the replica
    # lock); connects retry `rpc_connect_retries` times behind
    # `rpc_connect_backoff_s * 2**k` capped at `rpc_backoff_cap_s` (plus
    # deterministic jitter — serving/rpc.py backoff_delays);
    # `worker_start_timeout_s` bounds the spawn->ready-file handshake
    # (cold workers sit in jax import + first compiles).
    fleet_mode: str = "threads"
    # -- async shuffle-exchange weight sync (ISSUE 20) --
    # Off by default: publishes keep the two-phase all-replica barrier.
    # Enabled, publishes stage only to the trainer peer's current edge
    # partners and background sync steps spread the version along the
    # decentralized schedule inside sync.staleness_window.
    sync: AsyncSyncConfig = dataclasses.field(
        default_factory=AsyncSyncConfig)
    rpc_call_timeout_s: float = 60.0
    rpc_ping_timeout_s: float = 5.0
    rpc_connect_retries: int = 5
    rpc_connect_backoff_s: float = 0.05
    rpc_backoff_cap_s: float = 2.0
    worker_start_timeout_s: float = 180.0

    def __post_init__(self):
        if self.sync is None:
            self.sync = AsyncSyncConfig()
        elif isinstance(self.sync, dict):
            allowed = {f.name for f in dataclasses.fields(AsyncSyncConfig)}
            unknown = set(self.sync) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown router.sync config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            self.sync = AsyncSyncConfig(**self.sync)
        if self.num_replicas < 1:
            raise ConfigError(
                f"router.num_replicas must be >= 1, got {self.num_replicas}")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ConfigError(
                f"router needs 1 <= min_replicas <= max_replicas, got "
                f"min={self.min_replicas} max={self.max_replicas}")
        for name in ("prefix_affinity_weight", "queue_depth_weight",
                     "kv_pressure_weight", "adapter_affinity_weight"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or v < 0:
                raise ConfigError(f"router.{name} must be >= 0, got {v!r}")
        if self.scale_down_queue_depth >= self.scale_up_queue_depth:
            raise ConfigError(
                f"router.scale_down_queue_depth "
                f"({self.scale_down_queue_depth}) must be below "
                f"scale_up_queue_depth ({self.scale_up_queue_depth}) — equal "
                f"thresholds make the autoscaler oscillate every step")
        for name in ("retain_finished", "max_sessions"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 0:
                raise ConfigError(
                    f"router.{name} must be an int >= 0 (0 = unbounded), "
                    f"got {v!r}")
        for name in ("heartbeat_interval_s", "health_check_interval_s"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or v <= 0:
                raise ConfigError(f"router.{name} must be > 0, got {v!r}")
        for name in ("suspect_after_misses", "dead_after_misses",
                     "tick_exception_strikes", "max_retries",
                     "poison_death_threshold"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ConfigError(
                    f"router.{name} must be an int >= 1, got {v!r}")
        if self.suspect_after_misses > self.dead_after_misses:
            raise ConfigError(
                f"router.suspect_after_misses ({self.suspect_after_misses}) "
                f"must not exceed dead_after_misses "
                f"({self.dead_after_misses}) — a replica must pass through "
                f"SUSPECT before DEAD (the hysteresis window)")
        for name in ("tick_timeout_s", "retry_backoff_s"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or v < 0:
                raise ConfigError(f"router.{name} must be >= 0, got {v!r}")
        if not isinstance(self.kv_migration, bool):
            raise ConfigError(
                f"router.kv_migration must be a bool, got "
                f"{self.kv_migration!r}")
        if not isinstance(self.shed_queue_depth, int) or self.shed_queue_depth < 0:
            raise ConfigError(
                f"router.shed_queue_depth must be an int >= 0 (0 = off), "
                f"got {self.shed_queue_depth!r}")
        if self.fleet_mode not in ("threads", "process"):
            raise ConfigError(
                f"router.fleet_mode must be 'threads' or 'process', got "
                f"{self.fleet_mode!r}")
        for name in ("rpc_call_timeout_s", "rpc_ping_timeout_s",
                     "rpc_connect_backoff_s", "rpc_backoff_cap_s",
                     "worker_start_timeout_s"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or v <= 0:
                raise ConfigError(f"router.{name} must be > 0, got {v!r}")
        if not isinstance(self.rpc_connect_retries, int) \
                or self.rpc_connect_retries < 0:
            raise ConfigError(
                f"router.rpc_connect_retries must be an int >= 0, got "
                f"{self.rpc_connect_retries!r}")


@dataclasses.dataclass
class InferenceConfig:
    # shared
    dtype: str = "bfloat16"
    tensor_parallel: int = 1                  # reference tp_size
    max_batch_size: int = 8                   # reference max_out_tokens sizing
    max_seq_len: int = 2048
    # v1 generate
    max_new_tokens: int = 128
    eos_token_id: int = -1                    # -1 = never stop early
    pad_token_id: int = 0
    # sampling defaults (overridable per generate() call)
    temperature: float = 0.0                  # 0 = greedy
    top_k: int = 0                            # 0 = off
    top_p: float = 1.0                        # 1 = off
    # kernels
    attention_impl: str = "auto"              # reference replace_with_kernel_inject
    # Fused per-layer decode path (ops/fused_decode.py: QKV+RoPE+KV-append,
    # split-K paged flash-decode, residual+MLP — the reference's
    # linear_blocked_kv_rotary + blocked_flash fusion):
    #   "auto"   — fused kernels on TPU, XLA layer body elsewhere
    #   "pallas" — force fused kernels (errors surface; model structures
    #              the kernels can't take raise at engine construction)
    #   "xla"    — force the reference XLA layer body
    decode_kernel: str = "auto"
    # quantization (reference quant.enabled / FP6): int8 weight-only.
    # Layer matmul weights use int8 STORAGE (QuantizedMatrix + Pallas
    # kernel) with groups capped at 256 along K (one scale row per kernel
    # K-block); larger values apply to the moe/unembed rounding path.
    quantize_weights: bool = False
    quant_bits: Any = 8            # 8 (int8), 4 (packed nibbles), "fp8" (e4m3)
    quant_group_size: int = 2048
    # v2 paged KV (reference ragged/kv_cache.py BlockedKVCache)
    kv_block_size: int = 64
    num_kv_blocks: int = 256
    # KV-cache storage dtype (paged engine): "bf16" stores at the serving
    # dtype (the historical behavior); "int8"/"fp8" store 1 byte/element
    # with per-token-per-head scale planes — decode is KV-bandwidth-bound,
    # so halving resident KV bytes ~doubles the binding resource AND the
    # resident batch (reference compression/quantization machinery, SURVEY
    # §2.11/§2.8, applied to the serving cache). Kernels dequantize
    # in-register on stream; the XLA gather path is the CPU numerics
    # oracle. One-shot put() prefill logits stay BIT-identical to bf16
    # mode (the prompt attends the full-precision in-flight chunk; only
    # storage is compressed), but CHUNKED prefill — the scheduler's
    # mixed ticks, or a prefix-cache suffix — reads earlier KV back
    # dequantized, so scheduler-served tokens under int8/fp8 are
    # approximate vs the sequential reference (greedy near-ties can
    # flip); bf16 mode keeps the exact-token serving parity guarantee.
    kv_cache_dtype: str = "bf16"
    # Prefix caching (paged engine): committed full KV blocks are hashed
    # (chained per-block token hash) and admitted sequences reuse matching
    # committed prefix blocks ref-counted instead of re-prefilling them;
    # copy-on-write protects shared blocks on divergence. Off by default:
    # a cache hit prefills only the suffix through the extend kernels,
    # whose reduction order differs from the cold batched-prefill program,
    # so outputs are token-identical in practice but not guaranteed
    # bit-identical — production serving configs opt in.
    prefix_caching: bool = False
    # tiered paged KV (ISSUE 15): cold blocks spill host-ward over the
    # AIO pinned-buffer substrate so serving contexts can outgrow the
    # resident pool; the scheduler parks/unparks under KV pressure
    kv_tier: KVTierConfig = dataclasses.field(default_factory=KVTierConfig)
    # multi-tenant LoRA serving (ISSUE 18): paged adapter pool + per-row
    # batched adapter application in the one-dispatch serving step
    adapters: AdapterConfig = dataclasses.field(default_factory=AdapterConfig)
    # continuous-batching scheduler (inference/scheduler.py, engine_v2.step)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    # multi-replica serving front (serving/router.py: placement, sticky
    # sessions, elastic drain/scale — ISSUE 7)
    router: RouterConfig = dataclasses.field(default_factory=RouterConfig)
    # default per-request sampling for the fused in-dispatch sampler
    # (ISSUE 16): applied to requests submitted without their own
    # SamplingParams. The dataclass default is exactly greedy with no
    # stop condition — the historical scheduler behavior.
    sampling: SamplingParams = dataclasses.field(
        default_factory=SamplingParams)
    # misc
    seed: int = 0

    def __post_init__(self):
        # direct construction accepts a plain dict for the serving section
        # (from_dict validates unknown keys with a nicer error first);
        # None means defaults (e.g. an empty YAML "serving:" section)
        if self.serving is None:
            self.serving = ServingConfig()
        elif isinstance(self.serving, dict):
            self.serving = ServingConfig(**self.serving)
        if self.router is None:
            self.router = RouterConfig()
        elif isinstance(self.router, dict):
            self.router = RouterConfig(**self.router)
        if self.kv_tier is None:
            self.kv_tier = KVTierConfig()
        elif isinstance(self.kv_tier, dict):
            allowed = {f.name for f in dataclasses.fields(KVTierConfig)}
            unknown = set(self.kv_tier) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown kv_tier config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            self.kv_tier = KVTierConfig(**self.kv_tier)
        if self.adapters is None:
            self.adapters = AdapterConfig()
        elif isinstance(self.adapters, dict):
            allowed = {f.name for f in dataclasses.fields(AdapterConfig)}
            unknown = set(self.adapters) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown adapters config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            self.adapters = AdapterConfig(**self.adapters)
        if self.sampling is None:
            self.sampling = SamplingParams()
        elif isinstance(self.sampling, dict):
            allowed = {f.name for f in dataclasses.fields(SamplingParams)}
            unknown = set(self.sampling) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown sampling config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            self.sampling = SamplingParams(**self.sampling)
        self.kv_cache_dtype = _normalize_kv_cache_dtype(self.kv_cache_dtype)
        if not isinstance(self.prefix_caching, bool):
            raise ConfigError(
                f"prefix_caching must be a bool, got "
                f"{self.prefix_caching!r} ({type(self.prefix_caching).__name__})")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "InferenceConfig":
        d = dict(d or {})
        # reference compat: nested tensor_parallel {"tp_size": n}, "tp_size" alias
        tp = d.pop("tensor_parallel", None)
        if isinstance(tp, dict):
            d["tensor_parallel"] = int(tp.get("tp_size", 1))
        elif tp is not None:
            d["tensor_parallel"] = int(tp)
        if "tp_size" in d:
            d["tensor_parallel"] = int(d.pop("tp_size"))
        if "replace_with_kernel_inject" in d:
            # kernel injection == our fused/pallas attention path
            d.setdefault("attention_impl", "auto" if d.pop("replace_with_kernel_inject") else "reference")
        if "quant" in d:
            q = d.pop("quant")
            if isinstance(q, dict):
                d["quantize_weights"] = bool(q.get("enabled", False))
                if "bits" in q:
                    d["quant_bits"] = q["bits"]   # normalized/validated below
        dtype = d.get("dtype")
        if dtype is not None:
            key = str(dtype).replace("torch.", "")
            if key == "int8":
                # reference dtype=torch.int8 means int8-quantized weights with
                # fp16 compute; here: weight-only quantization + bf16 compute.
                d["dtype"] = "bfloat16"
                d["quantize_weights"] = True
            elif key not in _DTYPES:
                raise ConfigError(f"unsupported inference dtype {dtype!r}")
            else:
                d["dtype"] = _DTYPES[key]
        dk = d.get("decode_kernel", "auto")
        if dk not in ("auto", "pallas", "xla"):
            raise ConfigError(
                f'decode_kernel must be "auto", "pallas" or "xla", got {dk!r}')
        if "kv_cache_dtype" in d:
            d["kv_cache_dtype"] = _normalize_kv_cache_dtype(d["kv_cache_dtype"])
        pc = d.get("prefix_caching", False)
        if not isinstance(pc, bool):
            raise ConfigError(
                f"prefix_caching must be a bool, got {pc!r} "
                f"({type(pc).__name__})")
        qb = d.get("quant_bits", 8)
        if str(qb).strip().lower() == "fp8":
            d["quant_bits"] = "fp8"
        else:
            try:
                qb_int = int(qb)
            except (TypeError, ValueError):
                qb_int = None
            if qb_int not in (8, 4):
                raise ConfigError(
                    f"quant_bits must be 8, 4 or \"fp8\", got {qb!r}")
            d["quant_bits"] = qb_int
        sv = d.get("serving")
        if sv is None:
            d.pop("serving", None)   # empty section -> defaults
        elif isinstance(sv, dict):
            allowed = {f.name for f in dataclasses.fields(ServingConfig)}
            unknown = set(sv) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown serving config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            d["serving"] = ServingConfig(**sv)
        elif sv is not None and not isinstance(sv, ServingConfig):
            raise ConfigError(f"serving must be a dict or ServingConfig, "
                              f"got {type(sv).__name__}")
        kt = d.get("kv_tier")
        if kt is None:
            d.pop("kv_tier", None)   # empty section -> defaults
        elif isinstance(kt, dict):
            allowed = {f.name for f in dataclasses.fields(KVTierConfig)}
            unknown = set(kt) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown kv_tier config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            d["kv_tier"] = KVTierConfig(**kt)
        elif not isinstance(kt, KVTierConfig):
            raise ConfigError(f"kv_tier must be a dict or KVTierConfig, "
                              f"got {type(kt).__name__}")
        ad = d.get("adapters")
        if ad is None:
            d.pop("adapters", None)   # empty section -> defaults
        elif isinstance(ad, dict):
            allowed = {f.name for f in dataclasses.fields(AdapterConfig)}
            unknown = set(ad) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown adapters config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            d["adapters"] = AdapterConfig(
                **{k: (tuple(v) if k == "targets" else v)
                   for k, v in ad.items()})
        elif not isinstance(ad, AdapterConfig):
            raise ConfigError(f"adapters must be a dict or AdapterConfig, "
                              f"got {type(ad).__name__}")
        smp = d.get("sampling")
        if smp is None:
            d.pop("sampling", None)   # empty section -> defaults
        elif isinstance(smp, dict):
            allowed = {f.name for f in dataclasses.fields(SamplingParams)}
            unknown = set(smp) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown sampling config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            d["sampling"] = SamplingParams(
                **{k: (tuple(tuple(s) for s in v) if k == "stop" else v)
                   for k, v in smp.items()})
        elif not isinstance(smp, SamplingParams):
            raise ConfigError(f"sampling must be a dict or SamplingParams, "
                              f"got {type(smp).__name__}")
        rt = d.get("router")
        if rt is None:
            d.pop("router", None)   # empty section -> defaults
        elif isinstance(rt, dict):
            allowed = {f.name for f in dataclasses.fields(RouterConfig)}
            unknown = set(rt) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown router config keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            d["router"] = RouterConfig(**rt)
        elif not isinstance(rt, RouterConfig):
            raise ConfigError(f"router must be a dict or RouterConfig, "
                              f"got {type(rt).__name__}")
        known = {f.name for f in dataclasses.fields(cls)}
        ignored = {k: d.pop(k) for k in list(d) if k not in known}
        if ignored:
            logger.info("InferenceConfig: ignoring CUDA-specific/unknown keys %s", sorted(ignored))
        try:
            return cls(**d)
        except TypeError as e:  # pragma: no cover
            raise ConfigError(f"bad inference config: {e}") from e

    # -- tunable-overlay seam (ISSUE 14) --------------------------------

    #: the top-level keys a serving overlay may carry (the serving knob
    #: families the autotuner searches; everything else about an engine —
    #: model geometry, pool size, dtypes — is NOT a serving knob and must
    #: not ride in through an overlay file)
    OVERLAY_KEYS = ("serving", "kv_cache_dtype", "decode_kernel",
                    "prefix_caching", "kv_tier", "adapters")

    def serving_overlay(self) -> Dict[str, Any]:
        """This config's point in the serving knob space as a standalone
        overlay dict — the artifact ``scripts/autotune_serving.py`` emits
        for its winner, loadable back with :meth:`with_overlay` (or by
        merging into a DS-style config dict before ``from_dict``)."""
        sv: Dict[str, Any] = {
            "token_budget": self.serving.token_budget,
            "max_running": self.serving.max_running,
            "chunk_min": self.serving.chunk_min,
        }
        if self.serving.chunk_bins:
            sv["chunk_bins"] = list(self.serving.chunk_bins)
        spec = self.serving.speculative
        if spec.enabled:
            sp: Dict[str, Any] = {"enabled": True, "k": spec.k,
                                  "drafter": spec.drafter}
            if spec.k_bins:
                sp["k_bins"] = list(spec.k_bins)
            sv["speculative"] = sp
        else:
            sv["speculative"] = {"enabled": False}
        out = {"serving": sv, "kv_cache_dtype": self.kv_cache_dtype,
               "decode_kernel": self.decode_kernel,
               "prefix_caching": self.prefix_caching}
        if self.kv_tier.enabled:
            out["kv_tier"] = {
                "enabled": True,
                "hot_block_fraction": self.kv_tier.hot_block_fraction,
                "prefetch_depth": self.kv_tier.prefetch_depth,
            }
        else:
            # spill OFF is a point in the knob space too (same shape as
            # the speculative section): an overlay from a tier-disabled
            # config applied to a tier-enabled base must turn spill off,
            # not silently inherit it
            out["kv_tier"] = {"enabled": False}
        if self.adapters.enabled:
            out["adapters"] = {
                "enabled": True,
                "slots": self.adapters.slots,
                "prefetch_depth": self.adapters.prefetch_depth,
            }
        else:
            out["adapters"] = {"enabled": False}
        return out

    def with_overlay(self, overlay: Dict[str, Any]) -> "InferenceConfig":
        """A new config = this one with a serving-knob overlay applied.
        Nested ``serving`` (and ``serving.speculative``) keys MERGE over
        the current values; the result passes full construction
        validation, so an overlay can never smuggle in an invariant
        violation a hand-written config would be refused for. Unknown
        keys are rejected by name (an overlay is a tuned artifact — a
        typo in one must fail loudly, not silently skip a knob)."""
        d = dict(overlay or {})
        unknown = set(d) - set(self.OVERLAY_KEYS)
        if unknown:
            raise ConfigError(
                f"unknown serving-overlay keys {sorted(unknown)} "
                f"(allowed: {sorted(self.OVERLAY_KEYS)})")
        serving = self.serving
        sv_patch = d.pop("serving", None)
        if sv_patch is not None:
            if not isinstance(sv_patch, dict):
                raise ConfigError(
                    f"overlay 'serving' must be a dict, got "
                    f"{type(sv_patch).__name__}")
            sv_patch = dict(sv_patch)
            allowed = {f.name for f in dataclasses.fields(ServingConfig)}
            unknown = set(sv_patch) - allowed
            if unknown:
                raise ConfigError(
                    f"unknown serving overlay keys {sorted(unknown)} "
                    f"(allowed: {sorted(allowed)})")
            spec_patch = sv_patch.pop("speculative", None)
            cur = {f.name: getattr(serving, f.name)
                   for f in dataclasses.fields(ServingConfig)}
            if spec_patch is not None:
                if not isinstance(spec_patch, dict):
                    raise ConfigError(
                        f"overlay 'serving.speculative' must be a dict, "
                        f"got {type(spec_patch).__name__}")
                sp_allowed = {f.name
                              for f in dataclasses.fields(SpeculativeConfig)}
                sp_unknown = set(spec_patch) - sp_allowed
                if sp_unknown:
                    raise ConfigError(
                        f"unknown speculative overlay keys "
                        f"{sorted(sp_unknown)} (allowed: "
                        f"{sorted(sp_allowed)})")
                sp_cur = {f.name: getattr(serving.speculative, f.name)
                          for f in dataclasses.fields(SpeculativeConfig)}
                cur["speculative"] = SpeculativeConfig(
                    **{**sp_cur, **spec_patch})
            serving = ServingConfig(**{**cur, **sv_patch})
        kt_patch = d.pop("kv_tier", None)
        kv_tier = self.kv_tier
        if kt_patch is not None:
            if not isinstance(kt_patch, dict):
                raise ConfigError(
                    f"overlay 'kv_tier' must be a dict, got "
                    f"{type(kt_patch).__name__}")
            kt_allowed = {f.name for f in dataclasses.fields(KVTierConfig)}
            kt_unknown = set(kt_patch) - kt_allowed
            if kt_unknown:
                raise ConfigError(
                    f"unknown kv_tier overlay keys {sorted(kt_unknown)} "
                    f"(allowed: {sorted(kt_allowed)})")
            kt_cur = {f.name: getattr(self.kv_tier, f.name)
                      for f in dataclasses.fields(KVTierConfig)}
            kv_tier = KVTierConfig(**{**kt_cur, **kt_patch})
        ad_patch = d.pop("adapters", None)
        adapters = self.adapters
        if ad_patch is not None:
            if not isinstance(ad_patch, dict):
                raise ConfigError(
                    f"overlay 'adapters' must be a dict, got "
                    f"{type(ad_patch).__name__}")
            ad_allowed = {f.name for f in dataclasses.fields(AdapterConfig)}
            ad_unknown = set(ad_patch) - ad_allowed
            if ad_unknown:
                raise ConfigError(
                    f"unknown adapters overlay keys {sorted(ad_unknown)} "
                    f"(allowed: {sorted(ad_allowed)})")
            ad_cur = {f.name: getattr(self.adapters, f.name)
                      for f in dataclasses.fields(AdapterConfig)}
            adapters = AdapterConfig(**{**ad_cur, **ad_patch})
        dk = d.get("decode_kernel")
        if dk is not None and dk not in ("auto", "pallas", "xla"):
            # __post_init__ leaves decode_kernel to from_dict; an overlay
            # bypasses from_dict, so validate here
            raise ConfigError(
                f'decode_kernel must be "auto", "pallas" or "xla", got {dk!r}')
        return dataclasses.replace(self, serving=serving, kv_tier=kv_tier,
                                   adapters=adapters, **d)

    def jax_dtype(self) -> Any:
        import jax.numpy as jnp

        return getattr(jnp, self.dtype)
