"""Paged KV cache: block allocator + block-table attention.

Capability analog of the reference v2 ragged stack:
  - ``BlockedAllocator`` (ragged/blocked_allocator.py:11) — host-side
    free-list of KV blocks.
  - ``BlockedKVCache`` (ragged/kv_cache.py:40) — here ``PagedKVCache``:
    per-layer-stacked block pool [L, nblocks, KV, block, Dh] on device.
  - ``blocked_flash`` + ``atom_builder`` + ``linear_blocked_kv_rotary``
    (inference/v2/kernels/ragged_ops/) — here ``paged_decode_attention``
    (gather-by-block-table attention; the Pallas kernel variant lives in
    ops/paged_attention.py and is dispatched when on TPU).

TPU-first notes: block tables are static-shape int32 arrays padded with -1;
gathers/scatters are XLA ops inside jit, so a whole decode step (append +
attention over all layers) is one compiled program.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import numpy as np


class BlockedAllocator:
    """Free-list allocator over ``num_blocks`` KV blocks (host side).

    Mirrors ragged/blocked_allocator.py:11 (allocate/free with a linked
    free-list); numpy-free python deque is plenty at host rates.
    """

    def __init__(self, num_blocks: int):
        if num_blocks <= 0:
            raise ValueError("num_blocks must be positive")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(f"out of KV blocks: want {n}, have {len(self._free)}")
        out, self._free = self._free[:n], self._free[n:]
        return out

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if not (0 <= b < self.num_blocks):
                raise ValueError(f"bad block id {b}")
        self._free.extend(blocks)
        assert len(self._free) <= self.num_blocks, "double free"


class PagedKVCache(NamedTuple):
    """Device block pool. k/v: [L, num_blocks, KV, block_size, Dh].

    KV is a LEADING dim (round 3): the Pallas decode kernel DMAs one kv
    head's block per grid step, which TPU block specs only allow on
    non-minor dims; {block_size, Dh} minor also makes blocks native
    (8,128)-tileable."""

    k: "object"
    v: "object"

    @classmethod
    def create(cls, n_layers: int, num_blocks: int, block_size: int,
               kv_heads: int, head_dim: int, dtype) -> "PagedKVCache":
        import jax.numpy as jnp

        shape = (n_layers, num_blocks, kv_heads, block_size, head_dim)
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    @property
    def block_size(self) -> int:
        return self.k.shape[3]


def blocks_needed(n_tokens: int, block_size: int) -> int:
    return max(1, -(-n_tokens // block_size))


def gather_kv(ck, cv, block_table):
    """ck/cv [nblk, KV, bs, Dh] (one layer), block_table [B, maxblk] (-1 pad)
    -> k/v [B, maxblk*bs, KV, Dh]. Padding rows gather block 0; callers mask
    by seq length so the junk never contributes."""
    import jax.numpy as jnp

    bt = jnp.maximum(block_table, 0)
    B, M = bt.shape

    def g(c):
        nblk, KV, bs, Dh = c.shape
        x = jnp.take(c, bt.reshape(-1), axis=0)          # [B*M, KV, bs, Dh]
        x = x.reshape(B, M, KV, bs, Dh).transpose(0, 1, 3, 2, 4)
        return x.reshape(B, M * bs, KV, Dh)

    return g(ck), g(cv)


def append_token_kv(ck, cv, newk, newv, block_table, pos, layer=None):
    """Scatter one new token's K/V per sequence into the block pool.

    ck/cv [nblk, KV, bs, Dh] — or the stacked [L, nblk, KV, bs, Dh] pool
    with ``layer`` set, which scatters into layer ``layer`` WITHOUT ever
    slicing the pool (the decode loop carries one pool buffer and XLA
    updates it in place; a per-layer slice would read+write the whole
    layer each step). newk/newv [B, KV, Dh]; block_table [B, maxblk];
    pos [B] = token index within the sequence (the slot being written).
    Reference: linear_blocked_kv_rotary's KV append half.
    """
    import jax.numpy as jnp

    pooled = ck.ndim == 5
    bs = ck.shape[3] if pooled else ck.shape[2]
    blk = jnp.take_along_axis(jnp.maximum(block_table, 0), (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs
    # advanced indices around the KV slice: result is [B, KV, Dh] (numpy
    # moves the advanced dims to the front), matching newk/newv exactly
    if pooled:
        ck = ck.at[layer, blk, :, off].set(newk.astype(ck.dtype))
        cv = cv.at[layer, blk, :, off].set(newv.astype(cv.dtype))
    else:
        ck = ck.at[blk, :, off].set(newk.astype(ck.dtype))
        cv = cv.at[blk, :, off].set(newv.astype(cv.dtype))
    return ck, cv


def write_prefill_kv(ck, cv, ks, vs, block_table):
    """Write a whole prompt's K/V (one sequence) into its blocks.

    ck/cv [nblk, KV, bs, Dh]; ks/vs [Tpad, KV, Dh] with Tpad == nseq_blocks*bs
    (caller pads); block_table [nseq_blocks] real ids.
    """
    bs = ck.shape[2]
    n = block_table.shape[0]

    def blocks(x):
        KV, Dh = x.shape[1], x.shape[2]
        return x.reshape(n, bs, KV, Dh).transpose(0, 2, 1, 3)

    ck = ck.at[block_table].set(blocks(ks).astype(ck.dtype))
    cv = cv.at[block_table].set(blocks(vs).astype(cv.dtype))
    return ck, cv


def paged_decode_attention(q, ck, cv, block_table, kv_len, alibi_slopes=None,
                           layer=None):
    """q [B,1,H,Dh] against paged KV (one layer) [nblk, KV, bs, Dh], or
    the stacked [L, nblk, KV, bs, Dh] pool with ``layer`` set.

    On TPU this dispatches to the fused Pallas kernel
    (``ops/paged_attention.py``): the block table rides in scalar memory and
    KV blocks stream through VMEM once — no materialized [B,S,KV,Dh] gather
    (reference blocked_flash + atom_builder). Elsewhere (and as the numerics
    oracle) it gathers by table and runs dense decode attention.
    ``alibi_slopes`` [H] rides the kernel (BLOOM serving).
    """
    from ..ops.paged_attention import paged_decode_attention as _dispatch

    return _dispatch(q, ck, cv, block_table, kv_len,
                     alibi_slopes=alibi_slopes, layer=layer)
