"""Paged KV cache: block allocator + block-table attention.

Capability analog of the reference v2 ragged stack:
  - ``BlockedAllocator`` (ragged/blocked_allocator.py:11) — host-side
    free-list of KV blocks, here grown into a REF-COUNTED, CONTENT-ADDRESSED
    block store (round 11): full committed blocks are registered under a
    chained token hash, refcount-0 registered blocks park in a reusable LRU
    instead of losing their KV, and admission can acquire a matching prefix
    chain instead of re-prefilling it (the vLLM/FastGen prefix-cache idiom
    over the SURVEY §2.10 ragged substrate).
  - ``BlockedKVCache`` (ragged/kv_cache.py:40) — here ``PagedKVCache``:
    per-layer-stacked block pool [L, nblocks, KV, block, Dh] on device,
    optionally int8/fp8 STORAGE with per-token-per-head scale planes
    (``kv_cache_dtype``; the §2.11/§2.8 compression machinery applied to
    the serving cache — decode is KV-bandwidth-bound, so halving resident
    KV bytes is ~2x on the binding resource).
  - ``blocked_flash`` + ``atom_builder`` + ``linear_blocked_kv_rotary``
    (inference/v2/kernels/ragged_ops/) — here ``paged_decode_attention``
    (gather-by-block-table attention; the Pallas kernel variant lives in
    ops/paged_attention.py and is dispatched when on TPU).

TPU-first notes: block tables are static-shape int32 arrays padded with -1;
gathers/scatters are XLA ops inside jit, so a whole decode step (append +
attention over all layers) is one compiled program. Quantized pools pass
per-layer KV to the kernels as ``(data, scale)`` pairs; the kernels
dequantize in-register on stream and the XLA gather path doubles as the
CPU-testable numerics oracle.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from functools import lru_cache
from typing import Dict, List, NamedTuple, Sequence, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Content-addressed block keys
# ---------------------------------------------------------------------------


def _chain_key(parent: bytes, tokens: Sequence[int]) -> bytes:
    """Key for one full block given its parent block's key (b"" for the
    first block): position-dependent by construction, so two identical
    blocks at different depths never collide."""
    chunk = np.asarray(tokens, np.int64).tobytes()
    return hashlib.blake2b(parent + chunk, digest_size=16).digest()


@lru_cache(maxsize=512)
def _chain_keys_cached(tokens: Tuple[int, ...], block_size: int,
                       parent: bytes) -> Tuple[bytes, ...]:
    out: List[bytes] = []
    for i in range(len(tokens) // block_size):
        parent = _chain_key(parent, tokens[i * block_size:(i + 1) * block_size])
        out.append(parent)
    return tuple(out)


def chain_block_keys(tokens: Sequence[int], block_size: int,
                     parent: bytes = b"") -> List[bytes]:
    """Chained content keys for every FULL block of ``tokens`` (the partial
    tail has no key — only committed, immutable blocks are addressable).
    Keys are pure functions of (tokens, block_size, parent), so they are
    LRU-memoized: the scheduler peeks every QUEUED request's prompt every
    tick while it waits for admission, and without the cache a long
    prompt's whole blake2b chain would be re-hashed each time."""
    return list(_chain_keys_cached(tuple(int(t) for t in tokens),
                                   block_size, parent))


class BlockedAllocator:
    """Ref-counted, content-addressed allocator over ``num_blocks`` KV
    blocks (host side).

    Extends ragged/blocked_allocator.py:11's free-list with the three
    mechanisms prefix caching needs:

      - **refcounts**: ``allocate`` hands out blocks at refcount 1;
        ``retain`` shares them (prefix hit, fork); ``free`` decrements and
        only a refcount-0 block leaves a sequence's ownership. Freeing a
        block that is not allocated raises (the ISSUE 6 double-free fix —
        the old total-count assert missed per-id double frees).
      - **content registry**: ``register(key, block)`` binds a committed
        full block to its chained token hash; ``peek``/``acquire`` walk a
        key chain and return the longest registered prefix.
      - **cached-free LRU**: a registered block whose refcount hits 0
        parks in an LRU of reusable blocks instead of losing its KV; it
        still counts as allocatable (``free_blocks``) and is evicted —
        registration dropped — only when a fresh allocation needs it.
        ``acquire`` revives parked hits at refcount 1.
    """

    def __init__(self, num_blocks: int):
        if num_blocks <= 0:
            raise ValueError("num_blocks must be positive")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))
        self._ref: Dict[int, int] = {}                # live block -> refcount
        self._cached: "OrderedDict[int, None]" = OrderedDict()  # LRU, ref 0
        self._block_of: Dict[bytes, int] = {}         # key -> block
        self._key_of: Dict[int, bytes] = {}           # block -> key
        # counters (observability: the serving prefix_cache/* group and the
        # multichip dryrun's zero-new-allocation gate read these)
        self.fresh_allocs = 0     # blocks handed out by allocate()
        self.shared_acquires = 0  # prefix hits on LIVE blocks (ref +1)
        self.revives = 0          # prefix hits on parked cached-free blocks
        self.evictions = 0        # parked blocks recycled for fresh allocs

    # -- capacity ------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: truly free + parked cached-free (reusable
        content, but evictable the moment capacity is needed)."""
        return len(self._free) + len(self._cached)

    @property
    def live_blocks(self) -> int:
        return len(self._ref)

    @property
    def cached_blocks(self) -> int:
        return len(self._cached)

    @property
    def shared_blocks(self) -> int:
        """Live blocks held by more than one sequence."""
        return sum(1 for c in self._ref.values() if c > 1)

    def ref_count(self, block: int) -> int:
        return self._ref.get(block, 0)

    # -- allocate / retain / free --------------------------------------

    def allocate(self, n: int) -> List[int]:
        if n > self.free_blocks:
            raise RuntimeError(
                f"out of KV blocks: want {n}, have {self.free_blocks}")
        take = min(n, len(self._free))
        out, self._free = self._free[:take], self._free[take:]
        while len(out) < n:
            # recycle the least-recently-parked cached block; its content
            # is gone for good, so drop the registration with it
            b, _ = self._cached.popitem(last=False)
            self._unregister(b)
            self.evictions += 1
            out.append(b)
        for b in out:
            self._ref[b] = 1
        self.fresh_allocs += n
        return out

    def retain(self, blocks: Sequence[int]) -> None:
        """Add one reference to already-live blocks (fork / shared batch)."""
        for b in blocks:
            if b not in self._ref:
                raise ValueError(f"retain of unallocated block {b}")
        for b in blocks:
            self._ref[b] += 1

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block. Validates EVERY id before mutating
        anything, so a bad call leaves the allocator untouched; freeing a
        block that is not allocated raises (per-id double-free detection —
        the old ``len(self._free) <= num_blocks`` assert only caught
        aggregate overflows, never a specific id freed twice while another
        stayed leaked)."""
        drops: Dict[int, int] = {}
        for b in blocks:
            drops[b] = drops.get(b, 0) + 1
        for b, n in drops.items():
            if not (0 <= b < self.num_blocks):
                raise ValueError(f"bad block id {b}")
            have = self._ref.get(b, 0)
            if have < n:
                raise ValueError(
                    f"double free: block {b} dropped {n}x but holds "
                    f"{have} reference{'' if have == 1 else 's'}")
        for b in blocks:
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                if b in self._key_of:
                    # committed content stays reusable until evicted
                    self._cached[b] = None
                else:
                    self._free.append(b)

    # -- content addressing --------------------------------------------

    def register(self, key: bytes, block: int) -> bool:
        """Bind a committed full block to its chained content key. First
        writer wins: a key that is already registered (another sequence
        committed the same content first) keeps its existing block and this
        one stays private. Returns True when the binding was recorded."""
        if block not in self._ref:
            raise ValueError(f"register of unallocated block {block}")
        if key in self._block_of or block in self._key_of:
            return False
        self._block_of[key] = block
        self._key_of[block] = key
        return True

    def _unregister(self, block: int) -> None:
        key = self._key_of.pop(block, None)
        if key is not None:
            self._block_of.pop(key, None)

    def unregister(self, block: int) -> None:
        """Drop a block's content registration (the block stays live under
        its holder). For speculative-decode rewinds (ISSUE 8): a rejected
        draft invalidates a committed block's bytes-under-key binding —
        the rewinding sequence is about to overwrite part of the block, so
        future admissions must not resolve the stale key to it. Only legal
        on a block the caller holds exclusively (refcount 1); a ref-shared
        committed block must be COW-cloned instead, never unregistered out
        from under its other holders' future re-admissions."""
        if self.ref_count(block) != 1:
            raise ValueError(
                f"unregister of block {block} with refcount "
                f"{self.ref_count(block)}: only an exclusively-held block "
                "may lose its registration (shared committed blocks take "
                "the copy-on-write path)")
        self._unregister(block)

    def peek(self, keys: Sequence[bytes]) -> Tuple[int, int]:
        """(live, parked) counts for the longest registered prefix of
        ``keys`` — live blocks cost an admission ZERO new allocations,
        parked ones consume a slot from the free pool (they are already
        counted allocatable) but no prefill compute."""
        live = parked = 0
        for key in keys:
            b = self._block_of.get(key)
            if b is None:
                break
            if b in self._ref:
                live += 1
            else:
                parked += 1
        return live, parked

    def invalidate_registry(self) -> None:
        """Drop EVERY content registration and all parked blocks (back to
        the plain free list). For weight hot-swaps: cached KV was computed
        under the old weights, so a later admission hashing the same
        tokens must MISS — the keys are pure functions of token history
        and would otherwise resolve to stale content. Live blocks stay
        live (their holders own them); they just stop being addressable."""
        self._block_of.clear()
        self._key_of.clear()
        self._free.extend(self._cached)
        self._cached.clear()

    def acquire(self, keys: Sequence[bytes]) -> List[int]:
        """Acquire the longest registered prefix of ``keys``: live hits
        gain a reference, parked hits revive at refcount 1. Returns the
        blocks in chain order (possibly empty)."""
        out: List[int] = []
        for key in keys:
            b = self._block_of.get(key)
            if b is None:
                break
            if b in self._ref:
                self._ref[b] += 1
                self.shared_acquires += 1
            else:
                del self._cached[b]
                self._ref[b] = 1
                self.revives += 1
            out.append(b)
        return out


# ---------------------------------------------------------------------------
# KV quantization helpers (kv_cache_dtype: bf16 | int8 | fp8)
# ---------------------------------------------------------------------------

KV_CACHE_DTYPES = ("bf16", "int8", "fp8")


def kv_storage_dtype(kv_cache_dtype: str, compute_dtype):
    """Pool storage dtype for a kv_cache_dtype mode ("bf16" = the engine's
    serving dtype, the pre-round-11 behavior)."""
    import jax.numpy as jnp

    if kv_cache_dtype == "int8":
        return jnp.int8
    if kv_cache_dtype == "fp8":
        return jnp.float8_e4m3fn
    return compute_dtype


def _kv_maxval(qdtype) -> float:
    import jax.numpy as jnp

    if qdtype == jnp.int8:
        return 127.0
    return float(jnp.finfo(qdtype).max)   # e4m3: 448


def quantize_kv(x, qdtype):
    """Per-token-per-head symmetric quantization over the last (Dh) axis:
    x [..., Dh] -> (q [..., Dh] in ``qdtype``, scale [...] f32) with each
    row's absmax mapped to the storage dtype's max (the ops/quant.py
    group-wise idiom at row granularity — one scale per written KV row, so
    append/scatter paths stay single-scatter)."""
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1)
    maxv = _kv_maxval(qdtype)
    scale = jnp.where(absmax > 0, absmax / maxv, 1.0)
    y = x32 / scale[..., None]
    if qdtype == jnp.int8:
        q = jnp.clip(jnp.round(y), -maxv, maxv).astype(jnp.int8)
    else:
        q = y.astype(qdtype)
    return q, scale


def dequantize_kv(q, scale, dtype=None):
    """q [..., Dh] storage + scale [...] -> f32 (or ``dtype``) values."""
    import jax.numpy as jnp

    out = q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
    return out.astype(dtype) if dtype is not None else out


def kv_parts(c):
    """Split a per-layer KV operand into (data, scale_or_None): quantized
    pools travel as ``(data, scale)`` pairs through the layer scans and
    kernel wrappers; bf16 pools stay bare arrays."""
    if isinstance(c, tuple):
        return c[0], c[1]
    return c, None


class PagedKVCache(NamedTuple):
    """Device block pool. k/v: [L, num_blocks, KV, block_size, Dh].

    KV is a LEADING dim (round 3): the Pallas decode kernel DMAs one kv
    head's block per grid step, which TPU block specs only allow on
    non-minor dims; {block_size, Dh} minor also makes blocks native
    (8,128)-tileable.

    Round 11: ``kv_cache_dtype`` int8/fp8 stores k/v at 1 byte/element and
    grows per-token-per-head scale planes ``k_scale``/``v_scale``
    [L, num_blocks, KV, block_size] (f32). bf16 mode keeps the scale
    fields as empty pytrees so every jitted program signature is stable
    within an engine."""

    k: "object"
    v: "object"
    k_scale: "object" = ()
    v_scale: "object" = ()

    @classmethod
    def create(cls, n_layers: int, num_blocks: int, block_size: int,
               kv_heads: int, head_dim: int, dtype,
               kv_cache_dtype: str = "bf16") -> "PagedKVCache":
        import jax.numpy as jnp

        if kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(f"kv_cache_dtype must be one of "
                             f"{KV_CACHE_DTYPES}, got {kv_cache_dtype!r}")
        store = kv_storage_dtype(kv_cache_dtype, dtype)
        shape = (n_layers, num_blocks, kv_heads, block_size, head_dim)
        k, v = jnp.zeros(shape, store), jnp.zeros(shape, store)
        if kv_cache_dtype == "bf16":
            return cls(k, v)
        sshape = shape[:-1]
        return cls(k, v, jnp.ones(sshape, jnp.float32),
                   jnp.ones(sshape, jnp.float32))

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return not isinstance(self.k_scale, tuple)

    def pool_nbytes(self) -> int:
        """Resident bytes of the KV pool including scale planes — the
        figure the kv_cache_dtype modes halve (pool-size tests + the
        BASELINE.md resident-batch arithmetic pin this)."""
        total = 0
        for x in self:
            if not isinstance(x, tuple):
                total += int(np.prod(x.shape)) * x.dtype.itemsize
        return total


def blocks_needed(n_tokens: int, block_size: int) -> int:
    return max(1, -(-n_tokens // block_size))


def gather_kv(ck, cv, block_table):
    """ck/cv [nblk, KV, bs, Dh] (one layer) — or quantized ``(data, scale)``
    pairs with scale [nblk, KV, bs] — block_table [B, maxblk] (-1 pad)
    -> k/v [B, maxblk*bs, KV, Dh]. Quantized pools dequantize after the
    gather (this is the CPU numerics oracle for the in-kernel dequant).
    Padding rows gather block 0; callers mask by seq length so the junk
    never contributes."""
    import jax.numpy as jnp

    bt = jnp.maximum(block_table, 0)
    B, M = bt.shape

    def g(c):
        nblk, KV, bs, Dh = c.shape
        x = jnp.take(c, bt.reshape(-1), axis=0)          # [B*M, KV, bs, Dh]
        x = x.reshape(B, M, KV, bs, Dh).transpose(0, 1, 3, 2, 4)
        return x.reshape(B, M * bs, KV, Dh)

    def gs(s):
        nblk, KV, bs = s.shape
        x = jnp.take(s, bt.reshape(-1), axis=0)          # [B*M, KV, bs]
        x = x.reshape(B, M, KV, bs).transpose(0, 1, 3, 2)
        return x.reshape(B, M * bs, KV)

    kq, ks = kv_parts(ck)
    vq, vs = kv_parts(cv)
    if ks is None:
        return g(kq), g(vq)
    return (g(kq).astype(jnp.float32) * gs(ks)[..., None],
            g(vq).astype(jnp.float32) * gs(vs)[..., None])


def append_token_kv(ck, cv, newk, newv, block_table, pos, layer=None):
    """Scatter one new token's K/V per sequence into the block pool.

    ck/cv [nblk, KV, bs, Dh] — or the stacked [L, nblk, KV, bs, Dh] pool
    with ``layer`` set, which scatters into layer ``layer`` WITHOUT ever
    slicing the pool (the decode loop carries one pool buffer and XLA
    updates it in place; a per-layer slice would read+write the whole
    layer each step). Quantized pools ride as ``(data, scale)`` pairs:
    the new rows are quantized per (sequence, kv head) on write and the
    scale plane gets the matching scatter. newk/newv [B, KV, Dh];
    block_table [B, maxblk]; pos [B] = token index within the sequence
    (the slot being written).
    Reference: linear_blocked_kv_rotary's KV append half.
    """
    import jax.numpy as jnp

    kq, ks = kv_parts(ck)
    vq, vs = kv_parts(cv)
    pooled = kq.ndim == 5
    bs = kq.shape[3] if pooled else kq.shape[2]
    blk = jnp.take_along_axis(jnp.maximum(block_table, 0), (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs
    if ks is not None:
        newk, sk = quantize_kv(newk, kq.dtype)     # q [B,KV,Dh], scale [B,KV]
        newv, sv = quantize_kv(newv, vq.dtype)
    # advanced indices around the KV slice: result is [B, KV, Dh] (numpy
    # moves the advanced dims to the front), matching newk/newv exactly
    if pooled:
        kq = kq.at[layer, blk, :, off].set(newk.astype(kq.dtype))
        vq = vq.at[layer, blk, :, off].set(newv.astype(vq.dtype))
        if ks is not None:
            ks = ks.at[layer, blk, :, off].set(sk)
            vs = vs.at[layer, blk, :, off].set(sv)
    else:
        kq = kq.at[blk, :, off].set(newk.astype(kq.dtype))
        vq = vq.at[blk, :, off].set(newv.astype(vq.dtype))
        if ks is not None:
            ks = ks.at[blk, :, off].set(sk)
            vs = vs.at[blk, :, off].set(sv)
    if ks is None:
        return kq, vq
    return (kq, ks), (vq, vs)


def write_prefill_kv(ck, cv, ks_, vs_, block_table):
    """Write a whole prompt's K/V (one sequence) into its blocks.

    ck/cv [nblk, KV, bs, Dh] (or quantized ``(data, scale)`` pairs);
    ks_/vs_ [Tpad, KV, Dh] with Tpad == nseq_blocks*bs (caller pads);
    block_table [nseq_blocks] real ids.
    """
    kq, ksc = kv_parts(ck)
    vq, vsc = kv_parts(cv)
    bs = kq.shape[2]
    n = block_table.shape[0]

    def blocks(x):
        KV, Dh = x.shape[1], x.shape[2]
        return x.reshape(n, bs, KV, Dh).transpose(0, 2, 1, 3)

    def scale_blocks(s):           # [Tpad, KV] -> [n, KV, bs]
        KV = s.shape[1]
        return s.reshape(n, bs, KV).transpose(0, 2, 1)

    if ksc is not None:
        ks_, sk = quantize_kv(ks_, kq.dtype)
        vs_, sv = quantize_kv(vs_, vq.dtype)
        ksc = ksc.at[block_table].set(scale_blocks(sk))
        vsc = vsc.at[block_table].set(scale_blocks(sv))
    kq = kq.at[block_table].set(blocks(ks_).astype(kq.dtype))
    vq = vq.at[block_table].set(blocks(vs_).astype(vq.dtype))
    if ksc is None:
        return kq, vq
    return (kq, ksc), (vq, vsc)


def paged_decode_attention(q, ck, cv, block_table, kv_len, alibi_slopes=None,
                           layer=None):
    """q [B,1,H,Dh] against paged KV (one layer) [nblk, KV, bs, Dh], or
    the stacked [L, nblk, KV, bs, Dh] pool with ``layer`` set; quantized
    pools ride as ``(data, scale)`` pairs and dequantize in-register.

    On TPU this dispatches to the fused Pallas kernel
    (``ops/paged_attention.py``): the block table rides in scalar memory and
    KV blocks stream through VMEM once — no materialized [B,S,KV,Dh] gather
    (reference blocked_flash + atom_builder). Elsewhere (and as the numerics
    oracle) it gathers by table, dequantizes, and runs dense decode
    attention. ``alibi_slopes`` [H] rides the kernel (BLOOM serving).
    """
    from ..ops.paged_attention import paged_decode_attention as _dispatch

    return _dispatch(q, ck, cv, block_table, kv_len,
                     alibi_slopes=alibi_slopes, layer=layer)
