"""Draft-token sources for speculative serving (ISSUE 8).

The scheduler's speculative tick needs k candidate continuations per
running sequence; the verifier is the engine's own mixed-batch extend
path (engine_v2._spec_step_impl / _spec_sampled_impl), so a drafter only
has to PROPOSE — the acceptance contract is enforced entirely on the
target engine: the longest draft prefix matching the target's own token
chain (the greedy argmax chain at temperature 0, the seeded Gumbel
sampling chain under ISSUE 16's per-request SamplingParams). Both
drafters here are DETERMINISTIC (point-mass proposals), for which
chain-prefix matching is exactly the Leviathan/Chen speculative-sampling
accept rule — a proposal is accepted iff the target chain would have
emitted it, and the first rejected slot's chain token is the residual
resample — so speculation changes nothing about the emitted distribution
at any temperature. Two sources, both behind ``serving.speculative``:

  - :class:`NGramDrafter` — self-speculation / prompt-lookup (the LLMA /
    prompt-lookup-decoding idiom): match the sequence's trailing n-gram
    against its OWN earlier tokens and propose what followed. Zero extra
    weights, zero extra device dispatches; wins exactly where decode is
    most wasteful — repetitive suffixes (code, structured output,
    multi-turn transcripts, retrieval-grounded answers that quote their
    context).

  - :class:`DraftModelDrafter` — a small draft model (the classic
    Leviathan/Chen speculative-decoding shape) running its OWN paged
    engine: proposals come from ``decode_loop`` (one fused dispatch per
    tick per the SURVEY §2.9 inference-v1 generate-loop idiom), and the
    draft cache tracks the target's accepted history via the same
    ``rewind`` primitive the target uses for rejected drafts. Load the
    model through ``models/hf.py:from_hf`` (``load_draft_model`` gates
    the optional ``transformers`` dependency with a named error) or hand
    the drafter an in-process ``(model, params)`` pair.

A drafter is three methods — ``propose(uid, history, k) -> tokens``
(``history`` = prompt + everything emitted so far, whose LAST entry is
the sequence's pending decode input), ``forget(uid)`` (sequence finished
or preempted), ``close()`` — and proposals are best-effort: returning
``[]`` demotes the row to a plain decode token for that tick, never an
error on the serving path.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config.config_utils import ConfigError
from ..utils.logging import warning_once
from .config import InferenceConfig, SpeculativeConfig


class NGramDrafter:
    """Prompt-lookup self-speculation: propose the continuation that
    followed the most recent earlier occurrence of the sequence's
    trailing ``ngram`` tokens. Stateless per sequence — the history
    handed to ``propose`` is the whole state."""

    def __init__(self, ngram: int = 2):
        if ngram < 1:
            raise ConfigError(f"ngram must be >= 1, got {ngram}")
        self.ngram = int(ngram)

    def propose(self, uid: int, history: Sequence[int],
                k: int) -> List[int]:
        h = np.asarray(history, dtype=np.int64)
        n = self.ngram
        if k < 1 or len(h) <= n:
            return []
        # most recent earlier occurrence wins: recent context is the best
        # predictor of the immediate continuation (and a greedy loop's
        # cycle is caught at its latest period). One vectorized sliding-
        # window compare — this runs for every running sequence every
        # tick, so a Python scan over a 4k-token history would cost host
        # milliseconds between device dispatches.
        win = np.lib.stride_tricks.sliding_window_view(h, n)
        hits = np.nonzero((win[:-1] == h[-n:]).all(axis=1))[0]
        if not len(hits):
            return []
        i = int(hits[-1])
        return [int(t) for t in h[i + n:i + n + k]]

    def forget(self, uid: int) -> None:
        pass

    def close(self) -> None:
        pass


class DraftModelDrafter:
    """Draft-model speculation: a small model serving from its own paged
    engine proposes k greedy tokens per tick via ``decode_loop`` (one
    device dispatch on the DRAFT model; the target engine's
    one-dispatch-per-tick contract is untouched).

    The draft cache mirrors the target's ACCEPTED history: ``propose``
    diffs the caller's ``history`` against what the draft engine has
    written, rewinds past any rejected suffix (the same
    ``InferenceEngineV2.rewind`` primitive the target uses), extends with
    the newly-accepted tokens, then decodes k drafts. Draft-side KV
    pressure degrades to plain decode (``[]``) instead of erroring."""

    def __init__(self, model, params,
                 config: Optional[InferenceConfig] = None):
        from .engine_v2 import InferenceEngineV2

        self.engine = InferenceEngineV2(model, params,
                                        config or InferenceConfig())
        self._hist: Dict[int, List[int]] = {}

    @classmethod
    def for_target(cls, model, params,
                   target: InferenceConfig) -> "DraftModelDrafter":
        """Size the draft engine's cache to the target's serving geometry
        (same max_seq_len / block size / pool depth, full-precision draft
        KV — the draft pool is tiny next to the target's, and quantizing
        it would only add acceptance noise)."""
        import dataclasses

        cfg = InferenceConfig(
            dtype=target.dtype, max_seq_len=target.max_seq_len,
            kv_block_size=target.kv_block_size,
            num_kv_blocks=target.num_kv_blocks,
            max_batch_size=target.max_batch_size,
            decode_kernel=target.decode_kernel,
            serving=dataclasses.replace(target.serving,
                                        speculative=SpeculativeConfig()))
        return cls(model, params, cfg)

    def propose(self, uid: int, history: Sequence[int],
                k: int) -> List[int]:
        return self.propose_many([(uid, history, k)]).get(uid, [])

    def propose_many(self, reqs: Sequence[Tuple[int, Sequence[int], int]]
                     ) -> Dict[int, List[int]]:
        """Batched proposals for one scheduler tick: ONE sync ``put()``
        covering every divergent/new sequence, then ONE ``decode_loop``
        dispatch per distinct k — the §2.9 fused-generate idiom at fleet
        width. (A per-sequence propose() would pay one draft-engine
        dispatch per running sequence per tick — exactly the
        host-round-trip shape the target engine's one-dispatch contract
        exists to kill.) The ``_hist`` invariant — it mirrors the draft
        engine's written tokens — is maintained by mutating it only right
        after each engine call succeeds, so a mid-batch failure degrades
        those sequences to plain decode this tick and resyncs cold next
        tick."""
        eng = self.engine
        live = []
        for uid, history, k in reqs:
            h = [int(t) for t in history]
            if k < 1 or len(h) < 2:
                continue
            # decode_loop writes k slots past the current history tail
            k = min(k, eng.config.max_seq_len - len(h))
            if k >= 1:
                live.append((uid, h, k))
        out: Dict[int, List[int]] = {}
        try:
            puts: List[Tuple[int, List[int]]] = []
            ready: List[Tuple[int, int, int]] = []    # (uid, seed, k)
            for uid, h, k in live:
                tgt, t0 = h[:-1], h[-1]
                fed = self._hist.get(uid)
                if fed is not None:
                    p = 0
                    for a, b in zip(fed, tgt):
                        if a != b:
                            break
                        p += 1
                    if p == 0:
                        # diverged at the root (resubmitted uid) — resync
                        self.forget(uid)
                        fed = None
                    else:
                        if p < len(fed):
                            # rejected drafts (or a requeue) left stale
                            # draft KV past the accepted prefix — same
                            # rollback primitive as the target engine
                            eng.rewind(uid, p)
                            del fed[p:]
                        if p < len(tgt):
                            puts.append((uid, tgt[p:]))
                        ready.append((uid, t0, k))
                if fed is None:
                    puts.append((uid, list(tgt)))
                    self._hist[uid] = []
                    ready.append((uid, t0, k))
            if puts:
                eng.put([u for u, _ in puts], [c for _, c in puts])
                for uid, chunk in puts:
                    self._hist[uid].extend(chunk)
            groups: Dict[int, List[Tuple[int, int]]] = {}
            for uid, t0, k in ready:
                groups.setdefault(k, []).append((uid, t0))
            for k, rows in sorted(groups.items()):
                toks = np.asarray(eng.decode_loop(
                    [u for u, _ in rows], [t for _, t in rows], k))
                for (uid, t0), row in zip(rows, toks):
                    drafts = [int(x) for x in row]
                    # written this dispatch: seed plus all drafts but last
                    self._hist[uid].extend([t0] + drafts[:-1])
                    out[uid] = drafts
        except (RuntimeError, ValueError) as e:
            # draft-side KV pressure / admission refusal: the affected
            # sequences drop to plain decode for this tick and resync
            # cold next tick — never fail the serving tick. The dedup'd
            # warning stays STATIC (admission errors embed per-tick block
            # counts; interpolating them would defeat warning_once and
            # flood the log every tick under sustained pressure)
            # sxt: ignore[SXT005] exception class name only; the per-tick block counts are deliberately NOT interpolated (see comment above)
            warning_once(
                f"draft model: batched proposal failed "
                f"({type(e).__name__}); affected sequences fall back to "
                "plain decode while the pressure lasts")
            for uid, _, _ in live:
                if uid not in out:
                    self.forget(uid)
        return out

    def forget(self, uid: int) -> None:
        if self._hist.pop(uid, None) is not None and uid in self.engine._seqs:
            self.engine.flush([uid])

    def close(self) -> None:
        for uid in list(self._hist):
            self.forget(uid)


def make_drafter(spec: SpeculativeConfig,
                 like: Optional[InferenceConfig] = None,
                 draft: Optional[Tuple[object, object]] = None):
    """Build the drafter a ``serving.speculative`` section asks for.
    ``like`` sizes a draft-model engine to the target's geometry;
    ``draft`` = an in-process ``(model, params)`` pair that overrides the
    ``draft_model`` checkpoint path (tests, co-located draft heads)."""
    if spec.drafter == "ngram":
        return NGramDrafter(ngram=spec.ngram)
    if draft is not None:
        model, params = draft
    elif spec.draft_model:
        from ..models.hf import load_draft_model

        model, params = load_draft_model(spec.draft_model)
    else:
        raise ConfigError(
            "serving.speculative.drafter='model' needs a draft_model "
            "checkpoint path, or pass drafter=/draft= to the scheduler "
            "with an in-process (model, params) pair")
    if like is not None:
        return DraftModelDrafter.for_target(model, params, like)
    return DraftModelDrafter(model, params)
