"""Paged multi-tenant LoRA adapter pool (ISSUE 18).

ROADMAP item 4's "millions of users" means per-tenant fine-tunes, and the
hybrid-engine answer — fuse ONE adapter into the base weights
(``linear/optimized_linear.py``, SURVEY §2.3) — serializes the fleet per
tenant. This module is the S-LoRA/Punica-shaped alternative: a fixed-slot
HBM pool of rank-padded LoRA factor pairs that a mixed-adapter batch
gathers from *per row* inside the existing one-dispatch serving step
(``ops/lora_gemm.lora_delta``). Slot indices are data riding the
sequence descriptors; the pool's device arrays are ordinary jitted-step
operands whose shapes never depend on which adapters are loaded — a
warmed server admits brand-new adapter ids with zero recompiles.

Pool discipline is the host KV tier's (``kv_tier.py``), applied to
adapters instead of KV blocks:

- **Slot 0 is the reserved all-zeros null adapter** — no-adapter rows
  gather it and add an exact ``0.0`` (the scratch-block idiom of the
  paged KV cache, applied to weights). Device slot count is config
  ``slots`` + 1.
- **Content-keyed** like the prefix cache: registration digests the raw
  factors; re-registering identical bytes is a no-op, changed bytes
  bump the adapter's version (and rewrite its slot in place when
  resident) — the RLHF ``publish_adapter`` loop rides this.
- **Refcounted residency + LRU paging**: ``acquire`` pins an adapter's
  slot for a running sequence; a miss evicts the least-recently-used
  refs==0 slot; when every slot is pinned the pool is DRY and the
  scheduler *parks* the request (``AdapterPoolDry``) — park, never
  preempt, the kv_tier admission stance.
- **Double-buffered prefetch** through the pinned ``PinnedBufferPool``
  (recycled stage ids, never adapter-id keys), so a predicted fetch's
  critical path is only the host→HBM copy of pre-staged pinned bytes.
- **Scaling folded at registration**: stored B is ``B * (alpha / r)``
  and ranks are zero-padded to ``max_rank``, so runtime needs no
  per-adapter scaling operand and padded columns contribute exactly 0.

Threading: touched from replica scheduler threads and the fleet publish
path, so all mutable state rides ``AdapterPool._mu`` — rank 20 in
``utils.invariants.LOCK_ORDER``, a transfer-substrate leaf like
``HostKVTier._mu`` (device installs run under it; they acquire nothing).

Fault site: ``adapter_fetch`` fires at the top of a miss-path acquire,
BEFORE any pool mutation — a crashed fetch leaves residency, refcounts,
and device slots exactly as they were (the chaos drill's replay relies
on it).
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..testing import faults, sanitizer
from ..utils.invariants import locked_by, requires_lock

NULL_SLOT = 0

# attention projections the pool serves; FFN adapters are out of scope
# (the serving delta seam lives in the engine's attention layer body)
SUPPORTED_TARGETS = ("wq", "wk", "wv", "wo")


class AdapterPoolDry(RuntimeError):
    """Every pool slot is pinned by a running sequence — the scheduler
    parks the requesting sequence until a release frees a slot."""


def target_dims(tcfg, target: str) -> Tuple[int, int]:
    """(d_in, d_out) of one attention projection — the base matmul the
    adapter delta parallels."""
    q_dim = tcfg.n_heads * tcfg.head_dim
    kv_dim = tcfg.kv_heads * tcfg.head_dim
    return {
        "wq": (tcfg.d_model, q_dim),
        "wk": (tcfg.d_model, kv_dim),
        "wv": (tcfg.d_model, kv_dim),
        "wo": (q_dim, tcfg.d_model),
    }[target]


def pool_bytes(tcfg, slots: int, max_rank: int,
               targets: Sequence[str] = SUPPORTED_TARGETS,
               bytes_per_elem: int = 4) -> int:
    """Static HBM footprint of a pool geometry (slots incl. the null
    slot x padded-rank factor pairs over all layers/targets) — the
    autotuner's pruned_static feasibility check, computed without
    building a pool."""
    total = 0
    for t in targets:
        din, dout = target_dims(tcfg, t)
        total += tcfg.n_layers * (slots + 1) * max_rank * (din + dout)
    return total * bytes_per_elem


@dataclasses.dataclass
class _Resident:
    """One occupied device slot: which adapter, how many running
    sequences pin it, and which content version is installed."""

    adapter_id: str
    slot: int
    refs: int
    version: int


@locked_by("_mu", "_host", "_resident", "_slot_owner", "_free_slots",
           "_staged", "_stage_ids", "_free_stages", "_next_stage",
           "hits", "misses", "evictions", "installs", "prefetches",
           "prefetch_hits", "prefetch_misses", "a", "b")
class AdapterPool:
    """Fixed-slot device pool of padded LoRA factor pairs.

    Device layout (per target ``t``): ``a[t]`` is [L, S, d_in, R] and
    ``b[t]`` is [L, S, R, d_out] with S = ``slots`` + 1 and R =
    ``max_rank`` — leading L so the pair joins the engine's layer-scan
    ``xs`` and each layer body sees its own [S, d_in, R] stack."""

    _next_pool_id = itertools.count()

    def __init__(self, tcfg, slots: int, max_rank: int,
                 targets: Sequence[str] = SUPPORTED_TARGETS,
                 prefetch_depth: int = 1, dtype=None):
        import jax.numpy as jnp

        from ..ops.native.aio import get_buffer_pool

        for t in targets:
            if t not in SUPPORTED_TARGETS:
                raise ValueError(
                    f"adapters: unsupported target {t!r} "
                    f"(supported: {SUPPORTED_TARGETS})")
        if slots < 1:
            raise ValueError("adapters: slots must be >= 1")
        if max_rank < 1:
            raise ValueError("adapters: max_rank must be >= 1")
        self.tcfg = tcfg
        self.slots = int(slots)
        self.max_rank = int(max_rank)
        self.targets = tuple(targets)
        self.prefetch_depth = int(prefetch_depth)
        self.dtype = dtype or jnp.float32
        self.pool = get_buffer_pool()
        self._pid = next(AdapterPool._next_pool_id)
        # rank 20 (utils.invariants.LOCK_ORDER): transfer-substrate leaf
        # — device installs run under it but acquire no further locks
        self._mu = sanitizer.wrap(threading.Lock(), "AdapterPool._mu")
        L, S, R = tcfg.n_layers, self.slots + 1, self.max_rank
        self.a: Dict[str, object] = {}
        self.b: Dict[str, object] = {}
        for t in self.targets:
            din, dout = target_dims(tcfg, t)
            self.a[t] = jnp.zeros((L, S, din, R), self.dtype)
            self.b[t] = jnp.zeros((L, S, R, dout), self.dtype)
        # aid -> {target: (A_pad [L,din,R], B_pad [L,R,dout])} host copies
        # (numpy; the paged backing store the device slots fetch from)
        self._host: Dict[str, Dict[str, Tuple[np.ndarray, np.ndarray]]] = {}
        self._digest: Dict[str, str] = {}
        self._version: Dict[str, int] = {}
        # residency: insertion order of _resident IS the LRU order
        # (acquire-hit re-inserts — the dict is the recency list)
        self._resident: Dict[str, _Resident] = {}
        self._slot_owner: Dict[int, str] = {}
        self._free_slots: List[int] = list(range(1, S))
        # prefetch staging: recycled stage ids keyed into the pinned
        # pool (never adapter ids — the pool caches per key forever,
        # kv_tier's recycled-slot rationale)
        self._staged: Dict[str, List[np.ndarray]] = {}
        self._stage_ids: Dict[str, int] = {}
        self._free_stages: List[int] = []
        self._next_stage = 0
        # counters (the scheduler's adapter/* group reads these)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.installs = 0
        self.prefetches = 0
        self.prefetch_hits = 0
        self.prefetch_misses = 0

    # -- registration (content-keyed) ----------------------------------

    def _pad_factors(self, factors, alpha) -> Dict[
            str, Tuple[np.ndarray, np.ndarray]]:
        """Validate + normalize ``{target: (A, B)}`` (2-D per-layer-tied
        or 3-D [L, ...] factors) into padded [L, din, R] / [L, R, dout]
        host planes with alpha/r folded into B."""
        L, R = self.tcfg.n_layers, self.max_rank
        out = {}
        for t, (A, B) in factors.items():
            if t not in self.targets:
                raise ValueError(
                    f"adapters: target {t!r} not in pool targets "
                    f"{self.targets}")
            A = np.asarray(A)
            B = np.asarray(B)
            if A.ndim == 2:
                A = np.broadcast_to(A, (L,) + A.shape)
            if B.ndim == 2:
                B = np.broadcast_to(B, (L,) + B.shape)
            din, dout = target_dims(self.tcfg, t)
            r = A.shape[-1]
            if A.shape != (L, din, r) or B.shape != (L, r, dout):
                raise ValueError(
                    f"adapters: {t} factors have shapes {A.shape}/"
                    f"{B.shape}, want [L={L}, {din}, r]/[L, r, {dout}]")
            if r > R:
                raise ValueError(
                    f"adapters: {t} rank {r} exceeds pool max_rank {R}")
            scale = (alpha / r) if alpha is not None else 1.0
            A_pad = np.zeros((L, din, R), np.float32)
            B_pad = np.zeros((L, R, dout), np.float32)
            A_pad[:, :, :r] = A
            B_pad[:, :r, :] = B * scale   # padded rows of B stay 0 —
            out[t] = (A_pad, B_pad)       # delta is exactly unchanged
        return out

    def register(self, adapter_id: str, factors, alpha=None,
                 version: Optional[int] = None) -> int:
        """Make ``adapter_id`` known to the pool (host side; residency is
        acquire's business). ``factors`` maps target -> (A, B). Content-
        keyed: identical bytes are a no-op, changed bytes bump the
        version and — when the adapter is resident — rewrite its device
        slot in place so running sequences pick up the new factors next
        step (the publish_adapter semantics). Returns the version."""
        if not adapter_id:
            raise ValueError("adapters: adapter_id must be non-empty")
        padded = self._pad_factors(factors, alpha)
        h = hashlib.blake2b(digest_size=16)
        for t in sorted(padded):
            A_pad, B_pad = padded[t]
            h.update(t.encode())
            h.update(A_pad.tobytes())
            h.update(B_pad.tobytes())
        digest = h.hexdigest()
        with self._mu:
            if self._digest.get(adapter_id) == digest and version is None:
                return self._version[adapter_id]
            self._host[adapter_id] = padded
            self._digest[adapter_id] = digest
            self._version[adapter_id] = (
                version if version is not None
                else self._version.get(adapter_id, 0) + 1)
            self._release_staging(adapter_id)   # staged bytes are stale
            res = self._resident.get(adapter_id)
            if res is not None:
                self._install(adapter_id, res.slot)
                res.version = self._version[adapter_id]
            return self._version[adapter_id]

    def registered(self, adapter_id: str) -> bool:
        with self._mu:
            return adapter_id in self._host

    def version(self, adapter_id: str) -> Optional[int]:
        with self._mu:
            return self._version.get(adapter_id)

    # -- residency -----------------------------------------------------

    @requires_lock("_mu")
    def _install(self, adapter_id: str, slot: int,
                 staged: Optional[List[np.ndarray]] = None) -> None:
        """Write ``adapter_id``'s padded planes into device slot
        ``slot`` (from the prefetch staging when provided)."""
        planes = staged
        if planes is None:
            planes = []
            for t in self.targets:
                pair = self._host[adapter_id].get(t)
                if pair is None:
                    L, R = self.tcfg.n_layers, self.max_rank
                    din, dout = target_dims(self.tcfg, t)
                    pair = (np.zeros((L, din, R), np.float32),
                            np.zeros((L, R, dout), np.float32))
                planes.extend(pair)
        it = iter(planes)
        for t in self.targets:
            A_pad, B_pad = next(it), next(it)
            self.a[t] = self.a[t].at[:, slot].set(
                A_pad.astype(self.a[t].dtype))
            self.b[t] = self.b[t].at[:, slot].set(
                B_pad.astype(self.b[t].dtype))
        self.installs += 1

    def acquire(self, adapter_id: str) -> int:
        """Pin ``adapter_id`` resident and return its device slot.

        Hit: bump the refcount and recency. Miss: take a free slot, else
        evict the LRU refs==0 resident; when every slot is pinned raise
        :class:`AdapterPoolDry` (the caller parks — nothing was
        mutated). The fault site fires before any mutation for the same
        atomicity: a crashed fetch changes nothing."""
        with self._mu:
            if adapter_id not in self._host:
                raise KeyError(
                    f"adapters: {adapter_id!r} is not registered")
            res = self._resident.get(adapter_id)
            if res is not None:
                self.hits += 1
                res.refs += 1
                self._resident.pop(adapter_id)      # refresh recency
                self._resident[adapter_id] = res
                return res.slot
            # miss path — pick the victim/free slot, then crash-test,
            # then mutate (atomic-on-reject AND atomic-on-crash)
            victim = None
            if not self._free_slots:
                for aid, r in self._resident.items():   # LRU first
                    if r.refs == 0:
                        victim = aid
                        break
                if victim is None:
                    raise AdapterPoolDry(
                        f"adapters: all {self.slots} slots pinned "
                        f"({sorted(self._resident)}) — cannot load "
                        f"{adapter_id!r}")
            if faults.ACTIVE:
                faults.maybe_crash("adapter_fetch", 0)
            self.misses += 1
            if victim is not None:
                gone = self._resident.pop(victim)
                self._slot_owner.pop(gone.slot)
                self._free_slots.append(gone.slot)
                self.evictions += 1
            slot = self._free_slots.pop()
            staged = self._staged.get(adapter_id)
            if staged is not None:
                self.prefetch_hits += 1
            else:
                self.prefetch_misses += 1
            self._install(adapter_id, slot, staged=staged)
            self._release_staging(adapter_id)       # consumed
            self._resident[adapter_id] = _Resident(
                adapter_id=adapter_id, slot=slot, refs=1,
                version=self._version[adapter_id])
            self._slot_owner[slot] = adapter_id
            return slot

    def release(self, adapter_id: str) -> None:
        """Unpin one reference. The adapter STAYS resident at refs==0 —
        warm for re-acquire and for placement affinity — until LRU
        eviction reclaims its slot."""
        with self._mu:
            res = self._resident.get(adapter_id)
            if res is None or res.refs <= 0:
                raise RuntimeError(
                    f"adapters: release of {adapter_id!r} without a "
                    f"matching acquire")
            res.refs -= 1

    def can_acquire(self, adapter_id: str) -> bool:
        """Read-only acquirability probe for ``_admission_detail`` —
        True iff an ``acquire`` now would succeed (resident, or a slot
        is free/evictable). Mutates nothing."""
        with self._mu:
            if adapter_id not in self._host:
                return False
            if adapter_id in self._resident or self._free_slots:
                return True
            return any(r.refs == 0 for r in self._resident.values())

    def can_acquire_all(self, adapter_ids) -> Tuple[bool, str]:
        """Batch acquirability probe: would pinning ALL of ``adapter_ids``
        (with duplicates collapsed) succeed right now? Batch-aware where
        per-id :meth:`can_acquire` is not — refs==0 residents the batch
        itself re-acquires are NOT counted evictable, so a mixed batch
        cannot pass by planning to evict its own hits. Mutates nothing;
        ``(ok, why)`` with ``why`` naming the dry pool on refusal."""
        with self._mu:
            batch = {a for a in adapter_ids if a is not None}
            for aid in batch:
                if aid not in self._host:
                    return False, f"adapter {aid!r} is not registered"
            need = {a for a in batch if a not in self._resident}
            evictable = sum(1 for aid, r in self._resident.items()
                            if r.refs == 0 and aid not in batch)
            cap = len(self._free_slots) + evictable
            if len(need) > cap:
                return False, (
                    f"adapter pool dry: batch needs {len(need)} new "
                    f"slot(s) for {sorted(need)} but only {cap} of "
                    f"{self.slots} are free or evictable")
            return True, ""

    def slot_of(self, adapter_id: str) -> Optional[int]:
        with self._mu:
            res = self._resident.get(adapter_id)
            return res.slot if res is not None else None

    def resident_ids(self) -> List[str]:
        """Resident adapter ids, LRU-oldest first (the placement
        affinity signal ``load_report`` ships)."""
        with self._mu:
            return list(self._resident)

    # -- prefetch ------------------------------------------------------

    def prefetch(self, adapter_id: str) -> bool:
        """Stage ``adapter_id``'s padded planes into pinned buffers so
        the eventual acquire-miss install copies from pinned host memory
        (kv_tier's double-buffer half). Depth-bounded; True when a
        staging now exists."""
        with self._mu:
            if adapter_id not in self._host or \
                    adapter_id in self._resident:
                return False
            if adapter_id in self._staged:
                return True
            while len(self._staged) >= max(1, self.prefetch_depth):
                evicted = next(iter(self._staged))
                self._staged.pop(evicted)
                self._free_stages.append(self._stage_ids.pop(evicted))
            if self._free_stages:
                stage = self._free_stages.pop()
            else:
                stage = self._next_stage
                self._next_stage += 1
            staged = []
            i = 0
            for t in self.targets:
                pair = self._host[adapter_id].get(t)
                if pair is None:
                    L, R = self.tcfg.n_layers, self.max_rank
                    din, dout = target_dims(self.tcfg, t)
                    pair = (np.zeros((L, din, R), np.float32),
                            np.zeros((L, R, dout), np.float32))
                for p in pair:
                    buf = self.pool.staging(
                        ("adapter", self._pid, stage, i), p.shape,
                        p.dtype)
                    np.copyto(buf, p)
                    staged.append(buf)
                    i += 1
            self._staged[adapter_id] = staged
            self._stage_ids[adapter_id] = stage
            self.prefetches += 1
            return True

    @requires_lock("_mu")
    def _release_staging(self, adapter_id: str) -> None:
        committed = self._staged.pop(adapter_id, None) is not None
        stage = self._stage_ids.pop(adapter_id, None)
        if committed and stage is not None:
            self._free_stages.append(stage)

    # -- engine operands -----------------------------------------------

    def device_operands(self):
        """The layer-scan xs contribution: per-target (A-stack, B-stack)
        device arrays with leading L. Snapshot under the lock — a
        concurrent publish swaps whole arrays, never mutates in place."""
        with self._mu:
            return {"a": dict(self.a), "b": dict(self.b)}

    # -- observability -------------------------------------------------

    def reset_counters(self) -> None:
        with self._mu:
            self.hits = self.misses = self.evictions = 0
            self.installs = self.prefetches = 0
            self.prefetch_hits = self.prefetch_misses = 0

    def stats(self) -> Dict[str, object]:
        with self._mu:
            return {
                "slots": self.slots,
                "resident": len(self._resident),
                "pinned": sum(1 for r in self._resident.values()
                              if r.refs > 0),
                "registered": len(self._host),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "installs": self.installs,
                "prefetches": self.prefetches,
                "prefetch_hits": self.prefetch_hits,
                "prefetch_misses": self.prefetch_misses,
            }
