"""Host tier for paged KV blocks (ISSUE 15): spill, prefetch, fetch.

The serving pool (``PagedKVCache``) is sized by HBM; production contexts
are sized by books and codebases. This module is the tier between them:
COLD blocks of parked sequences move host-ward as raw pool storage —
data planes plus int8/fp8 scale planes, byte-exact, never re-quantized
(the disagg wire-format discipline of ``KVBlockPayload`` applied
vertically instead of horizontally) — and move back into FRESH device
blocks when the scheduler un-parks the sequence.

Substrate: the same AIO machinery the disaggregated transfer stages
through (``ops/native/aio.py``) — spilled bytes live in host arrays (or
an ``AsyncIOEngine``-written file per sequence when ``spill_dir`` is
set, the NVMe tier below host RAM), and prefetch assembles them into
long-lived page-aligned ``PinnedBufferPool`` staging buffers one tick
AHEAD of the expected fetch, so the fetch's critical path is only the
device scatter (the FPDT double-buffered-offload idiom, SURVEY §2.6 and
§5.7, at block granularity).

Threading: the tier is touched from replica threads (scheduler ticks)
and the failover path (export of a spilled sequence), so its state rides
one lock — ``HostKVTier._mu``, rank 20 in ``utils.invariants.LOCK_ORDER``
next to the transfer substrate's locks, sanitizer-wrapped at the
construction site like every other fleet lock.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..testing import sanitizer
from ..utils.invariants import locked_by, requires_lock
from ..utils.logging import logger


@dataclasses.dataclass
class TierEntry:
    """One sequence's spilled blocks: ``indices`` are BLOCK POSITIONS in
    the owning descriptor (not pool block ids — those were freed back to
    the allocator), ``shapes``/``dtypes`` describe the stacked
    pool-storage planes over those positions in index order
    ([L, nb, KV, bs, Dh] data; [L, nb, KV, bs] scales for quantized
    pools). ``planes`` holds the bytes in host RAM; ``path`` replaces it
    when the bytes live in a spill file."""

    indices: List[int]
    shapes: List[Tuple[int, ...]]
    dtypes: List[np.dtype]
    planes: Optional[List[np.ndarray]]
    path: Optional[str]
    nbytes: int


@locked_by("_mu", "_entries", "_staged", "_slots", "_free_slots",
           "_next_slot", "spills", "fetches", "prefetches",
           "prefetch_hits", "prefetch_misses", "spilled_blocks",
           "host_bytes")
class HostKVTier:
    """Host-side store of spilled KV blocks, keyed by sequence uid.

    ``store`` / ``load`` / ``drop`` are the engine's spill/fetch halves;
    ``prefetch`` stages a uid's bytes into pinned buffers ahead of its
    fetch (a fetch that finds its staging ready is a *prefetch hit* —
    the ``kv_tier/hit_rate`` the bench row publishes)."""

    _next_tier_id = itertools.count()

    def __init__(self, spill_dir: Optional[str] = None,
                 prefetch_depth: int = 1):
        from ..ops.native.aio import get_buffer_pool

        self.pool = get_buffer_pool()
        self._tid = next(HostKVTier._next_tier_id)
        # rank 20 (utils.invariants.LOCK_ORDER): the tier is a transfer-
        # substrate leaf — nothing else is acquired while holding it
        self._mu = sanitizer.wrap(threading.Lock(), "HostKVTier._mu")
        self.spill_dir = spill_dir
        self.prefetch_depth = int(prefetch_depth)
        self._entries: Dict[int, TierEntry] = {}
        # uid -> pinned staging views of the entry's planes (prefetch
        # output; consumed — or invalidated — by the next store/drop)
        self._staged: Dict[int, List[np.ndarray]] = {}
        # pinned stagings are keyed by a RECYCLED slot id, never by uid:
        # uids grow without bound over a serving process's life, and the
        # PinnedBufferPool caches per key forever — uid keys would pin
        # one staging's worth of host memory per request served under
        # pressure. _slots maps uid -> its slot (reserved at prefetch
        # start, so an in-flight copy is never evicted into); a slot
        # recycles when its staging is evicted/consumed, or — when a
        # store/drop cancels an in-flight prefetch — by that prefetch's
        # own failed commit (its copy has finished by then, so the slot's
        # buffers are quiescent before anyone reuses them).
        self._slots: Dict[int, int] = {}
        self._free_slots: List[int] = []
        self._next_slot = 0
        # counters (the scheduler's kv_tier/* group reads these)
        self.spills = 0            # store() calls (spill events)
        self.fetches = 0           # load() calls on the fetch path
        self.prefetches = 0
        self.prefetch_hits = 0     # fetches served from staged buffers
        self.prefetch_misses = 0   # fetches that had to assemble cold
        self.spilled_blocks = 0    # CURRENT blocks resident in the tier
        self.host_bytes = 0        # CURRENT bytes resident in the tier

    # -- introspection -------------------------------------------------

    def spilled(self, uid: int) -> List[int]:
        """Block positions of ``uid`` currently in the tier ([] = none)."""
        with self._mu:
            e = self._entries.get(uid)
            return list(e.indices) if e is not None else []

    def uids(self) -> List[int]:
        with self._mu:
            return list(self._entries)

    @property
    def hit_rate(self) -> Optional[float]:
        done = self.prefetch_hits + self.prefetch_misses
        return (self.prefetch_hits / done) if done else None

    # -- storage -------------------------------------------------------

    _next_gen = itertools.count()

    def _spill_path(self, uid: int) -> str:
        # generation-suffixed so a merge WRITES its new file before the
        # old entry (and file) is replaced — a failed merged write must
        # leave the previous spill readable, never half-replaced
        return os.path.join(
            self.spill_dir,
            f"kvtier_{self._tid}_{uid}_{next(HostKVTier._next_gen)}.bin")

    def _read_planes(self, e: TierEntry) -> List[np.ndarray]:
        """The entry's planes as host arrays (file entries read back
        through the AIO engine — byte-identical to what was written)."""
        if e.planes is not None:
            return e.planes
        from ..ops.native.aio import get_io_engine

        io = get_io_engine()
        out, reqs, off = [], [], 0
        for shape, dtype in zip(e.shapes, e.dtypes):
            arr = np.empty(shape, dtype)
            reqs.append(io.submit_read(e.path, arr, offset=off))
            off += arr.nbytes
            out.append(arr)
        for r in reqs:
            io.wait(r)
        return out

    def store(self, uid: int, indices: Sequence[int],
              planes: Sequence[np.ndarray]) -> None:
        """Record ``uid``'s blocks at descriptor positions ``indices``
        with their pool-storage ``planes`` (host copies the caller just
        gathered). A second spill of the same uid MERGES (positions must
        be disjoint), so incremental cold-prefix spills compose. With
        ``spill_dir``, bytes go to a generation-suffixed file through
        the AIO engine and the RAM copy is dropped; a failed write
        deletes the partial file and leaves the tier unchanged — on the
        merge path the OLD entry (and its file) survives intact until
        the merged bytes are fully written, so no previously spilled KV
        is ever lost to a failed re-spill."""
        indices = [int(i) for i in indices]
        planes = [np.ascontiguousarray(p) for p in planes]
        with self._mu:
            old = self._entries.get(uid)
        if old is not None:
            overlap = set(old.indices) & set(indices)
            if overlap:
                raise ValueError(
                    f"kv_tier: uid {uid} re-spills positions "
                    f"{sorted(overlap)} already in the tier")
            old_planes = self._read_planes(old)
            order = np.argsort(np.asarray(old.indices + indices),
                               kind="stable")
            planes = [np.ascontiguousarray(
                np.concatenate([op, p], axis=1)[:, order])
                for op, p in zip(old_planes, planes)]
            indices = sorted(old.indices + indices)
        nbytes = sum(p.nbytes for p in planes)
        shapes = [tuple(p.shape) for p in planes]
        dtypes = [p.dtype for p in planes]
        path = None
        if self.spill_dir is not None:
            from ..ops.native.aio import get_io_engine

            path = self._spill_path(uid)
            io = get_io_engine()
            try:
                off, reqs = 0, []
                for p in planes:
                    reqs.append(io.submit_write(path, p, offset=off))
                    off += p.nbytes
                for r in reqs:
                    io.wait(r)
            except BaseException:
                try:
                    os.remove(path)
                except OSError:
                    pass
                raise
        entry = TierEntry(indices=indices, shapes=shapes, dtypes=dtypes,
                          planes=None if path is not None else planes,
                          path=path, nbytes=nbytes)
        with self._mu:
            # refuse when a concurrent store/drop raced the merge read —
            # never clobber state the merge never saw
            raced = self._entries.get(uid) is not old
            if not raced:
                self._entries[uid] = entry
                self._release_staging(uid)   # stale staging, if any
                self.spills += 1
                self.spilled_blocks += len(indices) - (
                    len(old.indices) if old is not None else 0)
                self.host_bytes += nbytes - (old.nbytes if old is not None
                                             else 0)
        if raced:
            if path is not None:
                try:
                    os.remove(path)
                except OSError:
                    pass
            raise RuntimeError(
                f"kv_tier: uid {uid} mutated concurrently with a store "
                f"— spill calls must be serialized per uid")
        if old is not None and old.path is not None:
            try:
                os.remove(old.path)
            except OSError:
                pass

    def prefetch(self, uid: int) -> bool:
        """Stage ``uid``'s spilled bytes into pinned buffers ahead of the
        fetch (the double-buffer half: file read / RAM copy runs here, off
        the fetch critical path). Bounded by ``prefetch_depth`` staged
        uids — the oldest staging is evicted past it. Returns True when a
        staging now exists (already-staged uids are a cheap no-op)."""
        with self._mu:
            e = self._entries.get(uid)
            if e is None:
                return False
            if uid in self._staged:
                return True
            if uid in self._slots:
                return False   # another prefetch of this uid in flight
            # evict committed stagings past the depth bound (oldest
            # first — no in-flight copy targets an evicted slot, since
            # in-flight uids are in _slots but never in _staged yet)
            while len(self._staged) >= max(1, self.prefetch_depth):
                evicted = next(iter(self._staged))
                self._staged.pop(evicted)
                self._free_slots.append(self._slots.pop(evicted))
            if self._free_slots:
                slot = self._free_slots.pop()
            else:
                slot = self._next_slot
                self._next_slot += 1
            self._slots[uid] = slot
        try:
            planes = self._read_planes(e)
            staged = []
            for i, p in enumerate(planes):
                buf = self.pool.staging(("kv_tier", self._tid, slot, i),
                                        p.shape, p.dtype)
                np.copyto(buf, p)
                staged.append(buf)
        except Exception as exc:
            # prefetch is pure optimization — a failed read/copy must not
            # crash the tick that requested it, and the reservation must
            # recycle or this uid could never be staged again (and the
            # slot's staging keys would leak in the pinned pool). The
            # slot recycles UNCONDITIONALLY (same as the stale-commit
            # path below): a concurrent store/drop pops an uncommitted
            # reservation without freeing it, expecting exactly this
            # cleanup to return the slot id
            with self._mu:
                if self._slots.get(uid) == slot:
                    del self._slots[uid]
                self._free_slots.append(slot)
            logger.warning(
                f"kv_tier: prefetch of uid {uid} failed ({exc!r}) — "
                f"fetch will assemble cold")
            return False
        with self._mu:
            if self._entries.get(uid) is not e or \
                    self._slots.get(uid) != slot:
                # raced a store/drop; the staging is stale — recycle the
                # reservation (the copy above has finished, so the
                # slot's buffers are quiescent before reuse)
                if self._slots.get(uid) == slot:
                    del self._slots[uid]
                self._free_slots.append(slot)
                return False
            self._staged[uid] = staged
            self.prefetches += 1
        return True

    def load(self, uid: int,
             count: bool = True) -> Tuple[List[int], List[np.ndarray]]:
        """(indices, planes) for the fetch path — NON-destructive (the
        engine drops the entry only after the device scatter committed,
        so a crashed fetch leaves the tier byte-identically intact).
        Served from the prefetch staging when present (hit), assembled
        cold otherwise (miss). ``count=False`` reads without touching
        the fetch/hit counters (the export path — a failover migration
        reading spilled bytes is not a decode-window fetch)."""
        with self._mu:
            e = self._entries.get(uid)
            if e is None:
                raise KeyError(f"kv_tier: uid {uid} has no spilled blocks")
            # the export path (count=False) runs on the failover thread;
            # the staged pinned buffers belong to the tick thread, whose
            # next prefetch eviction recycles their slot and copytos
            # ANOTHER sequence's bytes into them mid-read — exports
            # assemble from the entry's own host bytes instead of
            # borrowing live staging views
            staged = self._staged.get(uid) if count else None
            if count:
                self.fetches += 1
                if staged is not None:
                    self.prefetch_hits += 1
                else:
                    self.prefetch_misses += 1
        if staged is not None:
            return list(e.indices), staged
        return list(e.indices), self._read_planes(e)

    @requires_lock("_mu")
    def _release_staging(self, uid: int) -> None:
        """Under ``_mu``: forget ``uid``'s staging. A COMMITTED staging's
        slot recycles immediately; an in-flight prefetch (slot reserved
        but not yet committed) recycles its own slot when its commit
        check fails — never here, while its copy may still be writing."""
        committed = self._staged.pop(uid, None) is not None
        slot = self._slots.pop(uid, None)
        if committed and slot is not None:
            self._free_slots.append(slot)

    def drop(self, uid: int) -> None:
        """Forget ``uid``'s tier state (fetch committed, or the sequence
        flushed). Deletes the spill file; safe for unknown uids."""
        with self._mu:
            e = self._entries.pop(uid, None)
            self._release_staging(uid)
            if e is not None:
                self.spilled_blocks -= len(e.indices)
                self.host_bytes -= e.nbytes
        if e is not None and e.path is not None:
            try:
                os.remove(e.path)
            except OSError:
                pass

    def reset_counters(self) -> None:
        """Zero the traffic counters (spills/fetches/prefetch hits and
        misses) without touching resident entries — a measurement epoch
        (e.g. the bench row's measured pass after its warm pass) starts
        from a clean count."""
        with self._mu:
            self.spills = self.fetches = self.prefetches = 0
            self.prefetch_hits = self.prefetch_misses = 0

    def stats(self) -> Dict[str, object]:
        with self._mu:
            return {
                "spills": self.spills,
                "fetches": self.fetches,
                "prefetches": self.prefetches,
                "prefetch_hits": self.prefetch_hits,
                "prefetch_misses": self.prefetch_misses,
                "hit_rate": self.hit_rate,
                "spilled_blocks": self.spilled_blocks,
                "host_bytes": self.host_bytes,
                "spilled_uids": len(self._entries),
                "spill_dir": self.spill_dir,
            }
