"""Inference engines — the serving half of the framework.

Capability analog of the reference's two inference stacks:
  - v1 "kernel injection" serving (``inference/engine.py:40`` InferenceEngine,
    ``init_inference`` ``deepspeed/__init__.py:299``): here a jit-compiled
    prefill + decode path over a dense KV cache with tensor-parallel sharded
    weights (the AutoTP analog is the model's partition specs).
  - v2 "FastGen" ragged/paged serving (``inference/v2/engine_v2.py:30``):
    here a paged KV cache (block allocator + block tables), per-sequence
    state manager, and a continuous-batching ``put/query/flush`` API.
"""

from .config import (InferenceConfig, RouterConfig, SamplingParams,
                     ServingConfig, SpeculativeConfig)
from .engine import InferenceEngine, init_inference, load_serving_weights
from .paged import BlockedAllocator, PagedKVCache
from .engine_v2 import (ImportReservation, InferenceEngineV2, KVBlockPayload,
                        SequenceDescriptor)
from .scheduler import (ContinuousBatchingScheduler, DeadlineExceededError,
                        ServingRequest)
from .speculative import DraftModelDrafter, NGramDrafter, make_drafter

__all__ = [
    "InferenceConfig",
    "RouterConfig",
    "SamplingParams",
    "ServingConfig",
    "SpeculativeConfig",
    "DraftModelDrafter",
    "NGramDrafter",
    "make_drafter",
    "InferenceEngine",
    "init_inference",
    "load_serving_weights",
    "BlockedAllocator",
    "PagedKVCache",
    "ImportReservation",
    "InferenceEngineV2",
    "KVBlockPayload",
    "SequenceDescriptor",
    "ContinuousBatchingScheduler",
    "DeadlineExceededError",
    "ServingRequest",
]
