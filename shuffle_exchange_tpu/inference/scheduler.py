"""Continuous-batching serving scheduler — Dynamic SplitFuse over engine_v2.

Capability analog of the reference FastGen *scheduler* (SURVEY §2.10): the
paged substrate (``ragged/ragged_manager.py:19`` DSStateManager +
``ragged/blocked_allocator.py:11``) is engine_v2's; what this module adds is
the iteration-level scheduling loop on top (the reference serves it from
MII's ``batching/ragged_batching.py`` ``ScheduleRequests``/``__call__``
around ``inference/v2/engine_v2.py:107 put``): a request queue and running
set where every tick packs a fixed per-step **token budget** with

  (a) one decode token for every running sequence, and
  (b) prefill *chunks* from queued / partially-prefilled sequences filling
      the remainder (chunked prefill a la Sarathi / Orca iteration-level
      scheduling — "Dynamic SplitFuse"),

then executes the whole mixed batch as ONE compiled dispatch via
``InferenceEngineV2.step()``. Uniform-size steps keep the chip busy through
phase changes: aggregate throughput rises with load instead of sinking into
host-driven phase-by-phase dispatches (the ROADMAP's "heavy traffic from
millions of users" north star).

KV pressure: admission is block-accounted before every dispatch; when the
allocator runs dry the youngest admitted sequence is preempted — its blocks
freed, the request requeued at the FRONT with its generated continuation
folded into the prefill target. Greedy decoding makes the replay
deterministic, so a preempted request's output is identical to an
uninterrupted run (tests/test_serving_scheduler.py pins this).

Counters (always observable through the in-process monitor, reference
``monitor/monitor.py:13``): ``serving/ttft_s``, ``serving/tpot_s``,
``serving/queue_depth``, ``serving/running``, ``serving/budget_fill``,
``serving/kv_free_blocks``, ``serving/tick_s``, ``serving/preemptions``,
and the prefix-cache group ``prefix_cache/{hit_tokens, miss_tokens,
cow_copies, shared_blocks}`` (ISSUE 6: with ``prefix_caching`` on,
admission reuses committed shared-prefix KV blocks ref-counted — zero new
allocations for the shared span — and prefill starts from the first
non-cached token, shrinking both TTFT and per-tick prefill spend).

Speculative decoding (ISSUE 8, ``serving.speculative``): a running
sequence may submit k draft tokens per tick — from the n-gram
prompt-lookup self-drafter or a small draft model (``speculative.py``) —
verified in the SAME one-dispatch mixed step via the extend path with
greedy acceptance, so each tick emits 1..k+1 tokens per sequence at
exact-token parity with sequential ``decode_loop`` (bf16 KV). The
``speculative/{proposed, accepted, rejected, acceptance_rate,
rollbacks}`` counter group tracks it; rejected drafts rewind paged-KV
state through ``InferenceEngineV2.rewind`` before anything commits.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..monitor import InMemoryMonitor, Monitor
from ..testing import faults, sanitizer
from ..utils.invariants import atomic_on_reject
from ..utils.logging import logger
from .config import SamplingParams, ServingConfig
from .engine_v2 import InferenceEngineV2
from .paged import blocks_needed

QUEUED, PREFILL, RUNNING, FINISHED = "queued", "prefill", "running", "finished"
FAILED = "failed"
# tiered KV (ISSUE 15): a PARKED request's cold blocks live in the host
# tier — it keeps its engine descriptor and generated tokens, takes no
# budget, and resumes via fetch (no re-prefill) when pressure subsides
PARKED = "parked"


class DeadlineExceededError(RuntimeError):
    """A request outlived its ``deadline_s`` before finishing (ISSUE 12).
    Deterministic and named: the message carries the uid, the deadline vs
    elapsed time, and the replica's state at expiry; the error object is
    retained on ``ServingRequest.error`` for the caller."""

    def __init__(self, uid: int, deadline_s: float, elapsed_s: float,
                 replica_id: int, generated: int, fleet_state: str):
        self.uid = uid
        self.deadline_s = deadline_s
        self.elapsed_s = elapsed_s
        super().__init__(
            f"request {uid} exceeded its {deadline_s:.3f}s deadline "
            f"({elapsed_s:.3f}s elapsed, {generated} tokens generated) on "
            f"replica {replica_id} [{fleet_state}]")


@dataclasses.dataclass
class ServingRequest:
    """One request's lifecycle state (queued -> prefill -> running ->
    finished, with preemption looping running -> queued)."""

    uid: int
    prompt: List[int]
    max_new_tokens: int
    state: str = QUEUED
    prefill_done: int = 0
    generated: List[int] = dataclasses.field(default_factory=list)
    submitted_at: float = 0.0
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    tpot_s: List[float] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    # ticks this request spent in the decode/verify lane (ISSUE 8): with
    # speculation on, decode_ticks / len(generated) is the per-sequence
    # steps-per-emitted-token — the lever speculative decoding pulls
    decode_ticks: int = 0
    # request-level robustness (ISSUE 12): ``deadline_s`` caps wall time
    # from submission (expired requests FAIL with a typed error at the
    # next tick boundary); ``not_before`` is the failover backoff gate —
    # a re-placed request yields its packing slot until the clock passes
    # it; ``retries`` counts failover re-placements and
    # ``replica_deaths`` the replica deaths it was mid-execution for
    # (the poison-quarantine signal). ``error`` retains the typed error
    # a FAILED request died with.
    deadline_s: Optional[float] = None
    not_before: float = 0.0
    retries: int = 0
    replica_deaths: int = 0
    error: Optional[BaseException] = None
    # tiered KV (ISSUE 15): the state a PARKED request resumes into
    # (PREFILL mid-prompt, RUNNING mid-decode) — recorded at park time
    # because ``prefill_target`` keeps growing with generated tokens
    parked_state: str = ""
    # one-dispatch sampling (ISSUE 16): per-request SamplingParams (None =
    # greedy, no EOS — the historical scheduler contract). The params ride
    # every export/inject/failover snapshot, so a re-placed request's
    # seeded chain replays bit-exactly on the survivor. ``stopped`` marks
    # EOS/stop-sequence early termination — the request finished before
    # its token budget, returning its KV blocks and running slot early.
    sampling: Optional[SamplingParams] = None
    stopped: bool = False
    # multi-tenant LoRA (ISSUE 18): the adapter this request decodes
    # under (None = base model, the reserved null slot 0). The id rides
    # every export/inject/failover snapshot so a re-placed request
    # re-binds the SAME adapter on the survivor. ``adapter_waiting``
    # marks a queued request parked on pool residency: it keeps its
    # FIFO seat but yields its packing slot until a slot frees — park,
    # never preempt, so adapter pressure costs queue time, not
    # re-prefill compute.
    adapter_id: Optional[str] = None
    adapter_waiting: bool = False
    # async weight sync (ISSUE 20): the serving weight version this
    # request's LAST token sampled under, stamped at finish — the
    # per-request staleness audit trail (bounded-window property tests
    # and honest RolloutRecord stamping read it, instead of assuming
    # every replica already serves the newest publish)
    weight_version: Optional[int] = None
    # expert-parallel MoE serving (ISSUE 19): a queued request parked on
    # expert-capacity pressure — the previous tick's routing saturated
    # some expert's buffer, so NEW sequences hold at their FIFO seat
    # until running ticks drain the pressure. Park, never preempt:
    # expert overload costs queue time, never a running sequence's KV.
    moe_waiting: bool = False

    @property
    def prefill_target(self) -> List[int]:
        """Tokens whose KV must exist before the next decode: the prompt
        plus everything generated so far. A preempted request re-enters
        prefill with its continuation folded in, so the replay resumes
        exactly where it left off."""
        return self.prompt + self.generated

    @property
    def done(self) -> bool:
        return self.stopped or len(self.generated) >= self.max_new_tokens


class ContinuousBatchingScheduler:
    """Queue + running set + per-tick token-budget packing over an
    :class:`InferenceEngineV2`. Decoding is greedy (the engine-parity
    reference semantics of ``decode_loop``); hook ``on_token`` for
    streaming output."""

    def __init__(self, engine: InferenceEngineV2,
                 on_token: Optional[Callable[[int, int], None]] = None,
                 monitor: Optional[Monitor] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 replica_id: int = 0,
                 drafter=None):
        if not isinstance(engine, InferenceEngineV2):
            raise TypeError("ContinuousBatchingScheduler needs the paged "
                            f"InferenceEngineV2, got {type(engine).__name__}")
        self.engine = engine
        # machine-readable replica identity (ISSUE 7): the serving router
        # runs N of these side by side and aggregates their stats() —
        # every summary and admission error can then name which replica
        # it talks about
        self.replica_id = int(replica_id)
        # a draining replica (SIGTERM'd, or scaled away) admits nothing
        # new; its unfinished requests are exported for requeue elsewhere
        self.draining = False
        # a FENCED replica was declared dead by the health layer while a
        # tick might still be in flight (hang): the zombie tick must emit
        # nothing when it finally returns — its requests were already
        # snapshotted and re-placed on survivors, so a late emission would
        # duplicate tokens. A bare bool write (no lock): the failover path
        # cannot take this replica's lock, the hung tick holds it.
        self.fenced = False
        self.cfg: ServingConfig = engine.config.serving
        self.queue: Deque[ServingRequest] = deque()  # FIFO; preempted at front
        self.active: List[ServingRequest] = []       # admission order
        # tiered KV (ISSUE 15): requests parked host-ward under pressure,
        # park order (oldest first — the unpark order); the engine's tier
        # is None unless the config enables kv_tier
        self.parked: List[ServingRequest] = []
        self.tier = getattr(engine, "tier", None)
        self.parks = 0
        self.unparks = 0
        # spillable_blocks() walks every live descriptor's block list —
        # too hot AND too racy for the router's load() polls (they run on
        # router threads while the tick thread mutates eng._seqs under
        # the replica lock), so ONLY the tick thread ever walks: the tick
        # tail (and the force-unpark early return) refresh this cache and
        # load() reads the plain int. Early-return ticks that free blocks
        # (deadline expiry on a backoff-gated tick) can leave it one tick
        # stale — acceptable for a placement-pressure heuristic.
        self._spillable_cache: int = 0
        self.requests: Dict[int, ServingRequest] = {}
        self.on_token = on_token
        self.clock = clock
        # always-on in-process sink (resilience-counter discipline): tests
        # and post-mortems read scheduler.memory_monitor.events even when
        # no external monitor backend is configured
        self.memory_monitor = InMemoryMonitor(maxlen=4096)
        self._sinks: List[Monitor] = [monitor] if monitor is not None else []
        self.ticks = 0
        self.preemptions = 0
        self.deadline_expired = 0
        self._next_uid = 0
        # speculative decoding (ISSUE 8): k drafts per running sequence
        # per tick, verified in the same one-dispatch mixed step. The
        # drafter comes from the config (ngram self-speculation needs no
        # weights; drafter="model" loads serving.speculative.draft_model
        # via models/hf) unless an instance is passed in — the router
        # hands each replica its engine's own serving config unchanged,
        # so per-replica speculation follows the replica's engine.
        self.spec = self.cfg.speculative
        self.drafter = drafter
        if self.spec.enabled and self.drafter is None:
            from .speculative import make_drafter

            self.drafter = make_drafter(self.spec, like=engine.config)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_rejected = 0
        # one-dispatch sampling (ISSUE 16): counters for the sampling/*
        # monitor group. ``sampling_seen`` latches once any request
        # carries SamplingParams — greedy-only serving never switches off
        # the step() path, so its dispatch behavior (and program-key
        # ladder) is bit-identical to pre-sampling builds.
        self.sampling_seen = False
        self.early_stops = 0
        self.dead_tokens_saved = 0
        self.sampling_resamples = 0
        # multi-tenant LoRA (ISSUE 18): the engine's AdapterPool (None
        # unless config.adapters.enabled), the residency-park counters,
        # and the per-adapter emitted-token tally the adapter/* monitor
        # group and per-tenant billing read
        self.apool = getattr(engine, "adapters", None)
        self.adapter_parks = 0
        self.adapter_unparks = 0
        self.adapter_tokens: Dict[str, int] = {}
        # expert-parallel MoE serving (ISSUE 19): expert-capacity park
        # counters for the moe/* monitor group (the engine owns the
        # routing-count tallies; the scheduler owns the admission parks)
        self.moe_capacity_parks = 0
        self.moe_unparks = 0

    # -- request intake ------------------------------------------------

    @atomic_on_reject(check="validate")
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               uid: Optional[int] = None,
               deadline_s: Optional[float] = None,
               sampling: Optional[SamplingParams] = None,
               adapter_id: Optional[str] = None) -> int:
        """Queue one request; returns its uid. Validates against the
        engine's hard caps up front so impossible requests fail at submit
        time with named numbers, not mid-serve. ``deadline_s`` caps the
        request's wall time from submission (ISSUE 12): a request still
        unfinished past it FAILS with a typed ``DeadlineExceededError``
        at the next tick boundary instead of holding budget forever.
        ``sampling`` (ISSUE 16) attaches per-request SamplingParams —
        temperature/top-k/top-p + seed sample in-dispatch off the seeded
        Gumbel chain, EOS/stop sequences end the request at the tick the
        stop hits. None inherits the engine config's ``sampling`` section
        (whose own default is exactly the historical greedy contract)."""
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if sampling is not None and not isinstance(sampling, SamplingParams):
            raise TypeError(
                f"sampling must be a SamplingParams, got "
                f"{type(sampling).__name__}")
        if sampling is None:
            base = self.engine.config.sampling
            if base != SamplingParams():
                sampling = base
        # multi-tenant LoRA (ISSUE 18): an unregistered adapter fails at
        # submit time with named numbers, never mid-serve — residency is
        # NOT checked here (a non-resident registered adapter pages in
        # at admission, or parks the request until a slot frees)
        if adapter_id is not None:
            if self.apool is None:
                raise ValueError(
                    f"replica {self.replica_id}: request names adapter "
                    f"{adapter_id!r} but the adapter pool is disabled "
                    f"(enable config.adapters)")
            if not self.apool.registered(adapter_id):
                raise ValueError(
                    f"replica {self.replica_id}: adapter {adapter_id!r} "
                    f"is not registered; publish_adapter it first")
        if self.draining:
            raise RuntimeError(
                f"replica {self.replica_id} is draining and admits no new "
                f"requests (route to a surviving replica)")
        prompt = list(map(int, prompt))
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        eng = self.engine
        total = len(prompt) + max_new_tokens
        if total > eng.config.max_seq_len:
            raise ValueError(
                f"replica {self.replica_id}: prompt {len(prompt)} + "
                f"max_new_tokens {max_new_tokens} = "
                f"{total} exceeds max_seq_len {eng.config.max_seq_len}")
        usable = eng.allocator.num_blocks - 1  # block 0 is scratch
        need_max = blocks_needed(total, eng.cache.block_size)
        if need_max > usable:
            # named numbers per replica (ISSUE 7 satellite): the router
            # aggregates these verbatim when NO replica can ever take the
            # request, so the fleet-level error still says which replica
            # wanted how many blocks against how many it has
            raise ValueError(
                f"replica {self.replica_id}: request needs up to {need_max} "
                f"KV blocks but the pool has "
                f"{usable} usable (num_kv_blocks={eng.allocator.num_blocks} "
                f"minus scratch); raise num_kv_blocks or shorten the request")
        if uid is None:
            while self._next_uid in self.requests or self._next_uid in eng._seqs:
                self._next_uid += 1
            uid = self._next_uid
            self._next_uid += 1
        elif uid in self.requests or uid in eng._seqs:
            raise ValueError(f"uid {uid} is already live")
        r = ServingRequest(uid=uid, prompt=prompt,
                           max_new_tokens=int(max_new_tokens),
                           submitted_at=self.clock(),
                           deadline_s=deadline_s,
                           sampling=sampling,
                           adapter_id=adapter_id)
        if sampling is not None:
            self.sampling_seen = True
        self.requests[uid] = r
        self.queue.append(r)
        return uid

    # -- bookkeeping helpers -------------------------------------------

    def _seen(self, r: ServingRequest) -> int:
        d = self.engine._seqs.get(r.uid)
        return d.seen_tokens if d else 0

    def _have_blocks(self, r: ServingRequest) -> int:
        d = self.engine._seqs.get(r.uid)
        return len(d.blocks) if d else 0

    def _preempt(self, r: ServingRequest) -> None:
        """Free a sequence's KV and requeue it at the front; its prefill
        target now includes the generated continuation (deterministic
        replay under greedy decoding)."""
        if r.uid in self.engine._seqs:
            self.engine.flush([r.uid])
        if self.drafter is not None:
            self.drafter.forget(r.uid)
        self.active.remove(r)
        r.state = QUEUED
        r.prefill_done = 0
        r.preemptions += 1
        self.preemptions += 1
        self.queue.appendleft(r)
        logger.info(
            f"serving: preempted uid {r.uid} ({len(r.generated)} tokens "
            f"generated) — KV pool pressure; requeued at front")

    def _park(self, r: ServingRequest) -> bool:
        """Park an admitted sequence host-ward instead of preempting it
        (ISSUE 15): its cold exclusive blocks spill to the tier (byte-
        exact), the descriptor and generated tokens stay, and a later
        tick fetches the bytes back — zero re-prefill compute, token-
        identical resume. Returns False when nothing was spillable (all
        blocks shared/hot) so the caller can fall back to preemption."""
        reclaimed = self.engine.spill_sequence(r.uid)
        if reclaimed <= 0:
            return False
        self.active.remove(r)
        r.parked_state = r.state
        r.state = PARKED
        self.parked.append(r)
        self.parks += 1
        logger.info(
            f"serving: parked uid {r.uid} ({reclaimed} KV blocks spilled "
            f"host-ward, {len(r.generated)} tokens kept) — KV pool "
            f"pressure; resumes via fetch, no re-prefill")
        return True

    def _unpark(self, r: ServingRequest) -> None:
        """Fetch a parked request's spilled blocks back into fresh pool
        slots and return it to the admitted set in its pre-park state."""
        self.engine.fetch_spilled(r.uid)
        self.parked.remove(r)
        r.state = r.parked_state or RUNNING
        r.parked_state = ""
        # re-enter at the request's ADMISSION-ORDER position, not the
        # tail: the park/preempt victim scans pick reversed(active) as
        # "youngest", so a tail append would re-victimize the unparked
        # request over genuinely younger ones, tick after tick
        idx = next((i for i, a in enumerate(self.active)
                    if a.submitted_at > r.submitted_at), len(self.active))
        self.active.insert(idx, r)
        self.unparks += 1

    def _finish(self, r: ServingRequest, now: float) -> None:
        r.state = FINISHED
        r.finished_at = now
        r.weight_version = self.engine.weight_version
        if r.uid in self.engine._seqs:
            # an early-stopped flush (ISSUE 16) tallies the KV blocks the
            # stop returned ahead of the request's budgeted lifetime
            self.engine.flush([r.uid], early_stop=r.stopped)
        if self.drafter is not None:
            self.drafter.forget(r.uid)
        if r in self.active:
            self.active.remove(r)
        if r in self.parked:
            self.parked.remove(r)

    def fail(self, r: ServingRequest, err: BaseException, now: float) -> None:
        """Terminally fail a request (deadline expiry, poison quarantine,
        retries exhausted): frees its KV, records the typed error on the
        request, and removes it from the queue/running set. Partial
        ``generated`` tokens stay readable on the request."""
        r.state = FAILED
        r.error = err
        r.finished_at = now
        if r.uid in self.engine._seqs:
            self.engine.flush([r.uid])
        if self.drafter is not None:
            self.drafter.forget(r.uid)
        if r in self.active:
            self.active.remove(r)
        if r in self.parked:
            self.parked.remove(r)
        if r in self.queue:
            self.queue.remove(r)
        logger.warning(f"serving: replica {self.replica_id} failed uid "
                       f"{r.uid}: {err}")

    def _expire_deadlines(self, now: float, events: list) -> None:
        """Fail every live request past its deadline (ISSUE 12). Runs at
        tick entry — the dispatch boundary — so an expiry never interleaves
        a half-executed tick, and the freed budget/KV goes to requests that
        can still meet theirs."""
        for r in [a for a in self.active] + list(self.parked) + list(self.queue):
            if r.deadline_s is None:
                continue
            elapsed = now - r.submitted_at
            if elapsed <= r.deadline_s:
                continue
            state = (f"state={r.state} queue_depth={len(self.queue)} "
                     f"running={len(self.active)} draining={self.draining}")
            err = DeadlineExceededError(r.uid, r.deadline_s, elapsed,
                                        self.replica_id, len(r.generated),
                                        state)
            self.fail(r, err, now)
            self.deadline_expired += 1
            events.append(("serving/deadline_expired",
                           self.deadline_expired, self.ticks))

    def _stop_hit(self, r: ServingRequest) -> bool:
        """Host-side stop-sequence check (ISSUE 16): does the generated
        stream now end with one of the request's stop sequences? EOS is
        the on-device flag; multi-token stop sequences are a suffix match
        on the small emitted list — the only per-token host work."""
        sp = r.sampling
        if sp is None or not sp.stop:
            return False
        g = r.generated
        return any(len(g) >= len(s) and tuple(g[-len(s):]) == s
                   for s in sp.stop)

    def _emit(self, r: ServingRequest, tok: int, now: float, events: list,
              eos: bool = False) -> None:
        r.generated.append(tok)
        if r.first_token_at is None:
            r.first_token_at = now
            events.append(("serving/ttft_s", now - r.submitted_at, self.ticks))
        elif r.last_token_at is not None:
            r.tpot_s.append(now - r.last_token_at)
            events.append(("serving/tpot_s", r.tpot_s[-1], self.ticks))
        r.last_token_at = now
        if r.adapter_id is not None:
            self.adapter_tokens[r.adapter_id] = \
                self.adapter_tokens.get(r.adapter_id, 0) + 1
        if self.on_token is not None:
            self.on_token(r.uid, tok)
        # EOS (the on-device flag) / stop sequence (host suffix match)
        # terminate the request at THIS tick: the stop token is kept in
        # ``generated``, the dead remainder of the budget never decodes,
        # and _finish returns the KV blocks and the running slot now
        if (eos or self._stop_hit(r)) and \
                len(r.generated) < r.max_new_tokens:
            r.stopped = True
            self.early_stops += 1
            self.dead_tokens_saved += r.max_new_tokens - len(r.generated)
        if r.done:
            self._finish(r, now)

    def _write_events(self, events: list) -> None:
        self.memory_monitor.write_events(events)
        for sink in self._sinks:
            sink.write_events(events)

    # -- the scheduling loop -------------------------------------------

    def tick(self) -> bool:
        """Pack one token-budget step and execute it as ONE dispatch.
        Returns True while admitted or queued work remains."""
        eng, cfg = self.engine, self.cfg
        bs = eng.cache.block_size

        # -1.5) concurrency sanitizer (ISSUE 13): a tick can park
        # indefinitely (cold compile, wedged dispatch, the replica_hang
        # drill) — dispatching one while the calling thread holds any
        # instrumented lock beyond this replica's own guard is the PR 11
        # deadlock shape, reported with both stacks. Disarmed: one bool.
        sanitizer.check_blocking("scheduler.tick", allow=("Replica.lock",))

        # -1) fault sites (ISSUE 12, armed per replica id): all three land
        # HERE, at tick entry — the dispatch boundary, where a real
        # preemption becomes observable — so a tripped fault never leaves
        # a half-executed tick. A hang parks until the failover path
        # fences this scheduler (or the drill releases it); the fence
        # check right after makes the woken zombie emit nothing.
        if faults.ACTIVE:
            faults.maybe_hang("replica_hang", self.replica_id,
                              wake=lambda: self.fenced)
            faults.maybe_crash("replica_crash", self.replica_id,
                               exc=faults.ReplicaCrashed)
            faults.maybe_crash("tick_exception", self.replica_id)
        if self.fenced:
            return False

        # 0) tick boundary (ISSUE 11): a deferred weight commit
        # (reload_weights/publish_weights with defer=True) lands HERE —
        # the previous tick's dispatch has fully drained and the next has
        # not packed yet, so the swap can never interleave a half-executed
        # tick. KV pools, allocator, and compiled programs all survive;
        # live sequences continue (mixed-weight, no_commit) exactly as a
        # force swap would leave them, but at a defined boundary.
        if eng.has_pending_weights and eng.apply_pending_weights():
            logger.info(
                f"serving: replica {self.replica_id} applied deferred "
                f"weight swap at tick boundary (now version "
                f"{eng.weight_version})")

        # 0.5) request deadlines (ISSUE 12): expire before packing, so an
        # expired request's budget and KV blocks fund live ones this tick
        now0 = self.clock()
        pre_events: list = []
        self._expire_deadlines(now0, pre_events)
        if pre_events:
            self._write_events(pre_events)

        # 0.7) tiered KV (ISSUE 15): un-park in park order while the pool
        # can fund the fetch plus headroom (one block per running sequence
        # and one for the un-parked sequence's own next decode write) —
        # the conservative gate that keeps park/unpark from thrashing
        if self.tier is not None and self.parked:
            while self.parked and len(self.active) < cfg.max_running:
                r = self.parked[0]
                desc = eng._seqs.get(r.uid)
                need = len(desc.spilled) if desc is not None else 0
                headroom = 1 + sum(1 for a in self.active
                                   if a.state == RUNNING)
                if need + headroom > eng.free_blocks:
                    break
                self._unpark(r)

        # 1) decode set: every running sequence takes one budget slot — or
        # 1+k slots when its drafter proposes k tokens this tick (ISSUE 8:
        # the pending token plus the drafts are one verify row through the
        # same dispatch). Draft+verify tokens are accounted — budget AND
        # KV blocks — BEFORE any state mutation; if the pool can't hold
        # them, preempt the youngest admitted sequence until it can.
        spec_rows: Dict[int, List[int]] = {}
        if self.spec.enabled and self.drafter is not None:
            reqs = []
            for r in self.active:
                if r.state != RUNNING:
                    continue
                # constrained rows (ISSUE 16): a logit_mask changes the
                # target chain per step, which drafters can't see — masked
                # requests decode one token at a time
                if r.sampling is not None and r.sampling.logit_mask is not None:
                    continue
                # cap the draft width so an accepted run can never emit
                # past max_new_tokens or write past max_seq_len
                cap = min(self.spec.k,
                          r.max_new_tokens - len(r.generated) - 1,
                          eng.config.max_seq_len - self._seen(r) - 1)
                if cap >= 1:
                    reqs.append((r, r.prompt + r.generated, cap))
            if reqs:
                # batch-shaped drafters (the draft-model one) propose the
                # whole tick's rows in one pass — one sync put + one
                # decode_loop dispatch per k, not one dispatch per row
                many = getattr(self.drafter, "propose_many", None)
                if many is not None:
                    got = many([(r.uid, h, c) for r, h, c in reqs])
                else:
                    got = {r.uid: self.drafter.propose(r.uid, h, c)
                           for r, h, c in reqs}
                for r, _, cap in reqs:
                    drafts = got.get(r.uid) or []
                    if drafts:
                        spec_rows[r.uid] = ([r.generated[-1]]
                                            + [int(t) for t in drafts[:cap]])

        def row_cost(r):
            return len(spec_rows.get(r.uid, ())) or 1

        def decode_need(rs):
            return sum(max(0, blocks_needed(self._seen(r) + row_cost(r), bs)
                           - self._have_blocks(r)) for r in rs)

        while True:
            decodes = [r for r in self.active if r.state == RUNNING]
            if decode_need(decodes) <= eng.free_blocks or not self.active:
                break
            # draft widths are OPTIONAL work: before preempting anyone,
            # demote the youngest verify row to a plain decode token and
            # recheck — dropping a proposal costs nothing (the drafter
            # resyncs off the emitted history next tick), where a preempt
            # flushes KV and replays the whole prefill
            victim = next((r for r in reversed(self.active)
                           if r.uid in spec_rows), None)
            if victim is not None:
                spec_rows.pop(victim.uid)
                continue
            # tiered KV (ISSUE 15): spillable blocks are reclaimable-not-
            # free — park the youngest admitted sequence host-ward
            # (byte-exact spill, no lost work) before ever preempting one
            # (flush + full re-prefill replay). Preemption remains the
            # fallback when nothing is spillable (all blocks shared).
            if self.tier is not None:
                # youngest-first, but keep probing older actives when the
                # youngest has nothing spillable (all blocks shared via
                # the prefix cache, or all hot): preemption is the
                # fallback only when NOTHING on the replica can spill
                pv = next((r for r in reversed(self.active)
                           if r.uid in eng._seqs and self._park(r)), None)
                if pv is not None:
                    spec_rows.pop(pv.uid, None)
                    continue
            self._preempt(self.active[-1])

        decode_cost = sum(row_cost(r) for r in decodes)
        budget_left = cfg.token_budget - decode_cost
        free_left = eng.free_blocks - decode_need(decodes)

        # 2) fill the remainder with prefill chunks: partially-prefilled
        # actives first (admission order), then FIFO admission from the
        # queue while the running-set cap and KV pressure allow. Strict
        # head-of-line order — a request never overtakes an earlier one
        # into the prefill lane, so admission is starvation-free.
        prefills: List[Tuple[ServingRequest, List[int]]] = []
        admitted: List[Tuple[ServingRequest, int]] = []
        for r in [a for a in self.active if a.state == PREFILL] + list(self.queue):
            if budget_left <= 0:
                break
            from_queue = r.state == QUEUED
            if from_queue and r.not_before > now0:
                # failover backoff (ISSUE 12): a re-placed request yields
                # its packing slot until its backoff window passes — the
                # one sanctioned exception to strict FIFO, since holding
                # the head would stall every request behind it for the
                # whole backoff
                continue
            if from_queue and r.adapter_id is not None and \
                    self.apool is not None:
                # multi-tenant LoRA (ISSUE 18): can the pool seat this
                # request's adapter ALONGSIDE everything already planned
                # this tick (batch-aware — a plan may not evict its own
                # hits)? If not, park in place: the request keeps its
                # FIFO seat, younger base-model or resident-adapter work
                # may pass it, and NO running sequence is ever preempted
                # for an adapter slot. The actual acquire happens at the
                # admission commit below, so a loop that breaks early
                # mutates nothing.
                want = [a.adapter_id for a, _ in admitted] + [r.adapter_id]
                if not self.apool.can_acquire_all(want)[0]:
                    if not r.adapter_waiting:
                        r.adapter_waiting = True
                        self.adapter_parks += 1
                    continue
            if from_queue and getattr(eng, "_moe_serving", False) and \
                    self.cfg.moe.overload_policy == "park" and \
                    (self.active or admitted) and \
                    eng.moe_pressure() > self.cfg.moe.overload_threshold:
                # expert capacity is the next admission resource after KV
                # blocks, tier residency, and adapter slots (ISSUE 19):
                # the previous tick's routing counts say some expert ran
                # past its buffer, so hold NEW sequences at their FIFO
                # seat — running ticks keep decoding (their routing is
                # what drains the pressure) and no sequence is ever
                # preempted for expert load. The ``active or admitted``
                # guard keeps a stale reading with nothing running from
                # parking the whole queue forever. Policy "drop" admits
                # anyway and lets the capacity impl drop overload tokens
                # on device (counted in moe/dropped).
                if not r.moe_waiting:
                    r.moe_waiting = True
                    self.moe_capacity_parks += 1
                continue
            if from_queue and self.parked and \
                    self.parked[0].submitted_at <= r.submitted_at:
                # tiered KV (ISSUE 15): freed blocks must fund the oldest
                # parked fetch before any YOUNGER arrival may consume
                # them — otherwise sustained arrivals absorb every freed
                # block chunk-by-chunk and the parked head starves
                # against the all-at-once unpark gate. Seniority is by
                # submission time, not queue-vs-parked lane: a preempted
                # request re-queued at the front can be OLDER than every
                # parked sequence and then packs ahead of them. Stop the
                # queue lane at the first younger request; in-flight
                # prefills above still pack (finishing them is what
                # frees blocks).
                break
            if from_queue and len(self.active) + len(admitted) >= cfg.max_running:
                break
            target = r.prefill_target
            if from_queue:
                # prefix cache: plan the admission from the first
                # NON-CACHED token — a LIVE shared block costs zero free
                # slots, a parked one only its revival slot (the engine
                # acquisition happens at the admission commit below, so a
                # packing loop that breaks early mutates nothing)
                hit, live, _parked = eng.prefix_peek(target)
                pd, free_have = hit, live
            else:
                pd, free_have = r.prefill_done, self._have_blocks(r)
            remaining = len(target) - pd
            chunk = min(budget_left, remaining)
            # a leftover-budget sliver that does not finish the prompt is
            # not worth a dispatch slot — wait for a fuller tick
            if chunk < remaining and chunk < cfg.chunk_min:
                break
            fit = (free_left + free_have) * bs - pd
            chunk = min(chunk, fit)
            if chunk <= 0 or (chunk < remaining and chunk < cfg.chunk_min):
                break
            free_left -= max(0, blocks_needed(pd + chunk, bs) - free_have)
            budget_left -= chunk
            prefills.append((r, target[pd:pd + chunk]))
            if from_queue:
                admitted.append((r, pd))
                if r.adapter_waiting:
                    r.adapter_waiting = False
                    self.adapter_unparks += 1
                if r.moe_waiting:
                    r.moe_waiting = False
                    self.moe_unparks += 1
        for r, hit in admitted:
            self.queue.remove(r)
            self.active.append(r)
            r.state = PREFILL
            # multi-tenant LoRA (ISSUE 18): stage the adapter binding
            # BEFORE the engine admission — acquire_prefix consumes the
            # pending binding and pins the pool slot, so the descriptor
            # is born adapter-bound and this very tick's chunk already
            # runs under the adapter's slot row
            if r.adapter_id is not None:
                eng.configure_adapter(r.uid, r.adapter_id)
            # admit in the engine NOW so shared prefix blocks are
            # ref-counted before the dispatch: the descriptor starts at
            # the cached boundary and this tick's chunk prefills only the
            # suffix (acquire_prefix is a cold admission when
            # prefix_caching is off — hit is 0 either way then)
            got = eng.acquire_prefix(r.uid, r.prefill_target)
            assert got == hit, (r.uid, got, hit)
            r.prefill_done = hit
            # one-dispatch sampling (ISSUE 16): the descriptor exists now
            # — attach the request's SamplingParams so the sampled step's
            # per-row operands pick them up from the first chunk onward
            if r.sampling is not None:
                eng.configure_sampling(r.uid, r.sampling)

        # 3) nothing packable?
        if not decodes and not prefills:
            if not (self.active or self.queue or self.parked):
                return False
            if self.parked and not self.active:
                # tiered KV (ISSUE 15): everything admitted is parked —
                # force-unpark the oldest past the headroom gate (nothing
                # else will free blocks) so progress resumes next tick.
                # The fetch must ALSO fund the sequence's own next decode
                # write when it sits on a block boundary: an equality
                # admit there leaves free_blocks == 0, the next tick
                # parks it right back, and the park/unpark pair livelocks
                # serve() without ever reaching the loud error below.
                r = self.parked[0]
                desc = eng._seqs.get(r.uid)
                need = len(desc.spilled) if desc is not None else 0
                if desc is not None and desc.seen_tokens % \
                        eng.config.kv_block_size == 0:
                    need += 1
                if desc is not None and need > eng.free_blocks:
                    # the OTHER parked sequences' hot tails
                    # (hot_block_fraction keeps them resident through
                    # _park) are reclaimable — spill them fully before
                    # declaring a stall the pool could still serve
                    for other in self.parked[1:]:
                        if eng.free_blocks >= need:
                            break
                        if other.uid in eng._seqs:
                            eng.spill_sequence(other.uid, keep_hot=0)
                if desc is not None and need <= eng.free_blocks:
                    self._unpark(r)
                    # this early return skips the tick-tail cache
                    # refresh, and the fetch just moved block state
                    self._spillable_cache = eng.spillable_blocks()
                    return True
                raise RuntimeError(
                    f"serving stalled: parked uid {r.uid} needs "
                    f"{need} KV blocks (spilled fetch + next decode "
                    f"write) but only {eng.free_blocks} of "
                    f"{eng.allocator.num_blocks} are free and nothing is "
                    f"running to release more; raise num_kv_blocks")
            if any(r.not_before > now0 or r.adapter_waiting
                   or r.moe_waiting for r in self.queue):
                # everything eligible is in its failover backoff window
                # or parked on adapter-pool residency — work remains, it
                # just may not pack yet (running/parked sequences release
                # slots as they finish)
                return True
            head = next((r for r in self.active if r.state == PREFILL),
                        self.queue[0] if self.queue else None)
            if head is None:     # running set exists; it will free budget
                return True
            raise RuntimeError(
                f"serving stalled: uid {head.uid} needs "
                f"{blocks_needed(len(head.prefill_target), bs)} KV blocks "
                f"for its prefill but only {eng.free_blocks} of "
                f"{eng.allocator.num_blocks} are free and nothing is "
                f"running to release more; raise num_kv_blocks or lower "
                f"max_running/concurrency")

        # 4) ONE mixed dispatch for the whole tick: plain decode rows,
        # prefill chunk rows, and speculative verify rows all ride it
        self.ticks += 1
        packed = decode_cost + sum(len(c) for _, c in prefills)
        spec_batch = [(r, spec_rows[r.uid]) for r in decodes
                      if r.uid in spec_rows]
        plain = [r for r in decodes if r.uid not in spec_rows]
        # one-dispatch sampling (ISSUE 16): any participant carrying
        # SamplingParams flips the WHOLE tick onto step_sampled — greedy
        # rows inside it are bit-identical to step()'s argmax chain, and
        # logits never ship to host. A tick with no sampled participant
        # keeps the historical step() path byte-for-byte.
        sampled = any(r.sampling is not None
                      for r in decodes) or any(r.sampling is not None
                                               for r, _ in prefills)
        t0 = self.clock()
        dtoks = ddone = ptoks = pdone = None
        if sampled:
            out = eng.step_sampled(
                [r.uid for r in plain], [r.generated[-1] for r in plain],
                [(r.uid, c) for r, c in prefills],
                speculative=[(r.uid, c) for r, c in spec_batch])
            dtoks, ddone, ptoks, pdone = out[:4]
            sres = out[4] if spec_batch else []
        elif spec_batch:
            dlogits, plogits, sres = eng.step(
                [r.uid for r in plain], [r.generated[-1] for r in plain],
                [(r.uid, c) for r, c in prefills],
                speculative=[(r.uid, c) for r, c in spec_batch])
        else:
            dlogits, plogits = eng.step(
                [r.uid for r in plain], [r.generated[-1] for r in plain],
                [(r.uid, c) for r, c in prefills])
            sres = []
        tick_s = self.clock() - t0
        if self.fenced:
            # the health layer declared this replica dead while the
            # dispatch was in flight: its requests were snapshotted and
            # re-placed on survivors — emitting now would duplicate tokens
            return False

        # 5) results: decode tokens stream immediately; a verify row
        # streams its accepted drafts plus the verifier's correction/bonus
        # token (every one the exact greedy/seeded chain); a finished
        # prefill yields the sequence's next token (its FIRST for fresh
        # requests)
        now = self.clock()
        events: list = []
        for i, r in enumerate(plain):
            r.decode_ticks += 1
            if sampled:
                self._emit(r, int(dtoks[i]), now, events,
                           eos=bool(ddone[i]))
            else:
                self._emit(r, int(np.argmax(dlogits[i])), now, events)
        for (r, chunk), (a, emitted) in zip(spec_batch, sres):
            j = len(chunk) - 1
            r.decode_ticks += 1
            self.spec_proposed += j
            self.spec_accepted += a
            self.spec_rejected += j - a
            sp = r.sampling
            if sp is not None and sp.temperature > 0 and a < j:
                # the residual-resample event (Leviathan): the chain
                # replaced the first rejected draft with its own token
                self.sampling_resamples += 1
            eos_id = sp.eos_token_id if sp is not None else -1
            for t in emitted:
                self._emit(r, int(t), now, events,
                           eos=(eos_id >= 0 and int(t) == eos_id))
                if r.done:
                    # EOS/stop inside the accepted run: the tokens after
                    # it are dead — never emitted, request already flushed
                    break
        for i, (r, chunk) in enumerate(prefills):
            r.prefill_done += len(chunk)
            if r.prefill_done == len(r.prefill_target):
                r.state = RUNNING
                if sampled:
                    self._emit(r, int(ptoks[i]), now, events,
                               eos=bool(pdone[i]))
                else:
                    self._emit(r, int(np.argmax(plogits[i])), now, events)
        events += [
            ("serving/queue_depth", len(self.queue), self.ticks),
            ("serving/running", len(decodes), self.ticks),
            ("serving/budget_fill", packed / cfg.token_budget, self.ticks),
            ("serving/kv_free_blocks", eng.free_blocks, self.ticks),
            ("serving/tick_s", tick_s, self.ticks),
            ("serving/preemptions", self.preemptions, self.ticks),
            # prefix-cache group (cumulative engine counters; ISSUE 6):
            # hit/miss tokens say how much prefill the cache absorbed,
            # cow_copies counts divergence clones, shared_blocks is the
            # CURRENT cross-sequence sharing in the pool
            ("prefix_cache/hit_tokens", eng.prefix_hit_tokens, self.ticks),
            ("prefix_cache/miss_tokens", eng.prefix_miss_tokens, self.ticks),
            ("prefix_cache/cow_copies", eng.cow_copies, self.ticks),
            ("prefix_cache/shared_blocks", eng.allocator.shared_blocks,
             self.ticks),
            # weight-version watermark (ISSUE 11): every tick records the
            # serving weight version its tokens were sampled under, so a
            # post-mortem can line the event stream up against the RLHF
            # replay log's per-rollout versions
            ("weights/version", eng.weight_version, self.ticks),
        ]
        if self.spec.enabled:
            # speculative group (cumulative; ISSUE 8): proposed/accepted/
            # rejected count draft tokens, acceptance_rate is their ratio,
            # rollbacks counts the engine's rejected-draft KV rewinds
            events += [
                ("speculative/proposed", self.spec_proposed, self.ticks),
                ("speculative/accepted", self.spec_accepted, self.ticks),
                ("speculative/rejected", self.spec_rejected, self.ticks),
                ("speculative/acceptance_rate",
                 self.spec_accepted / max(1, self.spec_proposed), self.ticks),
                ("speculative/rollbacks", eng.spec_rollbacks, self.ticks),
            ]
        if self.sampling_seen:
            # sampling group (cumulative; ISSUE 16): early_stops counts
            # EOS/stop-sequence terminations, dead_tokens_saved the budget
            # tokens they never decoded (the goodput lever), resamples the
            # speculative residual-resample events at temperature>0, and
            # early_stop_freed_blocks the KV the stops returned early
            events += [
                ("sampling/early_stops", self.early_stops, self.ticks),
                ("sampling/dead_tokens_saved", self.dead_tokens_saved,
                 self.ticks),
                ("sampling/resamples", self.sampling_resamples, self.ticks),
                ("sampling/early_stop_freed_blocks",
                 eng.early_stop_freed_blocks, self.ticks),
            ]
        if self.tier is not None:
            # tiered-KV group (ISSUE 15): spill/fetch traffic, prefetch
            # effectiveness, and the current host-tier footprint
            ts = self.tier.stats()
            events += [
                ("kv_tier/spills", ts["spills"], self.ticks),
                ("kv_tier/fetches", ts["fetches"], self.ticks),
                ("kv_tier/hit_rate",
                 ts["hit_rate"] if ts["hit_rate"] is not None else 0.0,
                 self.ticks),
                ("kv_tier/prefetch_misses", ts["prefetch_misses"],
                 self.ticks),
                ("kv_tier/spilled_blocks", ts["spilled_blocks"], self.ticks),
                ("kv_tier/host_bytes", ts["host_bytes"], self.ticks),
                ("kv_tier/parked", len(self.parked), self.ticks),
                ("kv_tier/parks", self.parks, self.ticks),
                ("kv_tier/unparks", self.unparks, self.ticks),
            ]
            # double-buffered prefetch (ISSUE 15): stage the next
            # ``prefetch_depth`` parked sequences' host bytes into pinned
            # buffers NOW — one tick ahead of the decode window they
            # rejoin — so their fetch is only the device scatter
            depth = max(0, eng.config.kv_tier.prefetch_depth)
            for r in self.parked[:depth]:
                self.tier.prefetch(r.uid)
        if self.apool is not None:
            # multi-tenant LoRA group (ISSUE 18): pool traffic plus the
            # scheduler's residency parks — a park is a FIFO-seat yield,
            # never a preemption, so adapter pressure shows up here as
            # queue time, not re-prefill compute
            ast = self.apool.stats()
            events += [
                ("adapter/hits", ast["hits"], self.ticks),
                ("adapter/misses", ast["misses"], self.ticks),
                ("adapter/evictions", ast["evictions"], self.ticks),
                ("adapter/parks", self.adapter_parks, self.ticks),
                ("adapter/unparks", self.adapter_unparks, self.ticks),
                ("adapter/active_adapters", ast["resident"], self.ticks),
            ]
            for aid in sorted(self.adapter_tokens):
                events.append((f"adapter/tokens/{aid}",
                               self.adapter_tokens[aid], self.ticks))
            # double-buffered adapter prefetch (the kv_tier discipline):
            # stage the next waiting adapters' padded factor planes into
            # pinned buffers one tick ahead of the admission that will
            # install them, so the acquire-miss copy is pinned-host ->
            # device only
            depth = max(0, eng.config.adapters.prefetch_depth)
            staged = 0
            seen: set = set()
            for r in self.queue:
                if staged >= depth:
                    break
                aid = r.adapter_id
                if aid is None or aid in seen or \
                        self.apool.slot_of(aid) is not None:
                    continue
                self.apool.prefetch(aid)
                seen.add(aid)
                staged += 1
        if getattr(eng, "_moe_serving", False):
            # expert-parallel MoE group (ISSUE 19): routing traffic from
            # the engine's per-tick counts (dispatched assignments, drops
            # at expert capacity, peak per-(layer, expert) load) plus the
            # scheduler's capacity parks — like adapter parks, a park is
            # a FIFO-seat yield under expert pressure, never a preemption
            events += [
                ("moe/dispatched", eng.moe_dispatched, self.ticks),
                ("moe/dropped", eng.moe_dropped, self.ticks),
                ("moe/capacity_parks", self.moe_capacity_parks, self.ticks),
                ("moe/expert_load_max", eng.moe_expert_load_max, self.ticks),
            ]
        # block state settled for this tick — refresh the placement-
        # pressure cache HERE, on the tick thread, where the _seqs walk
        # is safe (see __init__); load() only ever reads the int
        if self.tier is not None:
            self._spillable_cache = eng.spillable_blocks()
        self._write_events(events)
        return bool(self.active or self.queue or self.parked)

    # -- elastic drain / requeue (ISSUE 7) ------------------------------

    def export_requests(self) -> List[ServingRequest]:
        """Stop admitting, preempt every admitted sequence, and hand back
        ALL unfinished requests as requeue-able descriptors, oldest first.

        The elastic-drain half of the scheduler contract: a SIGTERM'd (or
        scaled-away) replica frees its whole KV pool here and the router
        front-requeues the returned requests on surviving replicas — each
        carries its generated continuation, so the replay elsewhere is
        token-identical under greedy decoding (the same discipline as
        ``_preempt``, applied fleet-wide). After this call the scheduler
        refuses new submits (``draining``) and holds no requests: nothing
        can be lost or served twice."""
        self.draining = True
        # active is admission order (oldest first); preempting frees KV and
        # folds the continuation into each request's prefill target.
        # Parked requests (ISSUE 15) drain the same way — flush drops both
        # their resident blocks and their host-tier entry, and the replay
        # elsewhere re-prefills prompt + generated token-identically.
        exported: List[ServingRequest] = []
        for r in list(self.active) + list(self.parked):
            if r.uid in self.engine._seqs:
                self.engine.flush([r.uid])
            if self.drafter is not None:
                self.drafter.forget(r.uid)
            r.state = QUEUED
            r.prefill_done = 0
            r.parked_state = ""
            r.preemptions += 1
            self.preemptions += 1
            exported.append(r)
        exported.extend(self.queue)
        self.active.clear()
        self.parked.clear()
        self.queue.clear()
        self._spillable_cache = 0
        for r in exported:
            # residency parks are THIS pool's state — a re-placed request
            # re-evaluates against the destination replica's pool
            r.adapter_waiting = False
            r.moe_waiting = False
            self.requests.pop(r.uid, None)
        self._write_events([
            ("serving/drained_requests", len(exported), self.ticks),
            ("serving/queue_depth", 0, self.ticks),
        ])
        if exported:
            logger.info(
                f"serving: replica {self.replica_id} drained — "
                f"{len(exported)} unfinished requests exported for requeue")
        return exported

    @atomic_on_reject(check="validate")
    def inject(self, r: ServingRequest, front: bool = True) -> None:
        """Adopt a request exported from another replica, by default at the
        FRONT of the queue (a drained request is older than anything queued
        here — front placement preserves fleet-wide FIFO fairness). The
        request's generated continuation rides along in its prefill target,
        so serving resumes token-identically."""
        if self.draining:
            raise RuntimeError(
                f"replica {self.replica_id} is draining and admits no new "
                f"requests (route to a surviving replica)")
        if r.uid in self.requests or r.uid in self.engine._seqs:
            raise ValueError(f"uid {r.uid} is already live on replica "
                             f"{self.replica_id}")
        eng = self.engine
        total = len(r.prompt) + r.max_new_tokens
        if total > eng.config.max_seq_len:
            raise ValueError(
                f"replica {self.replica_id}: request {r.uid} needs "
                f"{total} tokens but max_seq_len is "
                f"{eng.config.max_seq_len}; route it to a bigger replica")
        usable = eng.allocator.num_blocks - 1
        need_max = blocks_needed(total, eng.cache.block_size)
        if need_max > usable:
            raise ValueError(
                f"replica {self.replica_id}: request needs up to {need_max} "
                f"KV blocks but the pool has {usable} usable; route it to a "
                f"bigger replica")
        if r.adapter_id is not None and (
                self.apool is None or not self.apool.registered(r.adapter_id)):
            raise ValueError(
                f"replica {self.replica_id}: request {r.uid} needs adapter "
                f"{r.adapter_id!r} which is not registered here; "
                f"publish_adapter to this replica first")
        r.state = QUEUED
        r.prefill_done = 0
        r.adapter_waiting = False
        r.moe_waiting = False
        if r.sampling is not None:
            # the seed rides the request (ISSUE 16): its re-prefill replay
            # resumes the SAME seeded chain at the same absolute positions
            self.sampling_seen = True
        self.requests[r.uid] = r
        if front:
            self.queue.appendleft(r)
        else:
            self.queue.append(r)

    @atomic_on_reject(check="validate")
    def adopt_running(self, r: ServingRequest) -> None:
        """Adopt a request whose KV was MIGRATED into this replica's
        engine (hung-replica failover, ISSUE 12): the sequence is already
        live engine-side (``commit_import``), so it enters the running
        set directly and its next tick is a plain decode token — zero
        re-prefill tokens. Everything is validated before any mutation; a
        refusal leaves both scheduler and engine untouched, and the
        caller falls back to ``inject()`` (drain-replay re-prefill)."""
        if self.draining:
            raise RuntimeError(
                f"replica {self.replica_id} is draining and admits no new "
                f"requests (route to a surviving replica)")
        if r.uid in self.requests:
            raise ValueError(f"uid {r.uid} is already live on replica "
                             f"{self.replica_id}")
        if not r.generated:
            raise ValueError(
                f"uid {r.uid} has no generated tokens — a migrated "
                f"sequence must be mid-decode; inject() fresh requests")
        desc = self.engine._seqs.get(r.uid)
        if desc is None:
            raise ValueError(
                f"uid {r.uid} has no imported KV on replica "
                f"{self.replica_id} — commit_import first, or inject() "
                f"for re-prefill")
        want = len(r.prompt) + len(r.generated) - 1
        if desc.seen_tokens != want:
            raise ValueError(
                f"uid {r.uid}: imported KV covers {desc.seen_tokens} "
                f"tokens but the request's history needs {want} (prompt "
                f"{len(r.prompt)} + generated {len(r.generated)} - 1 "
                f"pending); the migrated pool state is torn")
        total = len(r.prompt) + r.max_new_tokens
        if total > self.engine.config.max_seq_len:
            raise ValueError(
                f"replica {self.replica_id}: request {r.uid} needs {total} "
                f"tokens but max_seq_len is "
                f"{self.engine.config.max_seq_len}")
        if len(self.active) >= self.cfg.max_running:
            raise RuntimeError(
                f"replica {self.replica_id}: running set is at max_running"
                f"={self.cfg.max_running}; requeue uid {r.uid} instead")
        if r.adapter_id is not None and (
                self.apool is None or not self.apool.registered(r.adapter_id)):
            raise ValueError(
                f"replica {self.replica_id}: request {r.uid} needs adapter "
                f"{r.adapter_id!r} which is not registered here; "
                f"publish_adapter to this replica first")
        if r.adapter_id is not None:
            # the migrated descriptor is live but adapter-unbound (slot
            # indices are replica-local); rebind so the next decode tick
            # runs under this pool's slot for the same adapter. May page
            # the adapter in — a refusal (pool fully pinned) lands before
            # any scheduler mutation, so the caller falls back to
            # inject() like any other adoption refusal.
            self.engine.configure_adapter(r.uid, r.adapter_id)
        r.state = RUNNING
        r.prefill_done = len(r.prompt) + len(r.generated)
        r.adapter_waiting = False
        r.moe_waiting = False
        if r.sampling is not None:
            self.sampling_seen = True
            self.engine.configure_sampling(r.uid, r.sampling)
        self.requests[r.uid] = r
        self.active.append(r)

    def knobs(self) -> Dict[str, object]:
        """The effective tunable-knob point this replica serves at
        (ISSUE 14 introspection): the serving families the autotuner
        searches — packing shape, derived chunk/k ladders, speculation —
        plus the engine's storage/kernel modes. Autotuner trial logs
        record this dict verbatim, so a winner's provenance names the
        exact knobs it was measured with, and a fleet post-mortem can
        diff what each replica actually ran."""
        ecfg = self.engine.config
        out = dict(self.cfg.knob_values())
        out.update({
            "decode_kernel": getattr(self.engine, "_decode_kernel",
                                     ecfg.decode_kernel),
            "kv_cache_dtype": ecfg.kv_cache_dtype,
            "prefix_caching": ecfg.prefix_caching,
            "kv_block_size": ecfg.kv_block_size,
            "num_kv_blocks": ecfg.num_kv_blocks,
            "spill_enabled": ecfg.kv_tier.enabled,
            "hot_block_fraction": ecfg.kv_tier.hot_block_fraction,
            "prefetch_depth": ecfg.kv_tier.prefetch_depth,
            "adapter_slots": (ecfg.adapters.slots
                              if ecfg.adapters.enabled else 0),
            "adapter_prefetch_depth": (ecfg.adapters.prefetch_depth
                                       if ecfg.adapters.enabled else 0),
        })
        return out

    def load(self) -> Dict[str, object]:
        """Cheap placement snapshot for the router: queue depth, running
        set, and KV-pool pressure, every tick-independent number the
        placement score needs."""
        eng = self.engine
        usable = max(1, eng.allocator.num_blocks - 1)
        # tier-aware pressure (ISSUE 15): spillable blocks are reclaimable
        # — a replica that could spill its way to room is less pressured
        # than its raw free count says, so the router's placement sees
        # free + spillable over usable. A plain int read: load() runs on
        # router threads, so it must never walk eng._seqs itself (the
        # tick thread refreshes the cache; see __init__)
        spillable = self._spillable_cache if self.tier is not None else 0
        return {
            "replica_id": self.replica_id,
            "queue_depth": len(self.queue),
            "running": len(self.active),
            "parked": len(self.parked),
            "free_blocks": eng.free_blocks,
            "spillable_blocks": spillable,
            "kv_pressure": max(
                0.0, 1.0 - (eng.free_blocks + spillable) / usable),
            "draining": self.draining,
            # multi-tenant LoRA (ISSUE 18): the placement-affinity signal
            # — a request routes toward a replica whose pool already
            # holds its adapter. The pool takes its own lock, so this is
            # safe from router threads like the rest of load().
            "resident_adapters": ([] if self.apool is None
                                  else self.apool.resident_ids()),
        }

    # -- drivers --------------------------------------------------------

    def drain(self) -> None:
        """Tick until every admitted and queued request finishes."""
        while self.tick():
            pass

    def serve(self, requests: Sequence[Union[Sequence[int], Tuple[Sequence[int], int]]],
              max_new_tokens: int = 32,
              arrivals: Optional[Sequence[float]] = None,
              deadline_s: Optional[float] = None,
              sampling: Optional[Union[SamplingParams,
                                       Sequence[Optional[SamplingParams]]]]
              = None,
              adapter_ids: Optional[Sequence[Optional[str]]] = None
              ) -> Dict[int, List[int]]:
        """Serve a batch of requests to completion, continuous-batching
        style. ``requests``: prompts, or ``(prompt, max_new)`` pairs.
        ``arrivals``: optional arrival offsets in seconds (e.g. a Poisson
        trace) — request i is submitted once ``clock() - t0 >=
        arrivals[i]``; None submits everything up front. ``deadline_s``
        applies one per-request deadline to every submission (an expired
        request FAILS with its partial tokens retained). ``sampling``
        (ISSUE 16): one SamplingParams for every request, or a per-request
        sequence (None entries run greedy). ``adapter_ids`` (ISSUE 18):
        per-request adapter names (None entries serve the base model) —
        a mixed trace exercises the multi-tenant pool. Returns ``{uid:
        generated tokens}`` in submission order."""
        items = []
        for req in requests:
            if (isinstance(req, tuple) and len(req) == 2
                    and not isinstance(req[1], (list, np.ndarray))):
                items.append((list(req[0]), int(req[1])))
            else:
                items.append((list(req), int(max_new_tokens)))
        if arrivals is not None and len(arrivals) != len(items):
            raise ValueError("arrivals must align with requests")
        if isinstance(sampling, SamplingParams) or sampling is None:
            samplings: List[Optional[SamplingParams]] = [sampling] * len(items)
        else:
            samplings = list(sampling)
            if len(samplings) != len(items):
                raise ValueError("sampling must align with requests")
        if adapter_ids is None:
            aids: List[Optional[str]] = [None] * len(items)
        else:
            aids = list(adapter_ids)
            if len(aids) != len(items):
                raise ValueError("adapter_ids must align with requests")
        pending = deque(enumerate(items))
        t0 = self.clock()
        uids: List[int] = []
        while pending or self.active or self.queue or self.parked:
            while pending and (arrivals is None
                               or self.clock() - t0 >= arrivals[pending[0][0]]):
                i, (prompt, mn) = pending.popleft()
                uids.append(self.submit(prompt, max_new_tokens=mn,
                                        deadline_s=deadline_s,
                                        sampling=samplings[i],
                                        adapter_id=aids[i]))
            if not self.tick() and pending and arrivals is not None:
                # idle: sleep until the next arrival is due (clock() may be
                # a test fake, so never pass a negative to sleep)
                wait = arrivals[pending[0][0]] - (self.clock() - t0)
                if wait > 0:
                    time.sleep(wait)
        return {uid: self.requests[uid].generated for uid in uids}

    # -- observability --------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Serving-quality summary over finished requests: sustained
        tokens/s (wall span from first submit to last finish), TTFT/TPOT
        p50/p95/p99 (tail latency is what a production SLO binds on, not
        the median), prefix-cache effectiveness, preemption and tick
        counts."""

        def pct(xs, q):
            return float(np.percentile(xs, q)) if len(xs) else None

        done = [r for r in self.requests.values() if r.state == FINISHED]
        ttft = [r.first_token_at - r.submitted_at for r in done
                if r.first_token_at is not None]
        tpot = [t for r in done for t in r.tpot_s]
        total = sum(len(r.generated) for r in done)
        span = (max(r.finished_at for r in done)
                - min(r.submitted_at for r in done)) if done else 0.0
        eng = self.engine
        hit, miss = eng.prefix_hit_tokens, eng.prefix_miss_tokens
        return {
            "replica_id": self.replica_id,
            "queue_depth": len(self.queue),
            "running": len(self.active),
            "draining": self.draining,
            "requests": len(done),
            "generated_tokens": total,
            "sustained_tokens_per_sec": (total / span) if span > 0 else None,
            "ttft_p50_s": pct(ttft, 50),
            "ttft_p95_s": pct(ttft, 95),
            "ttft_p99_s": pct(ttft, 99),
            "tpot_p50_s": pct(tpot, 50),
            "tpot_p95_s": pct(tpot, 95),
            "tpot_p99_s": pct(tpot, 99),
            "ticks": self.ticks,
            "preemptions": self.preemptions,
            # tiered KV (ISSUE 15): None when kv_tier is off; with it on,
            # the host-tier traffic + park/unpark counts — parks that did
            # NOT become preemptions are re-prefill compute saved
            "kv_tier": (None if self.tier is None else {
                **self.tier.stats(),
                "parks": self.parks,
                "unparks": self.unparks,
                "parked": len(self.parked),
            }),
            # request-level robustness (ISSUE 12): terminally-failed
            # requests by cause — deadline expiries counted here, poison
            # quarantines / exhausted retries land via router fail()s
            "failed": sum(1 for r in self.requests.values()
                          if r.state == FAILED),
            "deadline_expired": self.deadline_expired,
            "compiled_programs": len(self.engine.program_shapes),
            "weight_version": eng.weight_version,
            "prefix_cache": {
                "hit_tokens": hit,
                "miss_tokens": miss,
                "hit_rate": (hit / (hit + miss)) if (hit + miss) else None,
                "cow_copies": eng.cow_copies,
                "shared_blocks": eng.allocator.shared_blocks,
            },
            # ISSUE 8: the steps-per-token lever — with speculation on,
            # ticks per emitted token falls below 1 as acceptance rises
            # (the target is < 0.67 at k=4 on repetitive suffixes)
            "speculative": {
                "enabled": self.spec.enabled,
                "k": self.spec.k if self.spec.enabled else 0,
                "drafter": (type(self.drafter).__name__
                            if self.drafter is not None else None),
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "rejected": self.spec_rejected,
                "acceptance_rate": (self.spec_accepted / self.spec_proposed
                                    if self.spec_proposed else None),
                "rollbacks": eng.spec_rollbacks,
                "rolled_back_tokens": eng.spec_rolled_tokens,
                # per-sequence decode ticks per emitted token (the first
                # token of each request comes from prefill, so a k=0 run
                # measures (n-1)/n, and acceptance pushes it toward
                # 1/(k+1)); batching does NOT deflate this the way
                # ticks/total would
                "steps_per_emitted_token": (
                    sum(r.decode_ticks for r in done) / total if total
                    else None),
            },
            # one-dispatch sampling (ISSUE 16): early-stop effectiveness —
            # dead_tokens_saved is decode budget EOS/stop returned to the
            # pool, early_stop_freed_blocks the KV it released early
            "sampling": {
                "seen": self.sampling_seen,
                "early_stops": self.early_stops,
                "dead_tokens_saved": self.dead_tokens_saved,
                "resamples": self.sampling_resamples,
                "early_stop_freed_blocks": eng.early_stop_freed_blocks,
            },
            # multi-tenant LoRA (ISSUE 18): None when the pool is off;
            # with it on, pool traffic + the scheduler's residency parks
            # (FIFO-seat yields, never preemptions) and the per-adapter
            # emitted-token tally per-tenant billing reads
            "adapters": (None if self.apool is None else {
                **self.apool.stats(),
                "parks": self.adapter_parks,
                "unparks": self.adapter_unparks,
                "waiting": sum(1 for r in self.queue if r.adapter_waiting),
                "tokens_by_adapter": dict(self.adapter_tokens),
            }),
            # expert-parallel MoE serving (ISSUE 19): None on dense models;
            # with experts live, the routed-token traffic plus the
            # scheduler's capacity parks (FIFO-seat holds under expert
            # overload — never preemptions) and last tick's pressure
            "moe": (None if not getattr(eng, "_moe_serving", False) else {
                "dispatched": eng.moe_dispatched,
                "dropped": eng.moe_dropped,
                "expert_load_max": eng.moe_expert_load_max,
                "pressure": eng.moe_pressure(),
                "capacity_parks": self.moe_capacity_parks,
                "unparks": self.moe_unparks,
                "waiting": sum(1 for r in self.queue if r.moe_waiting),
            }),
        }
