"""Inference engine v1 — jit-compiled serving over a dense KV cache.

Capability analog of the reference ``InferenceEngine`` (``inference/engine.py:40``):
wrap a model + weights, apply the TP sharding policy (the AutoTP /
kernel-injection analog is the model's partition specs + Pallas attention),
and serve ``forward``/``generate``. Where the reference captures CUDA graphs
(``inference/engine.py:494``) we jit one prefill program per (batch, length)
bucket and one decode program — XLA's equivalent of graph replay.

Design (TPU-first):
  - KV cache is a pair of stacked arrays [L, B, S, KV, Dh] scanned alongside
    the stacked layer weights — O(1) compile in depth.
  - The whole generate loop (prefill -> lax.scan of decode steps with fused
    on-device sampling) is ONE jitted program: no host round-trip per token
    (the reference's decode loop re-enters python per token).
  - Right-padded prompts with per-sequence lengths; positions/RoPE are
    per-sequence gathers.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

from ..utils.logging import log_dist, logger
from .config import InferenceConfig
from . import sampling


class KVCache(NamedTuple):
    k: Any  # [L, B, S, KV, Dh]
    v: Any


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _rope_rows(cos, sin, pos):
    """Gather per-sequence rope rows. pos [B] or [B,T] -> cos/sin [B,T,D/2]."""
    import jax.numpy as jnp

    if pos.ndim == 1:
        pos = pos[:, None]
    return jnp.take(cos, pos, axis=0), jnp.take(sin, pos, axis=0)


def _apply_rope_batched(x, cos, sin, interleaved: bool = False):
    """x [B,T,H,D], cos/sin [B,T,rd/2] (per-sequence positions); partial
    rotary dims pass through, pairing per ``interleaved`` (see
    models/transformer.py apply_rope)."""
    import jax.numpy as jnp

    rd = 2 * cos.shape[-1]
    rot, rest = (x[..., :rd], x[..., rd:]) if rd < x.shape[-1] else (x, None)
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    if interleaved:
        x1, x2 = rot[..., 0::2], rot[..., 1::2]
        out = jnp.stack([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).reshape(rot.shape)
    else:
        x1, x2 = jnp.split(rot, 2, axis=-1)
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out if rest is None else jnp.concatenate([out, rest], axis=-1)


def decode_attention(q, ck, cv, kv_len, alibi_slopes=None):
    """Single-token attention against a cache.

    q [B,1,H,Dh], ck/cv [B,S,KV,Dh], kv_len [B] = #valid cache slots.
    fp32 softmax; GQA via head-group reshape (no materialized repeat).
    ``alibi_slopes`` [H]: ALiBi bias slope_h * j at key slot j (BLOOM).
    Reference: v1 softmax_context kernel (ops/transformer/inference/op_binding/
    softmax_context.py) and v2 blocked_flash decode path.
    """
    import jax.numpy as jnp

    B, S, KV, Dh = ck.shape
    H = q.shape[2]
    G = H // KV
    # Operands stay in cache dtype with fp32 ACCUMULATION — an
    # astype(float32) on ck/cv would materialize a fp32 copy of the whole
    # cache per layer per token (~2x the decode HBM traffic); softmax runs
    # on the fp32 scores either way.
    qf = q.astype(ck.dtype).reshape(B, KV, G, Dh)              # T=1 folded away
    scores = jnp.einsum("bkgd,bskd->bkgs", qf, ck,
                        preferred_element_type=jnp.float32) / np.sqrt(Dh)
    if alibi_slopes is not None:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(KV, G)
        scores = scores + slopes[None, :, :, None] * jnp.arange(S, dtype=jnp.float32)
    mask = (jnp.arange(S)[None, :] < kv_len[:, None])[:, None, None, :]
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    w = jnp.exp(scores - scores.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    out = jnp.einsum("bkgs,bskd->bkgd", w.astype(cv.dtype), cv,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, 1, H, Dh).astype(q.dtype)


def extend_attention(q, ck, cv, start_pos, kv_len, alibi_slopes=None):
    """Chunked-prefill attention: a C-token query chunk against the cache.

    q [B,C,H,Dh]; ck/cv [B,S,KV,Dh] already contain the chunk's own K/V at
    positions start_pos..start_pos+C-1; start_pos/kv_len [B]. Query i may see
    cache slots s with s <= start_pos + i and s < kv_len (causal within the
    chunk, full visibility of the prefix). fp32 softmax.
    Reference: the ragged "atom" attention over mixed prefill+decode
    (inference/v2/kernels/ragged_ops/blocked_flash) — decode is C == 1.
    """
    import jax.numpy as jnp

    B, S, KV, Dh = ck.shape
    C, H = q.shape[1], q.shape[2]
    G = H // KV
    # Same fp32-accumulate / no-cache-cast discipline as decode_attention.
    qf = q.astype(ck.dtype).reshape(B, C, KV, G, Dh)
    scores = jnp.einsum("bckgd,bskd->bckgs", qf, ck,
                        preferred_element_type=jnp.float32) / np.sqrt(Dh)
    if alibi_slopes is not None:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(KV, G)
        scores = scores + slopes[None, None, :, :, None] * jnp.arange(S, dtype=jnp.float32)
    s_idx = jnp.arange(S)[None, None, :]
    lim = jnp.minimum(start_pos[:, None] + jnp.arange(C)[None, :] + 1, kv_len[:, None])
    mask = (s_idx < lim[:, :, None])[:, :, None, None, :]
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    w = jnp.exp(scores - scores.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    out = jnp.einsum("bckgs,bskd->bckgd", w.astype(cv.dtype), cv,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, C, H, Dh).astype(q.dtype)


class InferenceEngine:
    """Serve a model: ``forward(ids)`` and ``generate(ids, prompt_lengths)``.

    ``model`` is our Transformer family (models/transformer.py); ``params``
    its pytree (cast to the serving dtype and TP-sharded on construction).
    """

    # v2 overrides: its paged decode step can fuse ATTENTION (split-K paged
    # kernel + in-pool append) even when qkv/mlp fusion is structurally off
    _fused_attention = False
    # v2 overrides: only the paged engine runs speculative verify rows, so
    # only it gets the verify-width routing gate/warning (a v1 engine built
    # from a speculative-enabled config has no verify lane to route)
    _has_verify_lane = False

    def __init__(self, model, params, config: Optional[InferenceConfig] = None):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.config = config or InferenceConfig()
        self._mcfg = model.config
        if self._mcfg.position == "alibi":
            from ..models.transformer import alibi_slopes

            self._alibi = (alibi_slopes(self._mcfg.n_heads)
                           * self._mcfg.alibi_slope_scale)
        else:
            self._alibi = None
        self._gen_cache: Dict[Tuple, Any] = {}
        self._fwd = jax.jit(model.apply)
        self._rng = jax.random.PRNGKey(self.config.seed)
        self._resolve_decode_kernel()
        self.update_params(params)

    def _resolve_decode_kernel(self) -> None:
        """Pin the decode-path implementation for this engine's lifetime
        (the jitted programs bake it in). "auto" falls back to the XLA
        layer body off-TPU or when the model structure isn't fusable;
        "pallas" raises instead of silently degrading."""
        from ..models.transformer import decode_fusion_eligibility
        from ..ops.dispatch import resolve_decode_kernel
        from ..utils.logging import warning_once

        requested = self.config.decode_kernel
        # speculative verify width (ISSUE 8): k+1-token verify rows are
        # outside the single-token fused decode kernels' contract — the
        # resolver warns once and the eligibility dict records the gate,
        # so the routing is explicit instead of shape-dependent
        spec = self.config.serving.speculative
        spec_k = spec.k if (spec.enabled and self._has_verify_lane) else 0
        self._decode_kernel = resolve_decode_kernel(requested,
                                                    speculative_k=spec_k)
        self._fuse_qkv = self._fuse_mlp = False
        if self._decode_kernel != "pallas":
            return
        elig = decode_fusion_eligibility(self._mcfg, speculative_k=spec_k)
        self._fuse_qkv = elig["qkv"] is None
        self._fuse_mlp = elig["mlp"] is None
        reasons = [r for r in (elig["qkv"], elig["mlp"]) if r]
        if not (self._fuse_qkv or self._fuse_mlp or self._fused_attention):
            if requested == "pallas":
                raise ValueError(
                    "decode_kernel='pallas' but no part of the decode "
                    f"layer is fusable for this model: {'; '.join(reasons)}")
            # sxt: ignore[SXT005] reasons derive from the model config, fixed per process — dedup cardinality 1
            warning_once(f"decode_kernel=auto: model not fusable "
                         f"({'; '.join(reasons)}); using the XLA decode path")
            self._decode_kernel = "xla"
        elif reasons:
            # sxt: ignore[SXT005] reasons derive from the model config, fixed per process — dedup cardinality 1
            warning_once("fused decode: partially fused layer body "
                         f"({'; '.join(reasons)})")

    def _prepare_params(self, params):
        """Cast to the serving dtype, quantize when configured, and place —
        everything ``update_params`` does short of the commit. Split out so
        the RLHF weight-publication path (``rlhf/publish.py``) can STAGE a
        prepared tree per replica and flip every replica's pointer only
        after all of them prepared successfully (two-phase publish: the
        prepare is the phase that can fail, the commit is a pointer swap)."""
        import jax
        import jax.numpy as jnp

        from ..ops.quant_matmul import QuantizedMatrix

        dtype = self.config.jax_dtype()
        params = jax.tree.map(
            lambda p: p.astype(dtype) if (not isinstance(p, QuantizedMatrix)
                                          and hasattr(p, "astype")
                                          and jnp.issubdtype(p.dtype, jnp.floating)) else p,
            params, is_leaf=lambda p: isinstance(p, QuantizedMatrix))
        if self.config.quantize_weights:
            params = self._quantize(params)
        return self._place(params)

    def update_params(self, params) -> None:
        """Swap in new weights (same tree/shapes) without dropping compiled
        programs — the hybrid-engine path (reference hybrid_engine.py swaps
        inference containers in during ``generate()``; here the jitted
        generate/prefill/decode programs are weight-agnostic, so refreshing
        the pytree is the whole swap)."""
        self.params = self._prepare_params(params)

    # -- checkpoint-backed serving (resilience layer) -------------------

    @classmethod
    def from_checkpoint(cls, model, ckpt_dir: str,
                        config: Optional[InferenceConfig] = None,
                        tag: Optional[str] = None) -> "InferenceEngine":
        """Serve straight from a training checkpoint directory, with the
        same torn-latest / corrupted-tag fallback as the trainer (see
        ``load_serving_weights``). Works for every engine class (v2
        inherits)."""
        return cls(model, load_serving_weights(ckpt_dir, model, tag=tag), config)

    def _try_load_serving_weights(self, ckpt_dir: str,
                                  tag: Optional[str] = None):
        """``load_serving_weights`` with the reload-path degrade policy:
        when no tag is loadable — mid-save, torn ``latest``, corrupted
        shards — log and return None so the caller KEEPS SERVING its
        current weights (shared by both reload_weights overloads; the
        exception set and message live in exactly one place)."""
        try:
            return load_serving_weights(ckpt_dir, self.model, tag=tag)
        except (ValueError, OSError) as e:
            logger.warning(f"reload_weights: no loadable checkpoint in "
                           f"{ckpt_dir} ({type(e).__name__}: {e}); continuing "
                           "to serve the current weights")
            return None

    def reload_weights(self, ckpt_dir: str, tag: Optional[str] = None) -> bool:
        """Hot-swap serving weights from the newest complete checkpoint in
        ``ckpt_dir`` (a serving fleet following a live trainer). Degrades
        gracefully (see ``_try_load_serving_weights``): an unloadable
        directory returns False and keeps serving."""
        params = self._try_load_serving_weights(ckpt_dir, tag=tag)
        if params is None:
            return False
        self.update_params(params)
        return True

    # -- sharding (AutoTP analog: inference/engine.py:247 TP group create) --

    def _place(self, params):
        import jax

        from ..parallel.mesh import get_topology, topology_is_initialized

        if not topology_is_initialized():
            return jax.device_put(params)
        from ..ops.quant_matmul import QuantizedMatrix

        topo = get_topology()
        if topo.size("tensor") == 1 or not hasattr(self.model, "partition_specs"):
            return jax.device_put(params)
        specs = self.model.partition_specs(params)

        def place(p, spec):
            if isinstance(p, QuantizedMatrix):
                # TP-sharding the int8 storage needs scale-aware specs;
                # replicate for now (quantized serving is single-chip-first)
                return jax.device_put(p)
            # replicate any leaf a mesh axis doesn't divide (odd vocab or
            # head counts must degrade, not crash serving)
            for dim, ax in enumerate(spec):
                if ax is None:
                    continue
                size = 1
                for a in (ax if isinstance(ax, tuple) else (ax,)):
                    size *= topo.size(a)
                if p.shape[dim] % size:
                    return jax.device_put(p)
            return jax.device_put(p, topo.named_sharding(*spec))

        return jax.tree.map(place, params, specs,
                            is_leaf=lambda p: isinstance(p, QuantizedMatrix))

    def _quantize(self, params):
        """int8 weight-only quantization (reference GroupQuantizer
        ``module_inject/replace_module.py:44`` + the mixed_gemm CUTLASS
        kernels, SURVEY §2.13). Layer matmul weights become int8-STORAGE
        :class:`QuantizedMatrix` leaves — half the HBM bytes; `y @ w`
        dequantizes into the dot (XLA fuses the convert, so weights cross
        HBM quantized — measured faster than the Pallas quant kernel at
        every serving shape, round 5: int8 generate 930 vs 612 tok/s).
        int8/fp8 MoE expert weights also take storage form (the grouped
        GEMM / batched-einsum paths dequantize into the dot); int4 MoE
        and unembed (fp32 head path) keep the rounding-only emulation."""
        import jax

        from ..ops.quant import quantize_dequantize
        from ..ops.quant_matmul import quantize_weight

        from ..utils.logging import warning_once

        gs = self.config.quant_group_size
        # storage weights group along K with one scale row per kernel
        # K-block; 256 is the largest MXU-friendly group (see
        # InferenceConfig.quant_group_size docs) — larger configured values
        # apply to the moe/unembed rounding path only
        storage_gs = min(gs, 256)
        storage_names = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}
        moe_names = {"moe_w_gate", "moe_w_up", "moe_w_down"}
        if self.config.quant_bits in (8, "fp8"):
            # expert-sharded MoE FFN weights join int8/fp8 STORAGE (ISSUE
            # 20 satellite): quantize_weight groups along K under the
            # stacked [L, E] lead dims, and both expert compute paths
            # dequantize into the dot (batched einsum in expert_mlp,
            # grouped_matmul's ragged_dot/gmm dispatch) — so expert
            # weights cross HBM at quantized width during streamed
            # decode, same contract as the dense w_* leaves. int4 keeps
            # the rounding emulation: its nibble-pair unpack is plumbed
            # for the 2D serving matmul only.
            storage_names = storage_names | moe_names
            qdq_names = {"unembed"}
        else:
            qdq_names = moe_names | {"unembed"}
        dtype = self.config.jax_dtype()

        def walk(tree):
            if isinstance(tree, dict):
                out = {}
                for k, v in tree.items():
                    if k in storage_names:
                        try:
                            out[k] = quantize_weight(v, group_size=storage_gs, dtype=dtype,
                                                      bits=self.config.quant_bits)
                        except ValueError as e:
                            # static message: this loop visits every weight,
                            # and a per-weight f-string would defeat the
                            # warning_once dedup (one line per leaf)
                            warning_once(
                                "quantize_weight rejected some weights; "
                                "using quantize-dequantize rounding for "
                                "them (per-weight detail at debug level)")
                            logger.debug(f"quantize_weight({k}): {e}; "
                                         f"qdq rounding instead")
                            out[k] = quantize_dequantize(v, group_size=gs).astype(v.dtype)
                    elif k in qdq_names:
                        out[k] = quantize_dequantize(v, group_size=gs).astype(v.dtype)
                    else:
                        out[k] = walk(v)
                return out
            return tree

        return walk(params)

    # -- cached forward pieces ----------------------------------------

    def _embed_at(self, params, ids, pos):
        """ids [B,T], pos [B] start positions -> x [B,T,D], plus rope tables."""
        import jax.numpy as jnp

        from ..models.transformer import rope_table

        cfg = self._mcfg
        x = jnp.take(params["embed"], ids, axis=0)
        if cfg.embed_ln:   # BLOOM word_embeddings_layernorm
            from ..models.transformer import _norm

            x = _norm(x, params["embed_ln_w"], params["embed_ln_b"], cfg.norm,
                      eps=cfg.norm_eps)
        T = ids.shape[1]
        positions = pos[:, None] + jnp.arange(T)[None, :]       # [B,T]
        if cfg.position == "learned":
            # "clip" keeps an out-of-range position (generation running past
            # max_seq_len) pinned to the last row instead of silently
            # wrapping via the default fill behavior.
            x = x + jnp.take(params["pos_embed"], positions + cfg.pos_offset,
                             axis=0, mode="clip").astype(x.dtype)
            return x, (None, None), positions
        if cfg.position == "alibi":
            return x, (None, None), positions
        cos, sin = rope_table(self.config.max_seq_len, cfg.rotary_dims, cfg.rope_theta)
        return x, (cos, sin), positions

    def _lora_add(self, base, x, lora, target):
        """``base + (x @ A_slot[row]) @ B_slot[row]`` — the per-row paged
        adapter delta (ISSUE 18). ``lora`` is ``(pool_slice, slots)``:
        the layer's [S, din, R]/[S, R, dout] factor stacks and the
        batch's i32 slot indices (slot 0 = zeros, an exact no-op)."""
        pool, slots = lora
        if target not in pool["a"]:
            return base
        from ..ops.lora_gemm import lora_delta

        delta = lora_delta(x, pool["a"][target], pool["b"][target], slots)
        return base + delta.astype(base.dtype)

    def _layer_body(self, lw, h, cos, sin, positions, attn_fn, lora=None):
        """One transformer block shared by every cached path (v1/v2 ×
        prefill/decode) — norm → QKV(+RoPE) → ``attn_fn`` → residual → FFN.
        ``attn_fn(q, k, v) -> (attn [B,T,H,Dh], cache_out)`` supplies the
        attention and the KV-cache write for that path.

        On 1-token steps with ``decode_kernel`` resolved to "pallas", the
        QKV projection(+bias+RoPE) and the residual+MLP collapse into the
        fused kernels (ops/fused_decode.py) so each weight matrix streams
        through VMEM exactly once per step.

        ``lora`` (ISSUE 18) threads the adapter pool's per-layer factor
        stacks + the batch's slot indices; the low-rank delta lands on
        each projection AFTER the base matmul and BEFORE bias/RoPE (the
        fused-QKV collapse is statically skipped — the engine only
        passes ``lora`` when adapters are enabled)."""
        from ..models.transformer import _norm

        cfg = self._mcfg
        B, T = h.shape[:2]
        H, KV, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
        y = _norm(h, lw["ln1_w"], lw.get("ln1_b", 0), cfg.norm, eps=cfg.norm_eps)
        qkv = None if lora is not None else \
            self._maybe_fused_qkv(lw, y, cos, sin, positions)
        if qkv is None:
            q = y @ lw["wq"]
            k = y @ lw["wk"]
            v = y @ lw["wv"]
            if lora is not None:
                q = self._lora_add(q, y, lora, "wq")
                k = self._lora_add(k, y, lora, "wk")
                v = self._lora_add(v, y, lora, "wv")
            q = q.reshape(B, T, H, Dh)
            k = k.reshape(B, T, KV, Dh)
            v = v.reshape(B, T, KV, Dh)
            if cfg.attn_qkv_bias:
                q = q + lw["b_q"].astype(y.dtype).reshape(H, Dh)
                k = k + lw["b_k"].astype(y.dtype).reshape(KV, Dh)
                v = v + lw["b_v"].astype(y.dtype).reshape(KV, Dh)
            if cfg.position == "rope":
                pc, ps = _rope_rows(cos, sin, positions)
                q = _apply_rope_batched(q, pc, ps, interleaved=cfg.rope_interleaved)
                k = _apply_rope_batched(k, pc, ps, interleaved=cfg.rope_interleaved)
        else:
            q, k, v = qkv
        attn, cache_out = attn_fn(q, k, v)
        return self._block_tail(lw, h, y, attn, lora=lora), cache_out

    def _block_tail(self, lw, h, y, attn, lora=None):
        """Output projection + residual(s) + FFN — shared by the XLA and
        fused layer bodies (engine_v2's fused paged step re-enters here
        after its fused attention)."""
        from ..models.transformer import _norm

        cfg = self._mcfg
        B, T = h.shape[:2]
        attn_flat = attn.reshape(B, T, cfg.n_heads * cfg.head_dim)
        attn_out = attn_flat @ lw["wo"]
        if lora is not None:
            attn_out = self._lora_add(attn_out, attn_flat, lora, "wo")
        if cfg.attn_out_bias:
            attn_out = attn_out + lw["b_o"].astype(attn_out.dtype)
        if cfg.parallel_block:
            resid = h + attn_out
            if cfg.parallel_shared_ln:
                out = self._maybe_fused_ffn(lw, resid, y, apply_norm=False)
                return out if out is not None else resid + self._ffn(lw, y)
            out = self._maybe_fused_ffn(lw, resid, h, apply_norm=True)
            if out is not None:
                return out
            y2 = _norm(h, lw["ln2_w"], lw.get("ln2_b", 0), cfg.norm,
                       eps=cfg.norm_eps)
            return resid + self._ffn(lw, y2)
        h = h + attn_out
        out = self._maybe_fused_ffn(lw, h, h, apply_norm=True)
        if out is not None:
            return out
        y2 = _norm(h, lw["ln2_w"], lw.get("ln2_b", 0), cfg.norm, eps=cfg.norm_eps)
        return h + self._ffn(lw, y2)

    def _fused_qkv_args(self, lw, cos, sin, positions):
        """Per-layer preconditions + argument assembly shared by the v1
        and v2 fused-QKV call sites (one definition so weight-form checks
        can never diverge between the engines): None when this layer's
        attention weights can't take the kernel, else
        ``(cos_rows, sin_rows, bias_kwargs)``."""
        cfg = self._mcfg
        from ..ops.quant_matmul import QuantizedMatrix
        from ..utils.logging import warning_once

        if any(isinstance(lw[n], QuantizedMatrix) for n in ("wq", "wk", "wv")):
            warning_once("fused decode: quantized attention weights — QKV "
                         "stays on the dequant-into-dot XLA path")
            return None
        cosr = sinr = None
        if cfg.position == "rope":
            pc, ps = _rope_rows(cos, sin, positions)
            cosr, sinr = pc[:, 0], ps[:, 0]
        bias = {}
        if cfg.attn_qkv_bias:
            bias = {"bq": lw["b_q"], "bk": lw["b_k"], "bv": lw["b_v"]}
        return cosr, sinr, bias

    def _maybe_fused_qkv(self, lw, y, cos, sin, positions):
        """Fused QKV+bias+RoPE for a 1-token step; None -> use the XLA
        path (not enabled, T > 1, or this layer's weights aren't dense)."""
        cfg = self._mcfg
        if not (self._fuse_qkv and self._decode_kernel == "pallas"
                and y.shape[1] == 1):
            return None
        from ..ops import fused_decode as fd
        from ..utils.logging import warning_once

        args = self._fused_qkv_args(lw, cos, sin, positions)
        if args is None:
            return None
        cosr, sinr, bias = args
        try:
            q, k, v = fd.fused_qkv_rope(
                y[:, 0], lw["wq"], lw["wk"], lw["wv"], cos=cosr, sin=sinr,
                n_heads=cfg.n_heads, kv_heads=cfg.kv_heads, **bias)
        except Exception as e:
            # sxt: ignore[SXT005] exception class + model dims: both fixed per process, bounded dedup
            warning_once(f"fused decode: QKV kernel failed with "
                         f"{type(e).__name__} (D={y.shape[-1]}, "
                         f"H={cfg.n_heads}, KV={cfg.kv_heads}); using the "
                         "XLA path")
            return None
        return q[:, None], k[:, None], v[:, None]

    def _maybe_fused_ffn(self, lw, resid, y_src, apply_norm: bool):
        """Fused residual+norm+MLP for a 1-token step; None -> XLA path."""
        cfg = self._mcfg
        if not (self._fuse_mlp and self._decode_kernel == "pallas"
                and resid.shape[1] == 1):
            return None
        from ..ops import fused_decode as fd
        from ..ops.quant_matmul import QuantizedMatrix
        from ..utils.logging import warning_once

        gated = cfg.activation == "swiglu"
        wg = lw["w_gate"] if gated else None
        reason = fd.mlp_weights_fusable(lw["w_up"], lw["w_down"], wg)
        has_bias = cfg.mlp_bias and not gated and "b_up" in lw
        if reason is None and has_bias and isinstance(lw["w_up"],
                                                      QuantizedMatrix):
            reason = "quantized MLP weights with fc biases"
        if reason is not None:
            # sxt: ignore[SXT005] reason derives from the weight structure, fixed per process
            warning_once(f"fused decode: MLP stays on the XLA path "
                         f"({reason})")
            return None
        kw = {}
        if has_bias:
            kw = {"b_up": lw["b_up"], "b_down": lw["b_down"]}
        # with apply_norm=False the norm params are unused; ln1_w rides
        # along as a shape-correct dummy
        ln_w = lw["ln2_w"] if apply_norm else lw["ln1_w"]
        ln_b = lw.get("ln2_b") if apply_norm else None
        try:
            out = fd.fused_mlp(
                resid[:, 0], y_src[:, 0], ln_w, ln_b,
                lw["w_up"], lw["w_down"], wg, norm=cfg.norm,
                eps=cfg.norm_eps, activation=cfg.activation,
                apply_norm=apply_norm, **kw)
        except Exception as e:
            # sxt: ignore[SXT005] exception class name only — a handful of distinct messages at worst
            warning_once(f"fused decode: MLP kernel failed with "
                         f"{type(e).__name__}; using the XLA path")
            return None
        return out[:, None]

    def _prefill(self, params, ids, prompt_len, cache: KVCache):
        """Process right-padded prompts [B,T]; fill cache[:, :, :T]; return
        (cache, last-token hidden [B,1,D])."""
        import jax
        import jax.numpy as jnp

        from ..ops.flash_attention import flash_attention

        cfg = self._mcfg
        B = ids.shape[0]
        x, (cos, sin), positions = self._embed_at(params, ids, jnp.zeros((B,), jnp.int32))

        def layer_fn(h, lw):
            def attn_fn(q, k, v):
                return flash_attention(q, k, v, causal=True, impl=self.config.attention_impl,
                                       alibi_slopes=self._alibi), (k, v)

            return self._layer_body(lw, h, cos, sin, positions, attn_fn)

        x, (ks, vs) = jax.lax.scan(layer_fn, x, params["layers"])
        k_cache = jax.lax.dynamic_update_slice(cache.k, ks.astype(cache.k.dtype), (0, 0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(cache.v, vs.astype(cache.v.dtype), (0, 0, 0, 0, 0))
        x_last = jnp.take_along_axis(x, (prompt_len - 1)[:, None, None].astype(jnp.int32), axis=1)
        return KVCache(k_cache, v_cache), x_last

    def _ffn(self, lw, y):
        """Dense or MoE FFN on normalized input (mirrors models/transformer.py
        layer_apply; MoE = reference moe_inference.py:159 capability)."""
        import jax

        cfg = self._mcfg
        if cfg.n_experts > 0:
            from ..moe.layer import moe_layer

            expert_params = {n[4:]: lw[n] for n in lw
                             if n.startswith("moe_")
                             and n != "moe_gate" and not n.startswith("moe_shared")}
            # scanned=True: _ffn runs inside the lax.scan over stacked
            # layers — "auto" must not pick the megablox ragged path here
            # (the ~4x scanned-gmm cliff, moe/resolve_moe_impl), same as
            # the training stack_apply call site. Serving (engine_v2) may
            # override impl/capacity_factor from serving.moe and arm a
            # per-layer tap collecting routing counts; both are inert on
            # the training-side engines (attributes absent).
            impl = getattr(self, "_moe_impl_override", None) or cfg.moe_impl
            cf = getattr(self, "_moe_cf_override", None)
            res = moe_layer(lw["moe_gate"], expert_params, y, k=cfg.moe_top_k,
                            capacity_factor=cfg.capacity_factor if cf is None else cf,
                            activation=cfg.activation,
                            impl=impl, normalize_weights=cfg.moe_norm_topk,
                            scanned=True)
            tap = getattr(self, "_moe_tap", None)
            if tap is not None:
                # counts [E] i32 (capacity impl: post-drop; ragged: pre-drop
                # with drop_fraction 0); dropped assignments = drop * S*k,
                # exact because drop_fraction = 1 - kept/(S*k)
                counts = res.metadata["expert_counts"]
                drop = res.metadata.get("drop_fraction", 0.0)
                total = 1
                for d in y.shape[:-1]:
                    total *= int(d)
                tap.append((counts, drop * (total * cfg.moe_top_k)))
            out = res.output
            if cfg.moe_shared_expert_ff > 0:
                shared = (jax.nn.silu(y @ lw["moe_shared_w_gate"])
                          * (y @ lw["moe_shared_w_up"])) @ lw["moe_shared_w_down"]
                gate_s = jax.nn.sigmoid(y @ lw["moe_shared_gate"])
                out = out + gate_s.astype(out.dtype) * shared
            return out
        if cfg.activation == "swiglu":
            return (jax.nn.silu(y @ lw["w_gate"]) * (y @ lw["w_up"])) @ lw["w_down"]
        from ..models.transformer import activation_fn

        act = activation_fn(cfg.activation)
        if not cfg.mlp_bias:
            return act(y @ lw["w_up"]) @ lw["w_down"]
        return act(y @ lw["w_up"] + lw["b_up"].astype(y.dtype)) @ lw["w_down"] + lw["b_down"].astype(y.dtype)

    def _decode_step(self, params, cache: KVCache, tok, pos):
        """One token for every sequence. tok [B], pos [B] = cache fill level.
        Returns (cache, logits [B,V])."""
        import jax
        import jax.numpy as jnp

        B = tok.shape[0]
        x, (cos, sin), _ = self._embed_at(params, tok[:, None], pos)
        barange = jnp.arange(B)

        def layer_fn(h, layer_and_cache):
            lw, ck, cv = layer_and_cache

            def attn_fn(q, k, v):
                ck2 = ck.at[barange, pos].set(k[:, 0].astype(ck.dtype))
                cv2 = cv.at[barange, pos].set(v[:, 0].astype(cv.dtype))
                return decode_attention(q, ck2, cv2, kv_len=pos + 1,
                                        alibi_slopes=self._alibi), (ck2, cv2)

            return self._layer_body(lw, h, cos, sin, pos, attn_fn)

        x, (k_cache, v_cache) = jax.lax.scan(layer_fn, x, (params["layers"], cache.k, cache.v))
        logits = self.model.head(params, x)[:, 0]
        return KVCache(k_cache, v_cache), logits

    # -- public API ----------------------------------------------------

    def forward(self, input_ids):
        """Full-sequence logits (reference inference/engine.py:554)."""
        import numpy as np

        return self._fwd(self.params, np.asarray(input_ids, dtype=np.int32))

    __call__ = forward

    def generate(self, input_ids, prompt_lengths=None, max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, eos_token_id: Optional[int] = None,
                 rng=None):
        """Autoregressive generation. input_ids [B, T] right-padded with
        per-seq ``prompt_lengths`` (defaults to full width). Returns int32
        [B, max_new_tokens] (positions after EOS hold pad_token_id).

        Reference guard ``inference/engine.py:583`` delegates to HF
        ``generate``; here the loop itself is on-device.
        """
        import jax
        import jax.numpy as jnp

        cfg = self.config
        ids = np.asarray(input_ids, dtype=np.int32)
        B, T = ids.shape
        if B > cfg.max_batch_size:
            raise ValueError(f"batch {B} exceeds max_batch_size {cfg.max_batch_size} "
                             "(raise it in the inference config)")
        if prompt_lengths is None:
            prompt_lengths = np.full((B,), T, dtype=np.int32)
        prompt_lengths = np.asarray(prompt_lengths, dtype=np.int32)
        max_new = int(max_new_tokens if max_new_tokens is not None else cfg.max_new_tokens)
        temperature = cfg.temperature if temperature is None else float(temperature)
        top_k = cfg.top_k if top_k is None else int(top_k)
        top_p = cfg.top_p if top_p is None else float(top_p)
        eos = cfg.eos_token_id if eos_token_id is None else int(eos_token_id)

        Tpad = min(_bucket(T), cfg.max_seq_len)
        assert T <= Tpad and T + max_new <= cfg.max_seq_len, (
            f"prompt {T} + max_new {max_new} exceeds max_seq_len {cfg.max_seq_len}")
        if Tpad > T:
            ids = np.pad(ids, ((0, 0), (0, Tpad - T)))

        key = (B, Tpad, max_new, temperature == 0.0, top_k, eos)
        fn = self._gen_cache.get(key)
        if fn is None:
            fn = jax.jit(functools.partial(self._generate_impl, max_new=max_new,
                                           greedy=temperature == 0.0, top_k=top_k, eos=eos))
            self._gen_cache[key] = fn
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        out = fn(self.params, ids, prompt_lengths, jnp.float32(temperature), jnp.float32(top_p), rng)
        return np.asarray(out)

    def _generate_impl(self, params, ids, prompt_len, temperature, top_p, rng,
                       *, max_new: int, greedy: bool, top_k: int, eos: int):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        mcfg = self._mcfg
        B, Tpad = ids.shape
        S = cfg.max_seq_len
        dtype = cfg.jax_dtype()
        cache = KVCache(
            jnp.zeros((mcfg.n_layers, B, S, mcfg.kv_heads, mcfg.head_dim), dtype),
            jnp.zeros((mcfg.n_layers, B, S, mcfg.kv_heads, mcfg.head_dim), dtype))
        cache, x_last = self._prefill(params, ids, prompt_len, cache)
        logits0 = self.model.head(params, x_last)[:, 0]

        def pick(logits, key):
            if greedy:
                return sampling.greedy(logits)
            return sampling.sample(logits, key, temperature=temperature, top_k=top_k, top_p=top_p)

        rng, k0 = jax.random.split(rng)
        tok0 = pick(logits0, k0)
        done0 = (tok0 == eos) if eos >= 0 else jnp.zeros((B,), bool)

        def step(carry, key):
            cache, tok, pos, done = carry
            new_cache, logits = self._decode_step(params, cache, tok, pos)
            nxt = pick(logits, key)
            nxt = jnp.where(done, cfg.pad_token_id, nxt)
            newly_done = (nxt == eos) if eos >= 0 else jnp.zeros((B,), bool)
            pos = jnp.minimum(pos + 1, S - 1)
            return (new_cache, nxt, pos, done | newly_done), nxt

        keys = jax.random.split(rng, max_new - 1) if max_new > 1 else jnp.zeros((0, 2), jnp.uint32)
        (_, _, _, _), rest = jax.lax.scan(step, (cache, tok0, prompt_len, done0), keys)
        return jnp.concatenate([tok0[None], rest], axis=0).T  # [B, max_new]


def load_serving_weights(ckpt_dir: str, model, tag: Optional[str] = None):
    """Load the MODEL WEIGHTS item of a training checkpoint for serving
    (reference: ``init_inference(checkpoint=...)`` + the mp-sharded
    checkpoint loaders, ``runtime/state_dict_factory.py`` /
    ``module_inject/load_checkpoint.py``). Works for checkpoints written by
    either checkpoint engine; the optimizer bytes are never read.

    Degrades gracefully like the trainer's ``load_checkpoint``: native
    loads are checksum-verified, and when the ``latest`` pointer is torn or
    the tag it names fails an integrity check, serving falls back to the
    newest *complete* earlier tag (one warning) instead of refusing to
    start. An explicit ``tag`` never falls back."""
    import os

    import jax

    from ..checkpoint.engine import (NativeCheckpointEngine, OrbaxCheckpointEngine,
                                     RECOVERABLE_ERRORS, load_with_fallback)

    target = jax.eval_shape(model.init, jax.random.PRNGKey(0))

    def load_tag(cand):
        path = os.path.join(ckpt_dir, cand, "model")
        errors, recoverable = [], None
        for eng in (OrbaxCheckpointEngine(), NativeCheckpointEngine()):
            try:
                return eng.load(path, target=target)
            except RECOVERABLE_ERRORS as e:
                recoverable = e
                errors.append(f"{type(eng).__name__}: {type(e).__name__}: {e}")
            except Exception as e:
                errors.append(f"{type(eng).__name__}: {type(e).__name__}: {e}")
        if recoverable is not None:
            # integrity-shaped failure: let load_with_fallback try an
            # earlier complete tag
            raise recoverable
        # structural (wrong model shape etc.): retrying older tags would
        # only bury the real error under 'unusable tag' warnings
        raise ValueError(f"could not load {path} with any checkpoint engine "
                         f"({errors})")

    return load_with_fallback(ckpt_dir, tag, load_tag, what="serving checkpoint")


def init_inference(model=None, params=None, config=None, checkpoint: Optional[str] = None,
                   **kwargs) -> InferenceEngine:
    """Build an InferenceEngine (reference ``deepspeed.init_inference``,
    ``deepspeed/__init__.py:299``). ``config`` is a dict in the reference's
    inference-config format or an InferenceConfig. ``model`` may also be a
    HF checkpoint path or transformers model — the engine-factory dispatch
    of the reference (inference/v2/engine_factory.py:32) via models/hf.py.
    ``checkpoint``: a training-checkpoint dir written by
    ``engine.save_checkpoint`` — its weights item becomes the serving
    params (the reference's checkpoint-loading serving path)."""
    if not isinstance(config, InferenceConfig):
        cfg_dict = dict(config or {})
        cfg_dict.update(kwargs)
        config = InferenceConfig.from_dict(cfg_dict)
    if isinstance(model, str) or (model is not None and hasattr(model, "state_dict")):
        from ..models.hf import from_hf

        model, params = from_hf(model)
    if checkpoint is not None:
        if model is None:
            raise ValueError("init_inference(checkpoint=...) needs the model object")
        params = load_serving_weights(checkpoint, model)
    if params is None:
        raise ValueError("init_inference requires params (the model weights pytree)")
    log_dist(f"init_inference: dtype={config.dtype} tp={config.tensor_parallel} "
             f"max_seq_len={config.max_seq_len}", ranks=[0])
    return InferenceEngine(model, params, config)
