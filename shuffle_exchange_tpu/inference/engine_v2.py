"""Inference engine v2 — continuous batching over a paged KV cache.

Capability analog of the reference FastGen stack (``inference/v2/engine_v2.py:30``
InferenceEngineV2, ``ragged/ragged_manager.py:19`` DSStateManager,
``ragged/sequence_descriptor.py:59``): host-side sequence state + block
allocator, device-side paged attention, and the ``put / query / flush``
serving API. Logits come back to the host (the reference samples on host
too); the v1 engine's fused generate covers the on-device loop.

TPU-first: every device program has static shapes — prompts are bucketed to
block multiples, decode batches to power-of-two widths — so a serving
process compiles a handful of programs total and replays them (the XLA
equivalent of the reference's CUDA-graph strategy).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.invariants import atomic_on_reject
from ..utils.logging import logger
from .config import InferenceConfig
from .engine import InferenceEngine, _bucket
from .paged import (BlockedAllocator,
                    PagedKVCache, _chain_key, append_token_kv, blocks_needed,
                    chain_block_keys, kv_parts, paged_decode_attention,
                    quantize_kv)




def _donate_cache():
    """KV-pool donation for the paged programs, disabled when the persistent
    compile cache + CPU backend combination makes donation unsafe (see
    utils/placement.cache_safe_donate_argnums)."""
    from ..utils.placement import cache_safe_donate_argnums

    return cache_safe_donate_argnums((1,))


@dataclasses.dataclass
class SequenceDescriptor:
    """Host state for one live sequence (ragged/sequence_descriptor.py:59).

    Round 11 prefix-cache fields: ``tokens`` is the full written-token
    history (every KV slot this sequence has filled — prompt plus decode
    inputs), ``committed`` counts the full blocks already registered in
    the allocator's content index, and ``last_key`` is the chained hash
    of the last committed block (parent for the next registration)."""

    uid: int
    seen_tokens: int = 0
    blocks: List[int] = dataclasses.field(default_factory=list)
    last_logits: Optional[np.ndarray] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    committed: int = 0
    last_key: bytes = b""
    # a sequence that lived through a force reload_weights() carries KV
    # from MIXED weights: its blocks must never enter the content index
    # (a fresh admission would hash the same tokens and hit stale KV)
    no_commit: bool = False
    # tiered KV (ISSUE 15): descriptor positions whose blocks were
    # spilled host-ward — ``blocks[i]`` holds the -1 sentinel for every
    # i in here, and the sequence cannot be dispatched until
    # ``fetch_spilled`` restores full residency
    spilled: set = dataclasses.field(default_factory=set)
    # per-request sampling (ISSUE 16): a SamplingParams for step_sampled's
    # fused in-dispatch sampler. None means greedy with no EOS — exactly
    # the pre-sampling engine contract, so step() callers never see it.
    sampling: Optional[object] = None
    # multi-tenant LoRA (ISSUE 18): the adapter this sequence decodes
    # under and its pinned AdapterPool slot. Slot 0 is the all-zeros
    # null adapter — no-adapter rows ride the same program and add an
    # exact 0.0, so the slot is ALWAYS a valid gather index.
    adapter_id: Optional[str] = None
    adapter_slot: int = 0


@dataclasses.dataclass
class KVBlockPayload:
    """One sequence's KV blocks in the POOL's own storage layout — the
    disaggregated prefill→decode wire format (ISSUE 7). ``k``/``v`` are
    [L, nb, KV, block, Dh] in the pool's storage dtype (bf16, or int8/fp8
    raw bytes), ``k_scale``/``v_scale`` the matching [L, nb, KV, block]
    f32 scale planes for quantized pools (None for bf16). Because the
    payload is a straight gather of pool storage, a transfer is bit-exact
    for bf16 and byte-exact (payload + scales) for quantized modes —
    nothing is ever re-quantized on the wire."""

    uid: int
    tokens: List[int]
    seen_tokens: int
    last_logits: Optional[np.ndarray]
    k: np.ndarray
    v: np.ndarray
    k_scale: Optional[np.ndarray]
    v_scale: Optional[np.ndarray]
    kv_cache_dtype: str
    block_size: int
    # the serving weight version the exported KV was computed under
    # (ISSUE 12): KV bytes are only valid against the weights that wrote
    # them, so a failover migration from a replica that missed a fleet
    # publish must be refused (commit_import validates) and fall back to
    # re-prefill under the survivor's weights. None (a pre-ISSUE-12
    # payload) skips the check.
    weight_version: Optional[int] = None

    def arrays(self) -> List[np.ndarray]:
        """The device payload planes in wire order (data, then scales)."""
        out = [self.k, self.v]
        if self.k_scale is not None:
            out += [self.k_scale, self.v_scale]
        return out

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.arrays())


@dataclasses.dataclass
class ImportReservation:
    """Decode-side half of the disagg admission handshake: KV blocks
    acquired (``begin_import``) before any payload bytes move, released
    by ``abort_import`` or consumed by ``commit_import``."""

    uid: int
    blocks: List[int]
    n_tokens: int
    done: bool = False


class InferenceEngineV2(InferenceEngine):
    """Paged continuous-batching engine.

    ``put(uids, tokens)`` runs prefill for new uids and single/multi-token
    extension for known ones, returning next-token logits per uid in order.
    """

    _fused_attention = True   # the paged decode step has a fused-attention
    # form (split-K kernel + in-pool append) independent of qkv/mlp fusion
    _has_verify_lane = True   # speculative verify rows exist here (ISSUE 8)

    def __init__(self, model, params, config: Optional[InferenceConfig] = None):
        super().__init__(model, params, config)
        cfg, mcfg = self.config, self._mcfg
        if cfg.max_seq_len % cfg.kv_block_size:
            raise ValueError("max_seq_len must be a multiple of kv_block_size")
        self.cache = PagedKVCache.create(mcfg.n_layers, cfg.num_kv_blocks, cfg.kv_block_size,
                                         mcfg.kv_heads, mcfg.head_dim, cfg.jax_dtype(),
                                         kv_cache_dtype=cfg.kv_cache_dtype)
        self.allocator = BlockedAllocator(cfg.num_kv_blocks)
        # prefix-cache observability (the scheduler's prefix_cache/* group
        # and bench's hit-rate read these; cow_copies also counts fork
        # divergence with prefix_caching off)
        self.prefix_hit_tokens = 0
        self.prefix_miss_tokens = 0
        self.cow_copies = 0
        # speculative-decode observability (ISSUE 8): rewinds of rejected
        # draft KV and the slots they returned (the scheduler's
        # speculative/* counter group reads these alongside its own
        # proposed/accepted tallies)
        self.spec_rollbacks = 0
        self.spec_rolled_tokens = 0
        # one-dispatch sampling observability (ISSUE 16): KV blocks
        # returned to the pool by EOS/stop early termination (the
        # scheduler's sampling/* counter group reads this), and the output
        # avals of every sampled program dispatched — the no-logits-to-host
        # proof (tests assert no [*, vocab]-shaped leaf ever ships).
        self.early_stop_freed_blocks = 0
        self.sampled_output_shapes: Dict[Tuple, Tuple] = {}
        # SamplingParams registered before their uid's first prefill lands
        # (configure_sampling on a not-yet-live uid); step_sampled pops
        # these into the descriptor it creates.
        self._pending_sampling: Dict[int, object] = {}
        # block 0 is scratch: padding table entries scribble here, never read.
        self._scratch = self.allocator.allocate(1)[0]
        self._seqs: Dict[int, SequenceDescriptor] = {}
        self._max_blocks = cfg.max_seq_len // cfg.kv_block_size
        self._prefill_cache: Dict[Tuple[int, int], object] = {}
        self._decode_cache: Dict[int, object] = {}
        self._extend_cache: Dict[int, object] = {}
        self._mixed_cache: Dict[Tuple, object] = {}
        # device programs launched (observability + the <=2-dispatch/step
        # contract for mixed batches; reference counts ragged-batch launches)
        self.dispatch_count = 0
        # distinct compiled-program shapes dispatched — the shape-bin
        # ladder's footprint. Serving tests assert this stays bounded by
        # the ladder while ticks grow unbounded.
        self._program_keys: set = set()
        # table width of the most recent decode dispatch (bench.py uses it
        # to count the KV bytes the kernels actually stream)
        self._last_decode_table_width = self._max_blocks
        # versioned serving weights (ISSUE 11): the RLHF train->serve flip
        # stamps every publication so rollout replay logs can name the
        # exact weights a token was sampled under. ``_staged_weights``
        # holds a prepared-but-uncommitted tree (the two-phase fleet
        # publish), ``_pending_weights`` a committed-but-deferred one
        # (applied at the next tick boundary — see apply_pending_weights).
        self.weight_version = 0
        self._staged_weights: Optional[Tuple[object, Optional[int]]] = None
        self._pending_weights: Optional[Tuple[object, Optional[int]]] = None
        # tiered paged KV (ISSUE 15): host tier for cold spilled blocks —
        # the scheduler parks sequences here under KV pressure instead of
        # preempting them (byte-exact spill/fetch over the AIO substrate)
        self.tier = None
        if cfg.kv_tier.enabled:
            from .kv_tier import HostKVTier

            self.tier = HostKVTier(spill_dir=cfg.kv_tier.spill_dir,
                                   prefetch_depth=cfg.kv_tier.prefetch_depth)
        # multi-tenant LoRA serving (ISSUE 18): paged pool of adapter
        # factor pairs; per-row slot indices gather from it inside every
        # serving program. ``_pending_adapter`` mirrors
        # ``_pending_sampling`` — bindings registered before the uid's
        # first prefill, consumed when admission creates the descriptor.
        self.adapters = None
        if cfg.adapters.enabled:
            from .adapters import AdapterPool

            self.adapters = AdapterPool(
                mcfg, slots=cfg.adapters.slots,
                max_rank=cfg.adapters.max_rank,
                targets=cfg.adapters.targets,
                prefetch_depth=cfg.adapters.prefetch_depth,
                dtype=cfg.jax_dtype())
        self._pending_adapter: Dict[int, str] = {}
        # expert-parallel MoE serving (ISSUE 19): the engine serves MoE
        # models through the same one-dispatch step — top-k routing is
        # per-token DATA inside the layer scan (sorted-by-expert grouped
        # GEMM / capacity dispatch), so expert assignment never keys a
        # program shape and the warmed server's zero-recompile invariant
        # holds. Per-tick routing counts ride out of every dispatch as an
        # extra [L, E] output (the "_pop_moe" seam) and feed the
        # scheduler's expert-capacity admission + the moe/* counters.
        self._moe_serving = self._mcfg.n_experts > 0
        self._moe_tap = None           # armed per layer-scan body (engine._ffn appends)
        self.moe_dispatched = 0        # expert assignments routed (post-drop)
        self.moe_dropped = 0           # assignments dropped at expert capacity
        self.moe_expert_load_max = 0   # peak per-(layer, expert) load seen
        self._moe_last_counts = None   # [E] worst-layer per-expert load, last tick
        self._moe_last_total = 0       # S*k of the last tick (capacity denominator)
        if self._moe_serving:
            mo = cfg.serving.moe
            # "auto" defers to the model config's moe_impl (which itself
            # resolves scanned "auto" -> capacity, the ~4x scanned-gmm
            # cliff); an explicit serving impl wins over the model config
            self._moe_impl_override = (None if mo.moe_impl == "auto"
                                       else mo.moe_impl)
            self._moe_cf_override = mo.capacity_factor
            self._shard_expert_weights()

    # -- scheduling queries (engine_v2.py:158-232) ---------------------

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    @property
    def program_shapes(self) -> frozenset:
        """Distinct compiled device-program shape keys dispatched so far —
        the shape-bin ladder's compile footprint. Serving runs of any
        length stay bounded by the ladder (tests assert it)."""
        return frozenset(self._program_keys)

    def query(self, uid: int) -> Tuple[int, int]:
        """(max further tokens for uid, free blocks) — engine_v2.py:158."""
        desc = self._seqs.get(uid)
        seen = desc.seen_tokens if desc else 0
        have = len(desc.blocks) * self.cache.block_size if desc else 0
        headroom = (have - seen) + self.allocator.free_blocks * self.cache.block_size
        return min(self.config.max_seq_len - seen, headroom), self.allocator.free_blocks

    def can_schedule(self, uids: Sequence[int], lengths: Sequence[int]) -> bool:
        """Admission check (engine_v2.py:184 can_schedule)."""
        return self._admission_detail(uids, lengths)[0]

    def _admission_detail(self, uids: Sequence[int], lengths: Sequence[int],
                          new_tokens: Optional[Dict[int, Sequence[int]]] = None
                          ) -> Tuple[bool, int, str]:
        """(ok, blocks_from_free_pool, why-not): the named-numbers
        admission check behind can_schedule/put()/step() — failures say
        how many KV blocks the batch wants vs how many are free and which
        uid asks for the most (decode_loop's error discipline). With
        ``new_tokens`` (uid -> prompt for NEW uids) and prefix_caching on,
        prefix-cached blocks are netted out: a live shared hit costs zero
        free-pool slots, a parked hit costs its revival slot but no
        prefill, and the message names cached vs new. A known uid whose
        next write lands in a still-shared block budgets one extra block
        for the copy-on-write clone."""
        bs = self.cache.block_size
        need, worst_uid, worst_ask, worst_cached = 0, None, -1, 0
        for uid, n in zip(uids, lengths):
            desc = self._seqs.get(uid)
            seen = desc.seen_tokens if desc else 0
            have = len(desc.blocks) if desc else 0
            if seen + n > self.config.max_seq_len:
                return False, 0, (
                    f"uid {uid} would overrun max_seq_len: {seen} seen + {n} "
                    f"new > {self.config.max_seq_len} (split the request or "
                    f"raise max_seq_len)")
            cached = 0
            if desc is None and new_tokens and uid in new_tokens:
                _, live, parked = self.prefix_peek(new_tokens[uid])
                cached = live + parked
                # only the LIVE hits are free; parked revivals consume a
                # slot from the free pool (they are counted free until
                # acquired)
                ask = max(0, blocks_needed(n, bs) - live)
            else:
                ask = max(0, blocks_needed(seen + n, bs) - have)
                if desc is not None and desc.blocks:
                    first, last = seen // bs, (seen + n - 1) // bs
                    ask += sum(
                        1 for i in range(first, min(last + 1, len(desc.blocks)))
                        if self.allocator.ref_count(desc.blocks[i]) > 1)
            need += ask
            if ask > worst_ask:
                worst_uid, worst_ask, worst_cached = uid, ask, cached
        if need > self.allocator.free_blocks:
            cache_note = (f" after {worst_cached} prefix-cached" if worst_cached
                          else "")
            tier_note = ""
            if self.tier is not None:
                # tier-aware accounting (ISSUE 15): spillable blocks are
                # reclaimable-not-free — a spill pass could fund this ask
                # without losing any sequence's KV, so the refusal names
                # them next to the free count for the scheduler's
                # park-instead-of-preempt decision
                tier_note = (f" + {self.spillable_blocks(exclude=uids)} "
                             f"reclaimable via kv_tier spill")
            stop_note = ""
            if self.early_stop_freed_blocks:
                # EOS/stop accounting (ISSUE 16): early terminations have
                # already been returning blocks — name them so a refusal
                # under sampled load reads against the right baseline
                stop_note = (f"; early stops have returned "
                             f"{self.early_stop_freed_blocks} blocks to the "
                             f"pool so far")
            return False, need, (
                f"needs {need} KV blocks, {self.allocator.free_blocks} free"
                f"{tier_note} "
                f"(largest single ask: uid {worst_uid} wants {worst_ask} new"
                f"{cache_note}); flush finished sequences or raise "
                f"num_kv_blocks{stop_note}")
        if self.adapters is not None:
            # adapter residency is the THIRD admission resource (ISSUE 18,
            # after KV blocks and max_seq_len): a batch whose pending
            # adapters cannot all be pinned is refused atomically, and the
            # refusal names the adapter pool — NOT KV — so the scheduler
            # parks the request instead of spilling KV that would not help
            want = []
            for uid in uids:
                if self._seqs.get(uid) is None:
                    aid = self._pending_adapter.get(uid)
                    if aid is not None:
                        want.append(aid)
            if want:
                aok, awhy = self.adapters.can_acquire_all(want)
                if not aok:
                    return False, need, (
                        f"adapter pool (KV is fine: {need} blocks needed, "
                        f"{self.allocator.free_blocks} free): {awhy}; park "
                        f"until a running sequence releases its slot")
        if self._moe_serving and any(self._seqs.get(u) is None for u in uids):
            # expert capacity is the FOURTH admission resource (ISSUE 19,
            # after KV blocks, max_seq_len, and adapter slots): when the
            # previous tick's routing saturated some expert's buffer,
            # NEW sequences are refused — named as expert-vs-KV pressure
            # so the scheduler parks instead of spilling KV that would
            # not help. Known uids always pass (running sequences keep
            # ticking, which is also what drains the pressure; the
            # ``self._seqs`` guard below keeps a stale reading from
            # blocking an idle engine forever).
            mo = self.config.serving.moe
            pr = self.moe_pressure()
            if (mo.overload_policy == "park" and self._seqs
                    and pr > mo.overload_threshold):
                return False, need, (
                    f"expert capacity (KV is fine: {need} blocks needed, "
                    f"{self.allocator.free_blocks} free): last tick's peak "
                    f"expert ran at {pr:.2f}x capacity (threshold "
                    f"{mo.overload_threshold:g}, policy park); hold new "
                    f"sequences until routing pressure drains")
        return True, need, ""

    # -- device programs ----------------------------------------------

    def _kv_xs(self, cache: PagedKVCache):
        """Per-layer KV operands for the layer scans: bf16 pools scan the
        bare [L, ...] arrays; quantized pools scan ``(data, scale)`` pairs
        so every layer body sees the pair the kernels take."""
        if cache.quantized:
            return (cache.k, cache.k_scale), (cache.v, cache.v_scale)
        return cache.k, cache.v

    @staticmethod
    def _cache_of(kp, vp) -> PagedKVCache:
        """Rebuild the pool from stacked scan outputs (pair-aware)."""
        if isinstance(kp, tuple):
            return PagedKVCache(kp[0], vp[0], kp[1], vp[1])
        return PagedKVCache(kp, vp)

    @staticmethod
    def _apool_xs(apool):
        """Adapter-pool xs for the layer scans: the pool's factor stacks
        are [L, S, ...] so they join the per-layer scan alongside weights
        and KV; each layer body sees its own [S, ...] slice. () when the
        program runs without adapters — pytree structure (not values)
        keys the jit specialization, so adapters-off programs are
        byte-identical to the pre-adapter ones."""
        return () if apool is None else (apool,)

    def _aargs(self, descs, B: int):
        """Trailing adapter operands for a dispatch: () when the pool is
        off, else ``(device_operands, slots[B] i32)`` with padding rows on
        the null slot. Slot VALUES are data — the operand shapes are
        fixed by (pool geometry, B-bin), so new adapters never recompile."""
        if self.adapters is None:
            return ()
        return (self.adapters.device_operands(), self._aslots(descs, B))

    @staticmethod
    def _aslots(descs, B: int):
        s = np.zeros((B,), np.int32)
        for i, d in enumerate(descs):
            s[i] = d.adapter_slot
        return s

    # -- expert-parallel MoE serving (ISSUE 19) ------------------------

    def _shard_expert_weights(self) -> None:
        """Expert-parallel weight placement: the stacked ``moe_*`` expert
        leaves are [L, E, ...], sharded over the mesh "expert" axis so
        each device holds E/ep experts and XLA lowers the dispatch/return
        all-to-all pair from the sharding constraints (the moe/layer.py
        pattern — ``_constrain_expert`` marks the activations inside the
        layer). On jax 0.4.x the facade's live-expert-axis emulation
        applies exactly as training does; both lanes are logged so the
        placement is never silently wrong. No-op off-topology or when the
        expert axis is 1 (single-chip serving: replicated experts)."""
        from ..parallel.mesh import (get_topology, native_shard_map,
                                     topology_is_initialized)
        from ..utils.logging import logger

        if not topology_is_initialized():
            return
        topo = get_topology()
        ep = topo.expert_parallel_world_size
        if ep <= 1:
            return
        import jax

        E = self._mcfg.n_experts
        if E % ep:
            raise ValueError(
                f"n_experts={E} is not divisible by the mesh expert axis "
                f"({ep}) — expert-parallel serving shards whole experts")
        sharding = topo.named_sharding(None, "expert")
        layers = dict(self.params["layers"])
        moved = []
        for name, leaf in layers.items():
            if (name.startswith("moe_") and name != "moe_gate"
                    and not name.startswith("moe_shared")
                    and getattr(leaf, "ndim", 0) >= 2):
                # int8/fp8 QuantizedMatrix expert stacks shard the same
                # way: device_put broadcasts the sharding over the
                # pytree's children, and both q and scales carry E on
                # dim 1 (scale groups run along K), so the expert split
                # never cuts a scale group
                layers[name] = jax.device_put(leaf, sharding)
                moved.append(name)
        if moved:
            params = dict(self.params)
            params["layers"] = layers
            self.params = params
            lane = ("native jax.shard_map lowering" if native_shard_map()
                    else "jax 0.4.x live-expert-axis emulation")
            logger.info(
                f"MoE serving: sharded {moved} over expert axis ({ep}-way, "
                f"{E // ep} experts/device, {lane}); dispatch/return "
                f"all-to-all lowered by XLA from sharding constraints")

    def _moe_arm(self):
        """Arm the per-layer routing-counts tap consumed by the base
        engine's ``_ffn`` (it appends ``(expert_counts [E], dropped)`` per
        MoE FFN call, up to one per lane). Called at the top of every
        layer-scan body — the tracers stay inside the scan trace and are
        folded into the scan's ys by :meth:`_moe_ys`."""
        if not self._moe_serving:
            return None
        tap = []
        self._moe_tap = tap
        return tap

    def _moe_ys(self, tap):
        """Close the tap and fold its entries (one per lane that ran this
        layer) into scan-ys elements ``(counts [E] i32, dropped [] f32)``.
        Returns ``()`` when MoE serving is off, so dense programs keep a
        byte-identical pytree structure."""
        if tap is None:
            return ()
        import jax.numpy as jnp

        self._moe_tap = None
        assert tap, "MoE serving armed a layer tap but no FFN appended " \
            "routing counts — the layer body bypassed engine._ffn"
        counts = sum(c.astype(jnp.int32) for c, _ in tap)
        dropped = sum(jnp.asarray(d, jnp.float32) for _, d in tap)
        return ((counts, dropped),)

    def _pop_moe(self, out):
        """Strip the trailing MoE routing-counts element off a dispatch
        result and fold it into the per-tick accounting; identity when
        MoE serving is off."""
        if not self._moe_serving:
            return out
        self._note_moe_counts(out[-1])
        return out[:-1]

    def _note_moe_counts(self, moe) -> None:
        """Host-side accounting from one dispatch's routing counts.
        ``moe = (counts [..., L, E], dropped [..., L])`` (a leading steps
        axis when the fused decode loop produced them). Updates the moe/*
        counters and the previous-tick load snapshot ``moe_pressure``
        reads — counts are post-drop for the capacity impl and pre-drop
        (dropped == 0) for the dropless ragged impl, so
        ``counts.sum() + dropped`` recovers S*k either way."""
        E = self._mcfg.n_experts
        counts = np.asarray(moe[0]).reshape(-1, E)
        dropped = np.asarray(moe[1], np.float64).reshape(-1)
        self.moe_dispatched += int(counts.sum())
        self.moe_dropped += int(round(float(dropped.sum())))
        self.moe_expert_load_max = max(self.moe_expert_load_max,
                                       int(counts.max()))
        self._moe_last_counts = counts.max(axis=0)
        self._moe_last_total = int(round(float(counts[-1].sum()
                                               + dropped[-1])))

    def moe_pressure(self) -> float:
        """Peak per-expert load from the previous tick's routing as a
        fraction of that tick's expert capacity — the scheduler's
        expert-overload signal (1/capacity_factor under balanced routing;
        > 1.0 means some expert saturated its buffer). 0.0 before the
        first MoE tick or on dense models."""
        if not self._moe_serving or self._moe_last_counts is None:
            return 0.0
        from ..moe.gating import compute_capacity

        k = max(1, self._mcfg.moe_top_k)
        S = max(1, self._moe_last_total // k)
        cap = compute_capacity(S, self._mcfg.n_experts, k,
                               self._moe_cf_override)
        return float(self._moe_last_counts.max()) / float(max(1, cap))

    def _paged_prefill_fn(self, p: int, tpad: int):
        fn = self._prefill_cache.get((p, tpad))
        if fn is not None:
            return fn
        import jax

        fn = jax.jit(self._paged_prefill_impl, donate_argnums=_donate_cache())
        self._prefill_cache[(p, tpad)] = fn
        return fn

    def _paged_prefill_impl(self, params, cache: PagedKVCache, ids, plen, btables,
                            apool=None, aslots=None):
        """BATCHED prefill — all pending new sequences in ONE program
        (reference packs them into one ragged batch, engine_v2.py:107).

        ids [P,tpad]; plen [P]; btables [P, tpad//block] (scratch-padded)
        -> cache, logits [P,V]. Sequences are independent rows; per-row
        block tables scatter each row's K/V into its own blocks (scratch
        rows collide harmlessly on the never-read scratch block)."""
        import jax
        import jax.numpy as jnp

        from ..ops.flash_attention import flash_attention

        P, tpad = ids.shape
        bs = self.cache.block_size
        nblk_pad = tpad // bs
        x, (cos, sin), positions = self._embed_at(params, ids, jnp.zeros((P,), jnp.int32))

        def layer_fn(h, layer_and_cache):
            lw, ck, cv = layer_and_cache[:3]
            lora = None if apool is None else (layer_and_cache[3], aslots)

            def attn_fn(q, k, v):
                KV, Dh = k.shape[2], k.shape[3]

                def blocks(x):   # [P,tpad,KV,Dh] -> pool blocks [P*nblk,KV,bs,Dh]
                    return (x.reshape(P, nblk_pad, bs, KV, Dh)
                            .transpose(0, 1, 3, 2, 4)
                            .reshape(P * nblk_pad, KV, bs, Dh))

                def sblocks(s):  # [P,tpad,KV] scale rows -> [P*nblk,KV,bs]
                    return (s.reshape(P, nblk_pad, bs, KV)
                            .transpose(0, 1, 3, 2)
                            .reshape(P * nblk_pad, KV, bs))

                flat = btables.reshape(-1)
                kq, ksc = kv_parts(ck)
                vq, vsc = kv_parts(cv)
                kw, vw = k, v
                if ksc is not None:
                    # quantize on write; attention below still uses the
                    # full-precision chunk (storage is what's compressed)
                    kw, sk = quantize_kv(k, kq.dtype)
                    vw, sv = quantize_kv(v, vq.dtype)
                    ksc = ksc.at[flat].set(sblocks(sk))
                    vsc = vsc.at[flat].set(sblocks(sv))
                kq2 = kq.at[flat].set(blocks(kw).astype(kq.dtype))
                vq2 = vq.at[flat].set(blocks(vw).astype(vq.dtype))
                ck2 = kq2 if ksc is None else (kq2, ksc)
                cv2 = vq2 if vsc is None else (vq2, vsc)
                return flash_attention(q, k, v, causal=True,
                                       impl=self.config.attention_impl,
                                       alibi_slopes=self._alibi), (ck2, cv2)

            tap = self._moe_arm()
            h2, (ck2, cv2) = self._layer_body(lw, h, cos, sin, positions,
                                              attn_fn, lora=lora)
            return h2, (ck2, cv2) + self._moe_ys(tap)

        x, ys = jax.lax.scan(layer_fn, x,
                             (params["layers"],) + self._kv_xs(cache)
                             + self._apool_xs(apool))
        kp, vp = ys[0], ys[1]
        x_last = jnp.take_along_axis(x, (plen - 1)[:, None, None].astype(jnp.int32), axis=1)
        logits = self.model.head(params, x_last)[:, 0]
        return (self._cache_of(kp, vp), logits) + tuple(ys[2:])

    def _extend_fn(self, c: int):
        fn = self._extend_cache.get(c)
        if fn is not None:
            return fn
        import jax

        fn = jax.jit(self._extend_impl, donate_argnums=_donate_cache())
        self._extend_cache[c] = fn
        return fn

    def _extend_layer(self, lw, h, ck, cv, cos, sin, positions, start, nnew,
                      btables, lora=None):
        """One chunked-prefill layer: scatter the chunk's K/V into the pool
        and attend through the block table. Shared by the pure extend
        program and the mixed Dynamic-SplitFuse step (step()). Returns
        ``(h2, (ck2, cv2))``."""
        import jax.numpy as jnp

        B, C = h.shape[:2]
        bs = self.cache.block_size

        def attn_fn(q, k, v):
            # scatter the chunk's K/V: token i of row b -> block
            # btables[b, (start+i)//bs], offset (start+i)%bs. Tokens past
            # nnew land on the scratch block.
            pos = positions                                   # [B,C]
            valid = jnp.arange(C)[None, :] < nnew[:, None]
            blk = jnp.take_along_axis(jnp.maximum(btables, 0),
                                      jnp.minimum(pos // bs, btables.shape[1] - 1),
                                      axis=1)                 # [B,C]
            blk = jnp.where(valid, blk, self._scratch)
            off = pos % bs
            kq, ksc = kv_parts(ck)
            vq, vsc = kv_parts(cv)
            kw, vw = k, v
            if ksc is not None:
                # quantize on write: one scale per (token, kv head) row
                kw, sk = quantize_kv(k, kq.dtype)             # [B,C,KV]
                vw, sv = quantize_kv(v, vq.dtype)
                ksc = ksc.at[blk.reshape(-1), :, off.reshape(-1)].set(
                    sk.reshape(B * C, sk.shape[2]))
                vsc = vsc.at[blk.reshape(-1), :, off.reshape(-1)].set(
                    sv.reshape(B * C, sv.shape[2]))
            # [nblk,KV,bs,Dh] pool: advanced (blk, off) around the KV
            # slice yields [B*C, KV, Dh] rows, matching the new K/V
            kq2 = kq.at[blk.reshape(-1), :, off.reshape(-1)].set(
                kw.reshape(B * C, *kw.shape[2:]).astype(kq.dtype))
            vq2 = vq.at[blk.reshape(-1), :, off.reshape(-1)].set(
                vw.reshape(B * C, *vw.shape[2:]).astype(vq.dtype))
            ck2 = kq2 if ksc is None else (kq2, ksc)
            cv2 = vq2 if vsc is None else (vq2, vsc)
            # paged extend: q chunk attends the pool through the
            # block table — no [B, S_max, KV, Dh] gather (r2 weak #7);
            # ALiBi slopes ride the kernel (round 5)
            from ..ops.paged_attention import paged_extend_attention

            out = paged_extend_attention(q, ck2, cv2, btables, start,
                                         nnew, alibi_slopes=self._alibi)
            return out, (ck2, cv2)

        return self._layer_body(lw, h, cos, sin, positions, attn_fn,
                                lora=lora)

    def _extend_impl(self, params, cache: PagedKVCache, ids, start, nnew, btables,
                     apool=None, aslots=None):
        """Chunked-prefill extension — a C-token chunk per sequence in ONE
        program (one program per CHUNK, not per token; VERDICT r1 weak #4).

        ids [B,C] (zero-padded past nnew); start [B] = first new position;
        nnew [B] <= C; btables [B, W] (W = binned block-table width) ->
        cache, logits [B,V] at each sequence's last new token."""
        import jax
        import jax.numpy as jnp

        x, (cos, sin), positions = self._embed_at(params, ids, start)

        def layer_fn(h, layer_and_cache):
            lw, ck, cv = layer_and_cache[:3]
            lora = None if apool is None else (layer_and_cache[3], aslots)
            tap = self._moe_arm()
            h2, (ck2, cv2) = self._extend_layer(lw, h, ck, cv, cos, sin,
                                                positions, start, nnew,
                                                btables, lora=lora)
            return h2, (ck2, cv2) + self._moe_ys(tap)

        x, ys = jax.lax.scan(layer_fn, x,
                             (params["layers"],) + self._kv_xs(cache)
                             + self._apool_xs(apool))
        kp, vp = ys[0], ys[1]
        x_last = jnp.take_along_axis(x, (nnew - 1)[:, None, None].astype(jnp.int32), axis=1)
        logits = self.model.head(params, x_last)[:, 0]
        return (self._cache_of(kp, vp), logits) + tuple(ys[2:])

    def _paged_decode_fn(self, b: int):
        fn = self._decode_cache.get(b)
        if fn is not None:
            return fn
        import jax

        fn = jax.jit(self._paged_decode_impl, donate_argnums=_donate_cache())
        self._decode_cache[b] = fn
        return fn

    def _paged_decode_impl(self, params, cache: PagedKVCache, tok, pos, btables,
                           apool=None, aslots=None):
        """tok [B], pos [B] (next slot), btables [B, max_blocks].

        Cache structure note (round 5, all three measured on-chip): this
        xs/ys layer scan rewrites the KV pool into stacked outputs every
        token (~22% of decode device time in the trace), yet it is the
        FASTEST of the structures tried — an unrolled layer loop with
        per-layer carry buffers measured 6-15% slower, and carrying the
        stacked pool through the scan with the pooled Pallas kernel
        (``paged_decode_attention(..., layer=i)``) measured 2x slower
        (XLA double-buffers a carry that is both a custom-call input and
        scatter-updated in the same iteration). Details in ROUND5_NOTES.

        Round 6: with ``decode_kernel`` resolved to "pallas" each layer
        runs the FUSED path (``_fused_paged_layer``): one kernel for
        QKV+RoPE+pool-append (``input_output_aliases`` on the layer's pool
        slice — the scatter that used to be an XLA whole-slice update is an
        in-kernel DMA of just the new rows), one split-K flash-decode
        kernel over the block table, and one residual+MLP kernel — the
        next candidate for closing the remaining per-token gap, to be
        traced on silicon against this scan structure."""
        import jax

        x, (cos, sin), _ = self._embed_at(params, tok[:, None], pos)

        def layer_fn(h, layer_and_cache):
            lw, ck, cv = layer_and_cache[:3]
            lora = None if apool is None else (layer_and_cache[3], aslots)
            tap = self._moe_arm()
            h2, (ck2, cv2) = self._decode_layer(lw, h, ck, cv, cos, sin,
                                                pos, btables, lora=lora)
            return h2, (ck2, cv2) + self._moe_ys(tap)

        x, ys = jax.lax.scan(layer_fn, x,
                             (params["layers"],) + self._kv_xs(cache)
                             + self._apool_xs(apool))
        kp, vp = ys[0], ys[1]
        logits = self.model.head(params, x)[:, 0]
        return (self._cache_of(kp, vp), logits) + tuple(ys[2:])

    def _decode_layer(self, lw, h, ck, cv, cos, sin, pos, btables, lora=None):
        """One decode layer (one token per sequence): fused Pallas path
        when eligible, else append + paged attention. Shared by the pure
        decode step, the fused decode_loop, and the mixed step(). Returns
        ``(h2, (ck2, cv2))``.

        With ``lora`` set the fully-fused layer is skipped — its fused
        QKV kernel bypasses ``_layer_body``'s projection seam where the
        per-row adapter deltas apply — but the attention-only split-K
        fusion below still runs (attention reads the pool, adapters only
        touch the projections)."""
        if self._decode_kernel == "pallas" and lora is None:
            fused = self._fused_paged_layer(lw, h, ck, cv, cos, sin,
                                            pos, btables)
            if fused is not None:
                return fused

        def attn_fn(q, k, v):
            ck2, cv2 = append_token_kv(ck, cv, k[:, 0], v[:, 0], btables, pos)
            if self._decode_kernel == "pallas":
                # attention-only fusion: even when QKV fusion is off
                # for this layer (quantized weights, interleaved rope)
                # the split-K kernel still replaces the per-kv-head
                # streaming one
                try:
                    from ..ops import fused_decode as fd

                    return fd.fused_paged_decode_attention(
                        q, ck2, cv2, btables, kv_len=pos + 1,
                        alibi_slopes=self._alibi), (ck2, cv2)
                except Exception as e:
                    from ..utils.logging import warning_once

                    # sxt: ignore[SXT005] exception class name only — bounded dedup cardinality
                    warning_once(
                        "fused decode: split-K attention kernel failed "
                        f"with {type(e).__name__}; using the streaming "
                        "paged kernel")
            # round 5: slopes ride the paged kernel (no cache gather
            # for BLOOM serving); the wrapper's CPU fallback gathers
            return paged_decode_attention(q, ck2, cv2, btables,
                                          kv_len=pos + 1,
                                          alibi_slopes=self._alibi), (ck2, cv2)

        return self._layer_body(lw, h, cos, sin, pos, attn_fn, lora=lora)

    def _fused_paged_layer(self, lw, h, ck, cv, cos, sin, pos, btables):
        """One fully-fused decode layer: fused QKV+RoPE+append writes the
        new token's K/V into the pool slice in place, the split-K paged
        kernel attends through the block table, and the shared
        ``_block_tail`` finishes (fusing the MLP when eligible). Returns
        ``(h_new, (ck2, cv2))`` or None to take the XLA path (quantized
        attention weights, or a kernel that fails to build)."""
        import jax.numpy as jnp

        from ..models.transformer import _norm
        from ..ops import fused_decode as fd
        from ..utils.logging import warning_once

        cfg = self._mcfg
        if not self._fuse_qkv:
            return None
        args = self._fused_qkv_args(lw, cos, sin, pos)
        if args is None:
            return None
        cosr, sinr, bias = args
        y = _norm(h, lw["ln1_w"], lw.get("ln1_b", 0), cfg.norm,
                  eps=cfg.norm_eps)
        bs = self.cache.block_size
        quantized = isinstance(ck, tuple)
        try:
            if quantized:
                # int8/fp8 pool: the in-kernel pool DMA would write raw
                # projections without the scale plane, so the append goes
                # through the XLA quantize-on-write scatter (one token's
                # rows — negligible next to the streamed KV read, which
                # stays fused and dequantizes in-register below)
                q, k, v = fd.fused_qkv_rope(
                    y[:, 0], lw["wq"], lw["wk"], lw["wv"], cos=cosr,
                    sin=sinr, n_heads=cfg.n_heads, kv_heads=cfg.kv_heads,
                    **bias)
                ck2, cv2 = append_token_kv(ck, cv, k, v, btables, pos)
            else:
                blk = jnp.take_along_axis(jnp.maximum(btables, 0),
                                          (pos // bs)[:, None], axis=1)[:, 0]
                off = pos % bs
                q, k, v, ck2, cv2 = fd.fused_qkv_rope(
                    y[:, 0], lw["wq"], lw["wk"], lw["wv"], cos=cosr, sin=sinr,
                    n_heads=cfg.n_heads, kv_heads=cfg.kv_heads,
                    pool_k=ck, pool_v=cv, blk=blk, off=off, **bias)
            attn = fd.fused_paged_decode_attention(
                q[:, None], ck2, cv2, btables, pos + 1,
                alibi_slopes=self._alibi)
        except Exception as e:
            # sxt: ignore[SXT005] exception class + pool/model dims are fixed per process — bounded dedup
            warning_once(f"fused decode: paged layer kernels failed with "
                         f"{type(e).__name__} (D={y.shape[-1]}, "
                         f"pool={tuple(kv_parts(ck)[0].shape)}); using the "
                         "XLA path")
            return None
        return self._block_tail(lw, h, y, attn), (ck2, cv2)

    # -- host-side scheduling ------------------------------------------

    def _clone_block(self, src: int, dst: int) -> None:
        """Device copy of one pool block (all layers, data + scale planes)
        — the copy half of copy-on-write. One cached jitted program with
        the pool donated (same discipline as every other cache-updating
        program here): XLA updates the pool in place and moves O(block)
        bytes, where an eager ``at[].set`` would materialize a full pool
        copy per clone — a transient 2x-pool allocation that could OOM a
        pool sized near HBM capacity. src/dst ride as i32 operands so
        every clone hits the same executable."""
        fn = getattr(self, "_clone_prog", None)
        if fn is None:
            import jax

            from ..utils.placement import cache_safe_donate_argnums

            def impl(cache, src_, dst_):
                def cp(x):
                    return x.at[:, dst_].set(x[:, src_])

                return PagedKVCache(*[cp(x) if not isinstance(x, tuple)
                                      else x for x in cache])

            fn = jax.jit(impl,
                         donate_argnums=cache_safe_donate_argnums((0,)))
            self._clone_prog = fn
        self.cache = fn(self.cache, np.int32(src), np.int32(dst))

    def _ensure_blocks(self, desc: SequenceDescriptor, total_tokens: int) -> None:
        """Grow ``desc`` to cover ``total_tokens``, copy-on-write first:
        the coming write spans [seen, total) — any EXISTING block in that
        span still shared with another sequence (a fork's partial tail, or
        a mid-block divergence from a shared prefix) gets a private clone
        before the dispatch writes into it. Committed full blocks are
        never in the write span (committed <= seen // block), so the
        content registry stays consistent without rollback."""
        bs = self.cache.block_size
        first = desc.seen_tokens // bs
        last = (max(total_tokens, 1) - 1) // bs
        for i in range(first, min(last + 1, len(desc.blocks))):
            b = desc.blocks[i]
            if self.allocator.ref_count(b) > 1:
                assert i >= desc.committed, (desc.uid, i, desc.committed)
                [nb] = self.allocator.allocate(1)
                self._clone_block(b, nb)
                self.allocator.free([b])
                desc.blocks[i] = nb
                self.cow_copies += 1
        need = blocks_needed(total_tokens, bs) - len(desc.blocks)
        if need > 0:
            desc.blocks.extend(self.allocator.allocate(need))

    # -- tiered KV: spill / fetch (ISSUE 15) ----------------------------

    def _pool_planes(self):
        """The pool's storage planes in wire order (data, then scales) —
        the same per-plane layout KVBlockPayload ships."""
        c = self.cache
        return ([c.k, c.v, c.k_scale, c.v_scale] if c.quantized
                else [c.k, c.v])

    def _require_resident(self, uids: Sequence[int], what: str) -> None:
        """Dispatch paths need FULL residency: a sequence with spilled
        blocks must be fetched back before its KV can be read or written
        (the block table addresses pool slots the spill freed)."""
        if self.tier is None:
            return
        for uid in uids:
            desc = self._seqs.get(uid)
            if desc is not None and desc.spilled:
                raise RuntimeError(
                    f"cannot {what}: uid {uid} has {len(desc.spilled)} KV "
                    f"blocks spilled to the host tier — fetch_spilled"
                    f"({uid}) first (the scheduler un-parks before "
                    f"dispatching)")

    def is_resident(self, uid: int) -> bool:
        desc = self._seqs.get(uid)
        return desc is not None and not desc.spilled

    def _keep_hot(self, desc: SequenceDescriptor) -> int:
        """Blocks of ``desc`` kept resident on a spill: the TAIL of the
        decode window (most recently written, first re-read), sized by
        ``kv_tier.hot_block_fraction``."""
        import math

        frac = self.config.kv_tier.hot_block_fraction
        return int(math.ceil(frac * len(desc.blocks)))

    def spillable_blocks(self, exclude: Sequence[int] = ()) -> int:
        """Reclaimable-not-free blocks (ISSUE 15 accounting): exclusively
        held (refcount 1), resident, cold (outside the hot tail) blocks
        of live sequences not in ``exclude`` — what a spill pass could
        return to the free pool without losing any token's KV. Shared
        prefix blocks are NOT spillable (another sequence may be
        dispatched against them this tick)."""
        if self.tier is None:
            return 0
        skip = set(exclude)
        total = 0
        for uid, desc in self._seqs.items():
            if uid in skip:
                continue
            limit = max(0, len(desc.blocks) - self._keep_hot(desc))
            total += sum(
                1 for i in range(limit)
                if i not in desc.spilled
                and self.allocator.ref_count(desc.blocks[i]) == 1)
        return total

    @atomic_on_reject(check="validate")
    def spill_sequence(self, uid: int,
                       keep_hot: Optional[int] = None) -> int:
        """Spill ``uid``'s cold exclusive blocks host-ward, freeing their
        pool slots; returns the number of blocks reclaimed. The host copy
        is the pool's OWN storage bytes (data + quantized scale planes,
        byte-exact — the KVBlockPayload discipline), so a later
        ``fetch_spilled`` restores the sequence with no re-prefill and no
        re-quantization. Shared (refcount > 1) blocks stay resident;
        ``keep_hot`` tail blocks (default from
        ``kv_tier.hot_block_fraction``) stay resident as the hot set.

        Crash discipline (the ``kv_spill`` fault site): the host gather
        happens BEFORE any engine mutation, and the tier store commits
        before the allocator free — a spill killed at the fault site
        leaves pool, allocator, and tier byte-identically unchanged."""
        from ..testing import faults

        if self.tier is None:
            raise RuntimeError("kv_tier is not enabled on this engine "
                               "(set inference config kv_tier.enabled)")
        desc = self._seqs.get(uid)
        if desc is None:
            raise ValueError(f"unknown uid {uid}")
        if keep_hot is None:
            keep_hot = self._keep_hot(desc)
        limit = max(0, len(desc.blocks) - keep_hot)
        cand = [i for i in range(limit)
                if i not in desc.spilled
                and self.allocator.ref_count(desc.blocks[i]) == 1]
        if not cand:
            return 0
        # gather width binned to a power of two (scratch-padded rows,
        # sliced off host-side): the device gather is a compiled program
        # per shape, and unbinned widths would compile a fresh executable
        # for every distinct spill size — a mid-trace compile that poisons
        # goodput exactly like the unbinned block tables of round 9
        n = len(cand)
        W = _bucket(n, minimum=1)
        idx = np.asarray([desc.blocks[i] for i in cand]
                         + [self._scratch] * (W - n), np.int32)
        planes = [np.asarray(p[:, idx])[:, :n]
                  for p in self._pool_planes()]
        if faults.ACTIVE:
            faults.maybe_crash("kv_spill", 0)
        self.tier.store(uid, cand, planes)
        self.allocator.free([desc.blocks[i] for i in cand])
        for i in cand:
            desc.blocks[i] = -1
            desc.spilled.add(i)
        return len(cand)

    @atomic_on_reject(check="validate")
    def fetch_spilled(self, uid: int) -> int:
        """Restore ``uid``'s spilled blocks into FRESH pool slots (one
        jitted scatter — the disagg import program); returns the block
        count fetched. Atomic-on-reject: the free-pool check and the tier
        read happen before any allocation, and a failure after the
        allocation (the ``kv_fetch`` fault site) frees the fresh blocks
        again — engine and tier end exactly as before the call."""
        from ..testing import faults

        desc = self._seqs.get(uid)
        if desc is None:
            raise ValueError(f"unknown uid {uid}")
        if not desc.spilled:
            return 0
        idxs = sorted(desc.spilled)
        n = len(idxs)
        if n > self.allocator.free_blocks:
            raise RuntimeError(
                f"cannot fetch uid {uid}'s {n} spilled KV blocks: only "
                f"{self.allocator.free_blocks} free "
                f"({self.spillable_blocks(exclude=[uid])} reclaimable via "
                f"further spill); park another sequence or raise "
                f"num_kv_blocks")
        tidx, planes = self.tier.load(uid)
        assert tidx == idxs, (uid, tidx, idxs)
        new = self.allocator.allocate(n)
        try:
            if faults.ACTIVE:
                faults.maybe_crash("kv_fetch", 0)
            # scatter width binned like the spill gather: pad the index
            # row with the scratch block (duplicate scratch writes land
            # in the garbage slot) and the planes with zero rows, so the
            # import program compiles once per power-of-two width instead
            # of once per distinct spilled-block count
            W = _bucket(n, minimum=1)
            idx_pad = np.asarray(list(new) + [self._scratch] * (W - n),
                                 np.int32)
            planes_pad = [
                p if W == n else np.concatenate(
                    [p, np.zeros(p.shape[:1] + (W - n,) + p.shape[2:],
                                 p.dtype)], axis=1)
                for p in planes]
            fn = self._import_fn(W, self.cache.quantized)
            self.cache = fn(self.cache, idx_pad, *planes_pad)
        except BaseException:
            self.allocator.free(new)
            raise
        for j, i in enumerate(idxs):
            desc.blocks[i] = new[j]
        desc.spilled.clear()
        self.tier.drop(uid)
        return n

    # -- speculative rollback (ISSUE 8) ---------------------------------

    def rewind(self, uid: int, n_tokens: int) -> None:
        """Roll ``uid``'s written-token history back to its first
        ``n_tokens`` slots — the rejected-draft half of speculative
        decoding. Surplus blocks return to the allocator; the stale KV
        bytes (data AND quantized scale planes) past the boundary are
        never read again (every read path masks by ``seen_tokens``) and
        the next write at those slots overwrites both planes.

        Composition with the prefix-cache commit chain: rewinding INTO a
        committed content-registered block invalidates its bytes-under-key
        binding. An exclusively-held committed block is unregistered; a
        REF-SHARED committed block is never touched — other sequences
        (and future admissions) read it — so the rewind takes the
        copy-on-write fallback: clone it privately first, or raise a
        targeted error naming the block when the pool can't fund the
        clone. Validation and the clone reservation happen BEFORE any
        mutation, so a refused rewind leaves allocator + descriptor
        untouched (the PR 6 free() atomicity discipline)."""
        desc = self._seqs.get(uid)
        if desc is None:
            raise ValueError(f"unknown uid {uid}")
        self._require_resident([uid], "rewind()")
        self._rewind(desc, int(n_tokens))

    def _rewind(self, desc: SequenceDescriptor, n_tokens: int) -> None:
        bs = self.cache.block_size
        if not 1 <= n_tokens <= desc.seen_tokens:
            raise ValueError(
                f"rewind of uid {desc.uid} to {n_tokens} tokens: must be "
                f"in [1, seen_tokens={desc.seen_tokens}]")
        if n_tokens == desc.seen_tokens:
            return
        new_nb = blocks_needed(n_tokens, bs)
        nc = n_tokens // bs            # full blocks that stay fully valid
        # ---- plan (validate + decide the COW before any mutation) ----
        tail_cow = tail_unregister = None
        if nc < desc.committed and n_tokens % bs:
            # the partial tail lands INSIDE a committed block: its tail
            # slots will be rewritten by the sequence's continuation
            b = desc.blocks[nc]
            if self.allocator.ref_count(b) > 1:
                if self.allocator.free_blocks < 1:
                    raise RuntimeError(
                        f"cannot rewind uid {desc.uid} to {n_tokens} "
                        f"tokens: block {b} is a committed prefix block "
                        f"shared by {self.allocator.ref_count(b)} "
                        "sequences and the pool has no free block for the "
                        "copy-on-write clone; flush finished sequences or "
                        "raise num_kv_blocks")
                tail_cow = b
            else:
                tail_unregister = b
        # ---- mutate ----
        if tail_cow is not None:
            [nb] = self.allocator.allocate(1)
            self._clone_block(tail_cow, nb)
            self.allocator.free([tail_cow])
            desc.blocks[nc] = nb
            self.cow_copies += 1
        elif tail_unregister is not None:
            self.allocator.unregister(tail_unregister)
        if new_nb < len(desc.blocks):
            # committed blocks PAST the boundary are freed intact: their
            # registered content still matches its key (the key hashes
            # exactly the tokens written there), so a ref-0 registered
            # block parks reusable in the allocator's cached-free LRU —
            # a re-proposed draft chain can hit it again for free
            self.allocator.free(desc.blocks[new_nb:])
            del desc.blocks[new_nb:]
        self.spec_rolled_tokens += desc.seen_tokens - n_tokens
        self.spec_rollbacks += 1
        desc.seen_tokens = n_tokens
        del desc.tokens[n_tokens:]
        if desc.committed > nc:
            desc.committed = nc
            keys = chain_block_keys(desc.tokens[:nc * bs], bs)
            desc.last_key = keys[-1] if keys else b""

    # -- prefix cache (content-addressed block reuse) -------------------

    def prefix_peek(self, tokens: Sequence[int]) -> Tuple[int, int, int]:
        """(hit_tokens, live_blocks, parked_blocks): the longest committed
        prefix of ``tokens`` currently reusable from the block store. Live
        blocks cost an admission ZERO free-pool slots (another sequence
        holds them resident); parked ones consume a free slot on revival
        but no prefill compute either way. Capped one token short of the
        full prompt so an admission always prefills at least the last
        token (the logits position)."""
        if not self.config.prefix_caching:
            return 0, 0, 0
        bs = self.cache.block_size
        max_full = (len(tokens) - 1) // bs
        if max_full <= 0:
            return 0, 0, 0
        keys = chain_block_keys(list(tokens)[:max_full * bs], bs)
        live, parked = self.allocator.peek(keys)
        return (live + parked) * bs, live, parked

    def acquire_prefix(self, uid: int, tokens: Sequence[int]) -> int:
        """Admit ``uid`` with the longest committed prefix of ``tokens``
        acquired from the block store (live hits gain a reference, parked
        hits revive): the descriptor starts at ``seen_tokens == hit`` and
        the caller prefills only the suffix. Returns the hit token count
        (0 admits a cold descriptor). The sequence's own continuation
        commits new full blocks back to the store as it grows."""
        if uid in self._seqs:
            raise ValueError(f"uid {uid} is already live")
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError(f"new uid {uid} with no tokens")
        desc = SequenceDescriptor(uid=uid)
        aid = self._pending_adapter.get(uid)
        if aid is not None:
            if self.adapters is None:
                raise RuntimeError(
                    f"uid {uid} names adapter {aid!r} but adapters are "
                    f"disabled (set adapters.enabled in the inference "
                    f"config)")
            # pin BEFORE any KV mutation: AdapterPoolDry here leaves the
            # engine untouched (put()'s atomic-on-reject contract); the
            # pending binding is only consumed on success
            desc.adapter_slot = self.adapters.acquire(aid)
            desc.adapter_id = aid
            self._pending_adapter.pop(uid, None)
        if self.config.prefix_caching:
            bs = self.cache.block_size
            max_full = (len(tokens) - 1) // bs
            keys = chain_block_keys(tokens[:max_full * bs], bs)
            blocks = self.allocator.acquire(keys)
            hit = len(blocks) * bs
            desc.blocks = blocks
            desc.seen_tokens = hit
            desc.tokens = tokens[:hit]
            desc.committed = len(blocks)
            desc.last_key = keys[len(blocks) - 1] if blocks else b""
            self.prefix_hit_tokens += hit
            self.prefix_miss_tokens += len(tokens) - hit
        self._seqs[uid] = desc
        return desc.seen_tokens

    def _commit(self, desc: SequenceDescriptor) -> None:
        """Register every newly-FULL block of ``desc`` under its chained
        content key (first writer wins; a lost race keeps the block
        private). Committed blocks are immutable from here on — the write
        paths never touch positions below ``seen_tokens`` and COW guards
        forks — so a later admission can share them by hash alone."""
        if not self.config.prefix_caching or desc.no_commit:
            return
        bs = self.cache.block_size
        nfull = min(desc.seen_tokens, len(desc.tokens)) // bs
        while desc.committed < nfull:
            i = desc.committed
            key = _chain_key(desc.last_key, desc.tokens[i * bs:(i + 1) * bs])
            self.allocator.register(key, desc.blocks[i])
            desc.last_key = key
            desc.committed += 1

    def fork(self, parent_uid: int, new_uid: int) -> None:
        """Clone a live sequence's host state sharing ALL its KV blocks
        (parallel sampling / beam candidates / speculative branches) —
        including the partial tail block, which stays shared until either
        side writes into it and triggers the copy-on-write clone in
        ``_ensure_blocks``."""
        parent = self._seqs.get(parent_uid)
        if parent is None:
            raise ValueError(f"unknown parent uid {parent_uid}")
        if new_uid in self._seqs:
            raise ValueError(f"uid {new_uid} is already live")
        self._require_resident([parent_uid], "fork()")
        if parent.adapter_id is not None:
            # the clone decodes under the parent's adapter: bump the slot
            # refcount (a resident-hit acquire) so eviction respects both
            self.adapters.acquire(parent.adapter_id)
        self.allocator.retain(parent.blocks)
        self._seqs[new_uid] = SequenceDescriptor(
            uid=new_uid, seen_tokens=parent.seen_tokens,
            blocks=list(parent.blocks),
            last_logits=None if parent.last_logits is None
            else np.array(parent.last_logits),
            tokens=list(parent.tokens), committed=parent.committed,
            last_key=parent.last_key, no_commit=parent.no_commit,
            sampling=parent.sampling, adapter_id=parent.adapter_id,
            adapter_slot=parent.adapter_slot)

    def _table(self, desc: SequenceDescriptor,
               width: Optional[int] = None) -> np.ndarray:
        """Block-table row for one sequence, ``width`` entries (default
        max_seq_len//block). Serving paths bin the width to the smallest
        power of two covering the batch's allocated blocks: the decode
        kernels stream EVERY table entry's block through VMEM, padding
        included, so table width is directly per-step HBM read traffic
        (the r5 engine_decode_sweep "hbm_util falls with batch" artifact —
        see BASELINE.md)."""
        width = self._max_blocks if width is None else width
        assert len(desc.blocks) <= width, (desc.uid, len(desc.blocks), width)
        t = np.full((width,), self._scratch, dtype=np.int32)
        t[:len(desc.blocks)] = desc.blocks
        return t

    def _binned_width(self, nblocks: int) -> int:
        """Power-of-two block-table width covering ``nblocks``, capped at
        the max_seq_len table."""
        return min(_bucket(max(1, int(nblocks)), minimum=1), self._max_blocks)

    def _pack_decode(self, descs: List[SequenceDescriptor],
                     toks: Sequence[int]):
        """(B, W, tok, pos, tables) for a batched one-token decode step.
        Blocks must already be ensured for seen+1."""
        W = self._binned_width(max(len(d.blocks) for d in descs))
        B = _bucket(len(descs), minimum=1)
        tok = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        tables = np.full((B, W), self._scratch, np.int32)
        for i, (d, t) in enumerate(zip(descs, toks)):
            tok[i], pos[i] = t, d.seen_tokens
            tables[i] = self._table(d, W)
        self._last_decode_table_width = W
        return B, W, tok, pos, tables

    def _pack_chunks(self, batch: List[Tuple[SequenceDescriptor, List[int]]],
                     pad_chunk: Optional[int] = None):
        """(B, C, W, ids, start, nnew, tables) for a chunked-prefill batch.
        ``pad_chunk`` pins the padded chunk length (the serving ladder);
        default is the power-of-two bucket of the longest chunk. Blocks
        must already be ensured for seen+len(chunk)."""
        cmax = max(len(c) for _, c in batch)
        C = pad_chunk if pad_chunk is not None else _bucket(cmax, minimum=1)
        assert C >= cmax, (C, cmax)
        W = self._binned_width(max(len(d.blocks) for d, _ in batch))
        B = _bucket(len(batch), minimum=1)
        ids = np.zeros((B, C), np.int32)
        start = np.zeros((B,), np.int32)
        nnew = np.ones((B,), np.int32)
        tables = np.full((B, W), self._scratch, np.int32)
        for i, (d, chunk) in enumerate(batch):
            ids[i, :len(chunk)] = chunk
            start[i] = d.seen_tokens
            nnew[i] = len(chunk)
            tables[i] = self._table(d, W)
        return B, C, W, ids, start, nnew, tables

    def _pack_prefill(self, prefills: List[Tuple[SequenceDescriptor, List[int]]]):
        """(P, tpad, ids, plen, btables) for the batched flash-prefill
        program — shared by put() and bench.py's one-dispatch compiled-
        prefill measurement (the decode_loop discipline applied to
        prefill). Allocates each descriptor's blocks."""
        bs = self.cache.block_size
        tmax = max(len(toks) for _, toks in prefills)
        tpad = max(bs, _bucket(tmax, minimum=bs))
        tpad = min(-(-tpad // bs) * bs, self.config.max_seq_len)
        nblk_pad = tpad // bs
        P = _bucket(len(prefills), minimum=1)
        ids = np.zeros((P, tpad), np.int32)
        plen = np.ones((P,), np.int32)
        btables = np.full((P, nblk_pad), self._scratch, np.int32)
        for i, (desc, toks) in enumerate(prefills):
            T = len(toks)
            self._ensure_blocks(desc, T)
            ids[i, :T] = toks
            plen[i] = T
            btables[i, :len(desc.blocks)] = desc.blocks[:nblk_pad]
        return P, tpad, ids, plen, btables

    @atomic_on_reject
    def put(self, uids: Sequence[int], tokens: Sequence[Sequence[int]]) -> np.ndarray:
        """Serve one engine step (engine_v2.py:107). New uids are prefilled;
        known uids extended by their new tokens. Returns fp32 logits
        [len(uids), vocab] for each sequence's latest position, in order."""
        import jax.numpy as jnp

        if len(uids) != len(tokens):
            raise ValueError("uids and tokens must align")
        if len(set(uids)) != len(uids):
            raise ValueError("duplicate uid in one put() batch: a sequence can "
                             "advance at most one decode position per engine step")
        for uid, toks in zip(uids, tokens):
            if uid not in self._seqs and not len(toks):
                raise ValueError(f"new uid {uid} with no tokens")
        self._require_resident(uids, "put()")
        new_tokens = {u: list(map(int, t)) for u, t in zip(uids, tokens)
                      if u not in self._seqs}
        # Admission check BEFORE any KV mutation (prefix acquisition
        # included): a rejected put() must leave the engine untouched so
        # the caller can retry it verbatim.
        ok, _, why = self._admission_detail(uids, [len(t) for t in tokens],
                                            new_tokens=new_tokens)
        if not ok:
            raise RuntimeError(f"cannot schedule put() batch: {why}")
        n_ext = sum(1 for uid, toks in zip(uids, tokens)
                    if uid in self._seqs and len(toks))
        n_ext += sum(1 for toks in new_tokens.values()
                     if self.prefix_peek(toks)[0] > 0)
        if n_ext > self.config.max_batch_size:
            raise ValueError(f"decode batch {n_ext} exceeds max_batch_size "
                             f"{self.config.max_batch_size} (raise it in the inference config)")
        bs = self.cache.block_size
        prefills: List[Tuple[SequenceDescriptor, List[int]]] = []
        extends: List[Tuple[SequenceDescriptor, List[int]]] = []
        for uid, toks in zip(uids, tokens):
            if uid in self._seqs and uid not in new_tokens:
                toks = list(map(int, toks))
                if toks:
                    extends.append((self._seqs[uid], toks))
        for uid, toks in new_tokens.items():
            # a prefix hit admits the descriptor at the cached boundary and
            # prefills ONLY the suffix through the extend/decode programs
            # (acquire_prefix is a no-op admission when prefix_caching is
            # off); cold prompts take the batched flash-prefill program
            hit = self.acquire_prefix(uid, toks)
            desc = self._seqs[uid]
            if hit:
                extends.append((desc, toks[hit:]))
            else:
                prefills.append((desc, toks))

        # ---- ALL pending prefills: one bucketed batched program ---------
        if prefills:
            P, tpad, ids, plen, btables = self._pack_prefill(prefills)
            fn = self._paged_prefill_fn(P, tpad)
            self.cache, logits = self._pop_moe(
                fn(self.params, self.cache, ids, plen, btables,
                   *self._aargs([d for d, _ in prefills], P)))
            self.dispatch_count += 1
            self._program_keys.add(("prefill", P, tpad))
            logits = np.asarray(logits)
            for i, (desc, toks) in enumerate(prefills):
                desc.seen_tokens = len(toks)
                desc.tokens = list(toks)
                desc.last_logits = logits[i]
                self._commit(desc)

        # ---- single-token extensions: one batched decode program --------
        singles = [(d, toks[0]) for d, toks in extends if len(toks) == 1]
        multis = [(d, toks) for d, toks in extends if len(toks) > 1]
        if singles:
            for d, _ in singles:
                self._ensure_blocks(d, d.seen_tokens + 1)
            B, W, tok, pos, tables = self._pack_decode(
                [d for d, _ in singles], [t for _, t in singles])
            fn = self._paged_decode_fn(B)
            self.cache, logits = self._pop_moe(
                fn(self.params, self.cache, tok, pos, tables,
                   *self._aargs([d for d, _ in singles], B)))
            self.dispatch_count += 1
            self._program_keys.add(("decode", B, W))
            logits = np.asarray(logits)
            for i, (d, t) in enumerate(singles):
                d.seen_tokens += 1
                d.tokens.append(int(t))
                d.last_logits = logits[i]
                self._commit(d)

        # ---- multi-token extensions: chunked prefill, one program/chunk --
        # (reference runs these as ragged atoms in the same batch; we batch
        # chunks across sequences and size them to the KV block, so an
        # N-token extension costs ceil(N/block) dispatches, NOT N —
        # VERDICT r1 weak #4)
        while any(toks for _, toks in multis):
            batch = []
            for d, toks in multis:
                if toks:
                    chunk, remaining = toks[:bs], toks[bs:]
                    toks[:] = remaining
                    batch.append((d, chunk))
            for d, chunk in batch:
                self._ensure_blocks(d, d.seen_tokens + len(chunk))
            B, C, W, ids, start, nnew, tables = self._pack_chunks(batch)
            fn = self._extend_fn((B, C))
            self.cache, logits = self._pop_moe(
                fn(self.params, self.cache, ids, start, nnew, tables,
                   *self._aargs([d for d, _ in batch], B)))
            self.dispatch_count += 1
            self._program_keys.add(("extend", B, C, W))
            logits = np.asarray(logits)
            for i, (d, chunk) in enumerate(batch):
                d.seen_tokens += len(chunk)
                d.tokens.extend(chunk)
                d.last_logits = logits[i]
                self._commit(d)

        return np.stack([self._seqs[uid].last_logits for uid in uids])

    # -- continuous-batching mixed step (Dynamic SplitFuse) ------------

    def _mixed_fn(self, key):
        fn = self._mixed_cache.get(key)
        if fn is not None:
            return fn
        import jax

        fn = jax.jit(self._mixed_step_impl, donate_argnums=_donate_cache())
        self._mixed_cache[key] = fn
        return fn

    def _mixed_step_impl(self, params, cache: PagedKVCache, dtok, dpos,
                         dtables, pids, pstart, pnnew, ptables,
                         apool=None, daslots=None, paslots=None):
        """The Dynamic-SplitFuse mixed step: ONE program advances every
        running sequence by one decode token ([Bd] rows) AND absorbs a
        prefill chunk for every prefilling sequence ([Bp, C] rows) — the
        reference FastGen scheduler's uniform mixed batch (SURVEY §2.10;
        Orca iteration-level scheduling / Sarathi chunked prefill), built
        from the existing paged decode + extend layer bodies over ONE
        layer scan so the KV pool is rewritten once per step, not twice.

        Decode and prefill rows are disjoint sequences (a uid plays one
        role per tick), so within a layer the decode append and the chunk
        scatter write disjoint blocks; both attentions read through their
        own block tables. Returns (cache, decode_logits [Bd,V],
        prefill_logits [Bp,V] at each chunk's last token)."""
        import jax
        import jax.numpy as jnp

        xd, (cos, sin), _ = self._embed_at(params, dtok[:, None], dpos)
        xp, _, ppos = self._embed_at(params, pids, pstart)

        def layer_fn(carry, layer_and_cache):
            hd, hp = carry
            lw, ck, cv = layer_and_cache[:3]
            ap = None if apool is None else layer_and_cache[3]
            tap = self._moe_arm()
            hd2, (ck2, cv2) = self._decode_layer(
                lw, hd, ck, cv, cos, sin, dpos, dtables,
                lora=None if ap is None else (ap, daslots))
            hp2, (ck3, cv3) = self._extend_layer(
                lw, hp, ck2, cv2, cos, sin, ppos, pstart, pnnew, ptables,
                lora=None if ap is None else (ap, paslots))
            return (hd2, hp2), (ck3, cv3) + self._moe_ys(tap)

        (xd, xp), ys = jax.lax.scan(layer_fn, (xd, xp),
                                    (params["layers"],) + self._kv_xs(cache)
                                    + self._apool_xs(apool))
        kp, vp = ys[0], ys[1]
        dlogits = self.model.head(params, xd)[:, 0]
        x_last = jnp.take_along_axis(xp, (pnnew - 1)[:, None, None].astype(jnp.int32),
                                     axis=1)
        plogits = self.model.head(params, x_last)[:, 0]
        return (self._cache_of(kp, vp), dlogits, plogits) + tuple(ys[2:])

    # -- speculative mixed step (ISSUE 8) ------------------------------

    def _spec_fn(self, key):
        fn = self._mixed_cache.get(key)
        if fn is not None:
            return fn
        import jax

        fn = jax.jit(self._spec_step_impl, donate_argnums=_donate_cache())
        self._mixed_cache[key] = fn
        return fn

    def _spec_step_impl(self, params, cache: PagedKVCache, dops, pops, sops,
                        apool=None):
        """The speculative mixed step: ONE program advances plain decode
        rows by one token, absorbs prefill chunks, AND verifies draft
        rows — each draft row is ``[pending_token, d1..dk]`` running
        through the SAME ``_extend_layer`` body as prefill chunks (the
        verifier is the chunked-prefill path; its intra-chunk causal mask
        is exactly the draft-verification mask). Lanes are pytree-absent
        (empty tuple) when unused, so every lane combination is its own
        compiled program on the shape-bin ladder.

        Verification is greedy and ON-DEVICE: per draft row the head runs
        at EVERY chunk position (this is the verify cost — k+1 head
        projections instead of 1), ``ver[j] = argmax`` after position j,
        and the accepted length is the longest prefix where
        ``ver[j] == ids[j+1]`` (draft j+1 matches the verifier). Returns
        per-row ``(ver [Bs,Cs], accepted [Bs], last_logits [Bs,V])`` —
        ``last_logits`` is the row's logits at its accepted position, so
        the host emits ``drafts[:a] + [ver[a]]`` (the correction when
        a < k, the bonus token when a == k) without shipping [Bs,Cs,V]
        logits off device."""
        import jax
        import jax.numpy as jnp

        dops, pops, sops = tuple(dops), tuple(pops), tuple(sops)
        # adapter slots ride INSIDE the lane tuples (one trailing [B] i32
        # per present lane) so lane presence still keys the program via
        # pytree structure alone
        dslots = pslots = sslots = None
        xd = xp = xs = None
        cos = sin = None
        if dops:
            if apool is not None:
                dtok, dpos, dtables, dslots = dops
            else:
                dtok, dpos, dtables = dops
            xd, (cos, sin), _ = self._embed_at(params, dtok[:, None], dpos)
        if pops:
            if apool is not None:
                pids, pstart, pnnew, ptables, pslots = pops
            else:
                pids, pstart, pnnew, ptables = pops
            xp, (cos, sin), ppos = self._embed_at(params, pids, pstart)
        if sops:
            if apool is not None:
                sids, sstart, snnew, stables, sslots = sops
            else:
                sids, sstart, snnew, stables = sops
            xs, (cos, sin), spos = self._embed_at(params, sids, sstart)

        def layer_fn(carry, layer_and_cache):
            hd, hp, hs = carry
            lw, ck, cv = layer_and_cache[:3]
            ap = None if apool is None else layer_and_cache[3]
            tap = self._moe_arm()
            if hd is not None:
                hd, (ck, cv) = self._decode_layer(
                    lw, hd, ck, cv, cos, sin, dpos, dtables,
                    lora=None if ap is None else (ap, dslots))
            if hp is not None:
                hp, (ck, cv) = self._extend_layer(
                    lw, hp, ck, cv, cos, sin, ppos, pstart, pnnew, ptables,
                    lora=None if ap is None else (ap, pslots))
            if hs is not None:
                # the verify lane IS the extend path (ISSUE 8 satellite:
                # k+1-wide rows are outside the single-token fused decode
                # kernels — decode_fusion_eligibility's "verify" gate);
                # with adapters, the verify rows apply their own slots so
                # drafts are verified under the SAME weights they decode
                hs, (ck, cv) = self._extend_layer(
                    lw, hs, ck, cv, cos, sin, spos, sstart, snnew, stables,
                    lora=None if ap is None else (ap, sslots))
            return (hd, hp, hs), (ck, cv) + self._moe_ys(tap)

        (xd, xp, xs), ys = jax.lax.scan(
            layer_fn, (xd, xp, xs), (params["layers"],) + self._kv_xs(cache)
            + self._apool_xs(apool))
        kp, vp = ys[0], ys[1]
        dlogits = self.model.head(params, xd)[:, 0] if dops else None
        plogits = None
        if pops:
            x_last = jnp.take_along_axis(
                xp, (pnnew - 1)[:, None, None].astype(jnp.int32), axis=1)
            plogits = self.model.head(params, x_last)[:, 0]
        sres = None
        if sops:
            slog = self.model.head(params, xs)          # [Bs, Cs, V]
            ver = jnp.argmax(slog, axis=-1).astype(jnp.int32)
            Bs, Cs = sids.shape
            nxt = jnp.concatenate(
                [sids[:, 1:], jnp.zeros((Bs, 1), sids.dtype)], axis=1)
            j = jnp.arange(Cs)[None, :]
            m = jnp.where(j < (snnew - 1)[:, None], ver == nxt, False)
            accepted = jnp.sum(jnp.cumprod(m.astype(jnp.int32), axis=1),
                               axis=1)                   # [Bs] in [0, k]
            slast = jnp.take_along_axis(
                slog, accepted[:, None, None], axis=1)[:, 0]
            sres = (ver, accepted, slast)
        return (self._cache_of(kp, vp), dlogits, plogits, sres) + tuple(ys[2:])

    @atomic_on_reject
    def _admit_step(self, decode_uids, decode_tokens, prefills, speculative,
                    what: str):
        """The shared validation + all-or-nothing admission front half of
        step()/step_sampled(): normalize the lane lists, validate lane
        membership, admit the WHOLE tick before any state mutation, then
        create descriptors for new prefill uids and ensure every
        participant's KV blocks. Returns (prefills, speculative, ddescs,
        pdescs, sdescs)."""
        prefills = [(u, list(map(int, c))) for u, c in prefills]
        speculative = [(u, list(map(int, c))) for u, c in speculative]
        if len(decode_uids) != len(decode_tokens):
            raise ValueError("decode_uids and decode_tokens must align")
        all_uids = (list(decode_uids) + [u for u, _ in prefills]
                    + [u for u, _ in speculative])
        if len(set(all_uids)) != len(all_uids):
            raise ValueError(
                f"duplicate uid in one {what}: a sequence is either "
                "decoding, prefilling or verifying drafts in a tick, never "
                "two at once")
        for uid in decode_uids:
            if uid not in self._seqs:
                raise ValueError(f"decode uid {uid} unknown — prefill it "
                                 "first (step(prefills=...) or put())")
        for uid, chunk in prefills:
            if not chunk:
                raise ValueError(f"prefill uid {uid} with an empty chunk")
        for uid, chunk in speculative:
            if uid not in self._seqs:
                raise ValueError(f"speculative uid {uid} unknown — a draft "
                                 "row verifies an already-running sequence")
            if len(chunk) < 2:
                raise ValueError(
                    f"speculative uid {uid} with {len(chunk)} tokens — a "
                    "verify row is [pending_token, drafts...]; a row with "
                    "no drafts belongs in decode_uids")
        self._require_resident(all_uids, what)
        ok, _, why = self._admission_detail(
            all_uids, [1] * len(decode_uids) + [len(c) for _, c in prefills]
            + [len(c) for _, c in speculative])
        if not ok:
            raise RuntimeError(f"cannot schedule {what}: {why}")

        # admission passed: pin this tick's new adapters FIRST (pool
        # mutations precede any descriptor/KV mutation; a crashed fetch
        # rolls the acquired refs back so the tick rejects atomically).
        # Residents sort first so a miss's LRU eviction can never steal a
        # slot an already-resident hit in this same batch is about to pin.
        abind: Dict[int, Tuple[str, int]] = {}
        if self.adapters is not None:
            order = [(uid, self._pending_adapter[uid])
                     for uid, _ in prefills
                     if uid not in self._seqs
                     and self._pending_adapter.get(uid) is not None]
            order.sort(key=lambda t: self.adapters.slot_of(t[1]) is None)
            done = []
            try:
                for uid, aid in order:
                    abind[uid] = (aid, self.adapters.acquire(aid))
                    done.append(aid)
            except BaseException:
                for aid in done:
                    self.adapters.release(aid)
                raise

        # create descriptors for new prefill uids
        pdescs = []
        for uid, chunk in prefills:
            desc = self._seqs.get(uid)
            if desc is None:
                desc = SequenceDescriptor(uid=uid)
                desc.sampling = self._pending_sampling.pop(uid, None)
                if uid in abind:
                    desc.adapter_id, desc.adapter_slot = abind[uid]
                    self._pending_adapter.pop(uid, None)
                self._seqs[uid] = desc
            pdescs.append(desc)
        ddescs = [self._seqs[u] for u in decode_uids]
        sdescs = [self._seqs[u] for u, _ in speculative]
        for d in ddescs:
            self._ensure_blocks(d, d.seen_tokens + 1)
        for d, (_, chunk) in zip(pdescs, prefills):
            self._ensure_blocks(d, d.seen_tokens + len(chunk))
        for d, (_, chunk) in zip(sdescs, speculative):
            self._ensure_blocks(d, d.seen_tokens + len(chunk))
        return prefills, speculative, ddescs, pdescs, sdescs

    @atomic_on_reject
    def step(self, decode_uids: Sequence[int], decode_tokens: Sequence[int],
             prefills: Sequence[Tuple[int, Sequence[int]]] = (),
             speculative: Sequence[Tuple[int, Sequence[int]]] = ()):
        """One continuous-batching tick: every uid in ``decode_uids``
        advances one token and every ``(uid, chunk)`` in ``prefills``
        absorbs a prompt chunk (new uids start chunked prefill at position
        0; known uids continue where their last chunk stopped), in ONE
        device dispatch — the serving loop's per-tick program
        (inference/scheduler.py packs these against the token budget).

        ``speculative`` (ISSUE 8): ``(uid, [pending_token, d1..dk])`` rows
        for KNOWN uids — the pending decode input plus k drafter
        proposals, verified in the SAME dispatch via the extend path.
        Greedy acceptance: the row advances by the longest draft prefix
        matching the verifier's argmax chain plus the verifier's own next
        token (correction on a reject, bonus on a full accept); rejected
        drafts roll the paged-KV state back (written-token history, block
        refcounts, prefix-cache commit chain — see ``rewind``) before the
        commit, so the engine state after the tick is exactly as if only
        the accepted tokens had ever been decoded.

        Shapes are binned so a serving process compiles a bounded program
        set: decode/prefill/verify row counts and block-table widths round
        up a power-of-two ladder, chunk length rounds up the
        ``serving.chunk_bins`` ladder, verify width rounds up the
        ``serving.speculative.k_bins`` ladder (asserted in
        tests/test_serving_scheduler.py + tests/test_speculative.py).
        Admission is all-or-nothing BEFORE any state mutation, with errors
        naming needed-vs-free KV blocks and the offending uid; the
        admission charges every speculative row its FULL draft+verify
        width (worst case, all accepted).

        Returns ``(decode_logits [len(decode_uids), V], prefill_logits
        [len(prefills), V])`` — prefill logits are at each chunk's last
        token (argmax of a final chunk's row is the sequence's first
        generated token). With ``speculative`` rows the return is a
        3-tuple ``(decode_logits, prefill_logits, spec_results)`` where
        ``spec_results[i] = (accepted_count, emitted_tokens)`` for row i —
        ``emitted_tokens`` is the accepted drafts plus the verifier's
        correction/bonus token, every one of them exactly the greedy
        reference chain."""
        prefills, speculative, ddescs, pdescs, sdescs = self._admit_step(
            decode_uids, decode_tokens, prefills, speculative, "step()")

        if sdescs:
            return self._speculative_dispatch(
                decode_tokens, ddescs, prefills, pdescs, speculative, sdescs)

        V = self._mcfg.vocab_size
        dlogits = np.zeros((0, V), np.float32)
        plogits = np.zeros((0, V), np.float32)
        if ddescs and pdescs:
            Bd, Wd, tok, pos, dtables = self._pack_decode(ddescs, decode_tokens)
            chunks = [(d, c) for d, (_, c) in zip(pdescs, prefills)]
            cmax = max(len(c) for _, c in chunks)
            Bp, C, Wp, ids, start, nnew, ptables = self._pack_chunks(
                chunks, pad_chunk=self.config.serving.bin_chunk(cmax))
            fn = self._mixed_fn((Bd, Wd, Bp, C, Wp))
            ax = ()
            if self.adapters is not None:
                ax = (self.adapters.device_operands(),
                      self._aslots(ddescs, Bd), self._aslots(pdescs, Bp))
            self.cache, dl, pl = self._pop_moe(
                fn(self.params, self.cache, tok, pos,
                   dtables, ids, start, nnew, ptables, *ax))
            self._program_keys.add(("mixed", Bd, Wd, Bp, C, Wp))
            dlogits, plogits = np.asarray(dl), np.asarray(pl)
        elif ddescs:
            Bd, Wd, tok, pos, dtables = self._pack_decode(ddescs, decode_tokens)
            fn = self._paged_decode_fn(Bd)
            self.cache, dl = self._pop_moe(
                fn(self.params, self.cache, tok, pos, dtables,
                   *self._aargs(ddescs, Bd)))
            self._program_keys.add(("decode", Bd, Wd))
            dlogits = np.asarray(dl)
        elif pdescs:
            chunks = [(d, c) for d, (_, c) in zip(pdescs, prefills)]
            cmax = max(len(c) for _, c in chunks)
            Bp, C, Wp, ids, start, nnew, ptables = self._pack_chunks(
                chunks, pad_chunk=self.config.serving.bin_chunk(cmax))
            fn = self._extend_fn((Bp, C))
            self.cache, pl = self._pop_moe(
                fn(self.params, self.cache, ids, start, nnew,
                   ptables, *self._aargs(pdescs, Bp)))
            self._program_keys.add(("extend", Bp, C, Wp))
            plogits = np.asarray(pl)
        else:
            return dlogits, plogits
        self.dispatch_count += 1

        for i, d in enumerate(ddescs):
            d.seen_tokens += 1
            d.tokens.append(int(decode_tokens[i]))
            d.last_logits = dlogits[i]
            self._commit(d)
        for i, (d, (_, chunk)) in enumerate(zip(pdescs, prefills)):
            d.seen_tokens += len(chunk)
            d.tokens.extend(chunk)
            d.last_logits = plogits[i]
            self._commit(d)
        return dlogits[:len(ddescs)], plogits[:len(pdescs)]

    def _speculative_dispatch(self, decode_tokens, ddescs, prefills, pdescs,
                              speculative, sdescs):
        """The spec-lane tail of step(): pack all three lanes, run ONE
        ``_spec_step_impl`` dispatch, then apply acceptance — advance each
        verify row by its full chunk, rewind the rejected suffix, commit,
        and hand back ``(accepted, emitted_tokens)`` per row."""
        sv = self.config.serving
        V = self._mcfg.vocab_size
        dops = pops = sops = ()
        Bd = Wd = Bp = C = Wp = 0
        lora = self.adapters is not None
        if ddescs:
            Bd, Wd, tok, pos, dtables = self._pack_decode(ddescs,
                                                          decode_tokens)
            dops = (tok, pos, dtables)
            if lora:
                dops += (self._aslots(ddescs, Bd),)
        if pdescs:
            chunks = [(d, c) for d, (_, c) in zip(pdescs, prefills)]
            cmax = max(len(c) for _, c in chunks)
            Bp, C, Wp, ids, start, nnew, ptables = self._pack_chunks(
                chunks, pad_chunk=sv.bin_chunk(cmax))
            pops = (ids, start, nnew, ptables)
            if lora:
                pops += (self._aslots(pdescs, Bp),)
        schunks = [(d, c) for d, (_, c) in zip(sdescs, speculative)]
        # verify width off the k ladder: a row carrying j drafts is j+1
        # tokens; pad to bin_k(max j) + 1 so the warmed server's verify
        # programs stay bounded exactly like chunk lengths do
        kmax = max(len(c) for _, c in schunks) - 1
        Bs, Cs, Ws, sids, sstart, snnew, stables = self._pack_chunks(
            schunks, pad_chunk=sv.speculative.bin_k(kmax) + 1)
        sops = (sids, sstart, snnew, stables)
        if lora:
            sops += (self._aslots(sdescs, Bs),)

        key = ("spec", Bd, Wd, Bp, C, Wp, Bs, Cs, Ws)
        fn = self._spec_fn(key)
        self.cache, dl, pl, sres = self._pop_moe(fn(
            self.params, self.cache, dops, pops, sops,
            *((self.adapters.device_operands(),) if lora else ())))
        self.dispatch_count += 1
        self._program_keys.add(key)
        dlogits = (np.asarray(dl) if dl is not None
                   else np.zeros((0, V), np.float32))
        plogits = (np.asarray(pl) if pl is not None
                   else np.zeros((0, V), np.float32))
        ver, accepted, slast = (np.asarray(x) for x in sres)

        for i, d in enumerate(ddescs):
            d.seen_tokens += 1
            d.tokens.append(int(decode_tokens[i]))
            d.last_logits = dlogits[i]
            self._commit(d)
        for i, (d, (_, chunk)) in enumerate(zip(pdescs, prefills)):
            d.seen_tokens += len(chunk)
            d.tokens.extend(chunk)
            d.last_logits = plogits[i]
            self._commit(d)
        spec_results = []
        for i, (d, chunk) in enumerate(schunks):
            n, a = len(chunk), int(accepted[i])
            d.seen_tokens += n
            d.tokens.extend(chunk)
            # keep [pending_token, d1..da]; roll back the n-1-a rejected
            # draft slots BEFORE the commit so the content registry never
            # sees a rejected token
            if a < n - 1:
                self._rewind(d, d.seen_tokens - (n - 1 - a))
            d.last_logits = slast[i]
            self._commit(d)
            spec_results.append((a, chunk[1:1 + a] + [int(ver[i, a])]))
        return dlogits[:len(ddescs)], plogits[:len(pdescs)], spec_results

    # -- one-dispatch sampling (ISSUE 16) ------------------------------
    # The sampled serving tick: temperature/top-k/top-p (greedy as the
    # temp=0 degenerate case) runs INSIDE the mixed/spec step programs, so
    # the host receives int32 tokens + bool EOS flags and logits never
    # ship over the tunnel. Every sampling knob is a traced per-row
    # operand, so the warmed server's program-key ladder is the SAME one
    # the greedy step compiles — a greedy/sampled mix in one tick is one
    # program. Randomness is the Gumbel-max coupling
    # ``argmax(filtered/T + gumbel(fold_in(PRNGKey(seed), position)))``
    # with ``position`` the token's ABSOLUTE sequence index, computed
    # in-dispatch from operands the tick already carries (decode: dpos+1,
    # prefill finish: pstart+pnnew, verify slot j: sstart+j+1) — the chain
    # is a pure function of (seed, position, distribution), hence
    # bit-exactly replayable across batch composition, preemption,
    # failover re-prefill, and speculative verification.

    def configure_sampling(self, uid: int, params) -> None:
        """Attach per-request ``SamplingParams`` to ``uid``. Live uids
        update in place; unknown uids are registered pending and picked up
        when their first prefill chunk creates the descriptor. ``None``
        restores the greedy/no-EOS default."""
        desc = self._seqs.get(uid)
        if desc is not None:
            desc.sampling = params
        elif params is None:
            self._pending_sampling.pop(uid, None)
        else:
            self._pending_sampling[uid] = params

    def configure_adapter(self, uid: int, adapter_id: Optional[str]) -> None:
        """Bind ``adapter_id`` to ``uid`` — the ``configure_sampling``
        shape (ISSUE 18). Unknown uids register a PENDING binding consumed
        when admission creates the descriptor (that is where the pool slot
        is pinned, under the tick's atomic admission); live uids rebind in
        place, acquiring the new adapter before releasing the old so a
        failed acquire changes nothing. ``None`` restores the base model
        (null slot 0)."""
        desc = self._seqs.get(uid)
        if desc is None:
            if adapter_id is None:
                self._pending_adapter.pop(uid, None)
                return
            if self.adapters is None:
                raise RuntimeError(
                    "configure_adapter: adapters are disabled (set "
                    "adapters.enabled in the inference config)")
            if not self.adapters.registered(adapter_id):
                raise KeyError(
                    f"configure_adapter: {adapter_id!r} is not registered "
                    f"— publish it first")
            self._pending_adapter[uid] = adapter_id
            return
        if adapter_id == desc.adapter_id:
            return
        if adapter_id is not None:
            if self.adapters is None:
                raise RuntimeError(
                    "configure_adapter: adapters are disabled (set "
                    "adapters.enabled in the inference config)")
            slot = self.adapters.acquire(adapter_id)
        else:
            slot = 0
        if desc.adapter_id is not None:
            self.adapters.release(desc.adapter_id)
        desc.adapter_id, desc.adapter_slot = adapter_id, slot

    def _sampling_operands(self, descs, B: int):
        """Per-row traced sampling operands, padded to the binned batch:
        (seeds u32, temperature f32, top_k i32 0=off, top_p f32,
        eos i32 -1=off). Padding rows are greedy with EOS off, so they
        sample nothing and can never flag done."""
        seeds = np.zeros((B,), np.uint32)
        temps = np.zeros((B,), np.float32)
        topks = np.zeros((B,), np.int32)
        topps = np.ones((B,), np.float32)
        eos = np.full((B,), -1, np.int32)
        for i, d in enumerate(descs):
            sp = d.sampling
            if sp is None:
                continue
            seeds[i] = np.uint32(sp.seed)
            temps[i] = sp.temperature
            topks[i] = sp.top_k
            topps[i] = sp.top_p
            eos[i] = sp.eos_token_id
        return seeds, temps, topks, topps, eos

    def _lane_masks(self, descs, tails, B: int):
        """Constrained-decoding plane for one lane: [B, V] bool (True =
        allowed), or None when no row constrains. Each masked row's
        ``logit_mask(history)`` callable sees the FULL consumed history —
        the descriptor's written tokens plus this tick's new tokens
        (``tails[i]``: the pending decode token, or the prefill chunk) —
        and must allow at least one token."""
        if not any(d.sampling is not None and d.sampling.logit_mask is not None
                   for d in descs):
            return None
        V = self._mcfg.vocab_size
        m = np.ones((B, V), bool)
        for i, (d, tail) in enumerate(zip(descs, tails)):
            sp = d.sampling
            if sp is None or sp.logit_mask is None:
                continue
            row = np.asarray(sp.logit_mask(list(d.tokens) + list(tail)),
                             dtype=bool)
            if row.shape != (V,):
                raise ValueError(
                    f"logit_mask for uid {d.uid} returned shape {row.shape}, "
                    f"want ({V},)")
            if not row.any():
                raise ValueError(
                    f"logit_mask for uid {d.uid} allows no tokens — a "
                    "constrained row must keep at least one candidate")
            m[i] = row
        return m

    def _sampled_fn(self, key, impl):
        fn = self._mixed_cache.get(key)
        if fn is not None:
            return fn
        import jax

        fn = jax.jit(impl, donate_argnums=_donate_cache())
        self._mixed_cache[key] = fn
        return fn

    def _assert_on_device_sampling(self, key, outs) -> None:
        """The no-logits-to-host proof: every leaf a sampled dispatch
        returns must be token/flag-shaped — nothing with a vocab-sized
        trailing dim may cross to host. Records the avals per program key
        so tests can audit the full set."""
        import jax

        V = self._mcfg.vocab_size
        shapes = tuple(tuple(int(s) for s in x.shape)
                       for x in jax.tree_util.tree_leaves(outs))
        for s in shapes:
            assert not (s and s[-1] == V), (
                f"sampled step {key} ships a vocab-shaped output {s} to "
                "host — sampling must stay in-dispatch")
        self.sampled_output_shapes[key] = shapes

    def _mixed_sampled_impl(self, params, cache: PagedKVCache, dtok, dpos,
                            dtables, dsp, dmask, pids, pstart, pnnew,
                            ptables, psp, pmask, apool=None, daslots=None,
                            paslots=None):
        """The mixed step with the sampler fused at the head: identical
        trunk to ``_mixed_step_impl`` (same layer scan, same gather-last
        head projections), then ``seeded_tokens`` per lane. Returns
        (cache, decode_tokens [Bd], decode_eos [Bd], prefill_tokens [Bp],
        prefill_eos [Bp]) — int32/bool only, never [*, V]."""
        from .sampling import seeded_tokens

        out = self._mixed_step_impl(
            params, cache, dtok, dpos, dtables, pids, pstart, pnnew, ptables,
            apool=apool, daslots=daslots, paslots=paslots)
        cache, dlogits, plogits = out[:3]
        dseeds, dtemp, dtk, dtp, deos = dsp
        pseeds, ptemp, ptk, ptp, peos = psp
        # decode row emits the token at absolute index dpos+1 (dpos is the
        # slot the input token writes); a finished prefill's first
        # generated token sits at pstart+pnnew
        dtoks = seeded_tokens(dlogits, dseeds, dpos + 1, dtemp, dtk, dtp,
                              mask=dmask)
        ptoks = seeded_tokens(plogits, pseeds, pstart + pnnew, ptemp, ptk,
                              ptp, mask=pmask)
        ddone = (dtoks == deos) & (deos >= 0)
        pdone = (ptoks == peos) & (peos >= 0)
        return (cache, dtoks, ddone, ptoks, pdone) + out[3:]

    def _decode_sampled_impl(self, params, cache: PagedKVCache, dtok, dpos,
                             dtables, dsp, dmask, apool=None, daslots=None):
        from .sampling import seeded_tokens

        out = self._paged_decode_impl(params, cache, dtok, dpos,
                                      dtables, apool=apool,
                                      aslots=daslots)
        cache, dlogits = out[:2]
        dseeds, dtemp, dtk, dtp, deos = dsp
        dtoks = seeded_tokens(dlogits, dseeds, dpos + 1, dtemp, dtk, dtp,
                              mask=dmask)
        ddone = (dtoks == deos) & (deos >= 0)
        return (cache, dtoks, ddone) + out[2:]

    def _extend_sampled_impl(self, params, cache: PagedKVCache, pids, pstart,
                             pnnew, ptables, psp, pmask, apool=None,
                             paslots=None):
        from .sampling import seeded_tokens

        out = self._extend_impl(params, cache, pids, pstart,
                                pnnew, ptables, apool=apool,
                                aslots=paslots)
        cache, plogits = out[:2]
        pseeds, ptemp, ptk, ptp, peos = psp
        ptoks = seeded_tokens(plogits, pseeds, pstart + pnnew, ptemp, ptk,
                              ptp, mask=pmask)
        pdone = (ptoks == peos) & (peos >= 0)
        return (cache, ptoks, pdone) + out[2:]

    def _spec_sampled_impl(self, params, cache: PagedKVCache, dops, pops,
                           sops, dsp, psp, ssp, dmask, pmask, apool=None):
        """The speculative mixed step generalized to TRUE speculative
        sampling: the verify lane evaluates the seeded sampling chain
        ``st[j] = seeded_tokens(logits_after_j, seed, sstart+j+1)`` at
        EVERY chunk position and accepts the longest draft prefix that
        MATCHES the chain. Our drafters are deterministic (point-mass
        proposals), for which Gumbel-coupled chain-matching IS the
        Leviathan accept/residual-resample rule: a draft is accepted iff
        the target chain would have emitted it, and the first rejected
        slot's chain token is exactly the residual resample. The emitted
        tokens are therefore the seeded chain itself — bit-identical with
        speculation on or off, at any k, greedy or sampled. Returns
        (cache, (dtoks, ddone) | None, (ptoks, pdone) | None,
        (chain [Bs, Cs] i32, accepted [Bs])) — the [Bs, Cs, V] verify
        logits never leave the device (the greedy path ships last_logits
        [Bs, V]; this path ships nothing vocab-shaped at all)."""
        import jax
        import jax.numpy as jnp

        from .sampling import seeded_tokens

        dops, pops, sops = tuple(dops), tuple(pops), tuple(sops)
        dslots = pslots = sslots = None
        xd = xp = xs = None
        cos = sin = None
        if dops:
            if apool is not None:
                dtok, dpos, dtables, dslots = dops
            else:
                dtok, dpos, dtables = dops
            xd, (cos, sin), _ = self._embed_at(params, dtok[:, None], dpos)
        if pops:
            if apool is not None:
                pids, pstart, pnnew, ptables, pslots = pops
            else:
                pids, pstart, pnnew, ptables = pops
            xp, (cos, sin), ppos = self._embed_at(params, pids, pstart)
        if apool is not None:
            sids, sstart, snnew, stables, sslots = sops
        else:
            sids, sstart, snnew, stables = sops
        xs, (cos, sin), spos = self._embed_at(params, sids, sstart)

        def layer_fn(carry, layer_and_cache):
            hd, hp, hs = carry
            lw, ck, cv = layer_and_cache[:3]
            ap = None if apool is None else layer_and_cache[3]
            tap = self._moe_arm()
            if hd is not None:
                hd, (ck, cv) = self._decode_layer(
                    lw, hd, ck, cv, cos, sin, dpos, dtables,
                    lora=None if ap is None else (ap, dslots))
            if hp is not None:
                hp, (ck, cv) = self._extend_layer(
                    lw, hp, ck, cv, cos, sin, ppos, pstart, pnnew, ptables,
                    lora=None if ap is None else (ap, pslots))
            hs, (ck, cv) = self._extend_layer(
                lw, hs, ck, cv, cos, sin, spos, sstart, snnew, stables,
                lora=None if ap is None else (ap, sslots))
            return (hd, hp, hs), (ck, cv) + self._moe_ys(tap)

        (xd, xp, xs), ys = jax.lax.scan(
            layer_fn, (xd, xp, xs), (params["layers"],) + self._kv_xs(cache)
            + self._apool_xs(apool))
        kp, vp = ys[0], ys[1]
        dres = pres = None
        if dops:
            dlogits = self.model.head(params, xd)[:, 0]
            dseeds, dtemp, dtk, dtp, deos = dsp
            dtoks = seeded_tokens(dlogits, dseeds, dpos + 1, dtemp, dtk,
                                  dtp, mask=dmask)
            dres = (dtoks, (dtoks == deos) & (deos >= 0))
        if pops:
            x_last = jnp.take_along_axis(
                xp, (pnnew - 1)[:, None, None].astype(jnp.int32), axis=1)
            plogits = self.model.head(params, x_last)[:, 0]
            pseeds, ptemp, ptk, ptp, peos = psp
            ptoks = seeded_tokens(plogits, pseeds, pstart + pnnew, ptemp,
                                  ptk, ptp, mask=pmask)
            pres = (ptoks, (ptoks == peos) & (peos >= 0))
        slog = self.model.head(params, xs)          # [Bs, Cs, V], on device
        Bs, Cs = sids.shape
        sseeds, stemp, stk, stp, _ = ssp
        spositions = sstart[:, None] + jnp.arange(Cs)[None, :] + 1
        bc = lambda a: jnp.broadcast_to(a[:, None], (Bs, Cs))  # noqa: E731
        chain = seeded_tokens(slog, bc(sseeds), spositions, bc(stemp),
                              bc(stk), bc(stp))
        nxt = jnp.concatenate(
            [sids[:, 1:], jnp.zeros((Bs, 1), sids.dtype)], axis=1)
        j = jnp.arange(Cs)[None, :]
        m = jnp.where(j < (snnew - 1)[:, None], chain == nxt, False)
        accepted = jnp.sum(jnp.cumprod(m.astype(jnp.int32), axis=1),
                           axis=1)                   # [Bs] in [0, k]
        return (self._cache_of(kp, vp), dres, pres,
                (chain, accepted)) + tuple(ys[2:])

    @atomic_on_reject
    def step_sampled(self, decode_uids: Sequence[int],
                     decode_tokens: Sequence[int],
                     prefills: Sequence[Tuple[int, Sequence[int]]] = (),
                     speculative: Sequence[Tuple[int, Sequence[int]]] = ()):
        """step() with sampling fused into the dispatch: same lanes, same
        admission, same shape-bin ladder — but the return is tokens and
        EOS flags, never logits. Per-uid behavior comes off the
        descriptor's ``SamplingParams`` (``configure_sampling``); uids
        without one run greedy with EOS off, bit-identical to step()'s
        argmax chain.

        Returns ``(decode_tokens [nd], decode_eos [nd], prefill_tokens
        [np], prefill_eos [np])`` int32/bool — prefill entries are only
        meaningful on a sequence's FINAL chunk (mid-prompt chunks sample a
        position the prompt will overwrite; callers ignore them, exactly
        as they ignored mid-chunk logits). With ``speculative`` rows a
        5-tuple appends ``spec_results[i] = (accepted, emitted_tokens)``
        where every emitted token is the row's seeded chain (EOS inside
        the emitted list is the caller's host-side cut — the flags here
        cover the single-token lanes). Commits set ``last_logits = None``:
        a sampled sequence has no host logits by design, and anything that
        silently assumed them fails loudly instead of reading stale rows.

        Constrained rows (``SamplingParams.logit_mask``) dispatch masked
        program variants (distinct ``*_m`` program keys) and are rejected
        from the speculative lane — the mask changes the target chain
        mid-flight, which drafters can't see."""
        prefills, speculative, ddescs, pdescs, sdescs = self._admit_step(
            decode_uids, decode_tokens, prefills, speculative,
            "step_sampled()")
        for d in sdescs:
            if d.sampling is not None and d.sampling.logit_mask is not None:
                raise ValueError(
                    f"speculative uid {d.uid} carries a logit_mask — "
                    "constrained sequences must decode one token at a time "
                    "(schedule it in decode_uids)")
        if sdescs:
            return self._speculative_sampled_dispatch(
                decode_tokens, ddescs, prefills, pdescs, speculative, sdescs)

        nd, npre = len(ddescs), len(pdescs)
        dtoks = np.zeros((0,), np.int32)
        ddone = np.zeros((0,), bool)
        ptoks = np.zeros((0,), np.int32)
        pdone = np.zeros((0,), bool)
        if ddescs and pdescs:
            Bd, Wd, tok, pos, dtables = self._pack_decode(ddescs,
                                                          decode_tokens)
            chunks = [(d, c) for d, (_, c) in zip(pdescs, prefills)]
            cmax = max(len(c) for _, c in chunks)
            Bp, C, Wp, ids, start, nnew, ptables = self._pack_chunks(
                chunks, pad_chunk=self.config.serving.bin_chunk(cmax))
            dsp = self._sampling_operands(ddescs, Bd)
            psp = self._sampling_operands(pdescs, Bp)
            dmask = self._lane_masks(ddescs, [[t] for t in decode_tokens], Bd)
            pmask = self._lane_masks(pdescs, [c for _, c in prefills], Bp)
            masked = dmask is not None or pmask is not None
            key = (("mixed_m" if masked else "mixed"), Bd, Wd, Bp, C, Wp)
            fn = self._sampled_fn(("s",) + key, self._mixed_sampled_impl)
            ax = ()
            if self.adapters is not None:
                ax = (self.adapters.device_operands(),
                      self._aslots(ddescs, Bd), self._aslots(pdescs, Bp))
            self.cache, dt, dd, pt, pd = self._pop_moe(fn(
                self.params, self.cache, tok, pos, dtables, dsp, dmask,
                ids, start, nnew, ptables, psp, pmask, *ax))
            self._assert_on_device_sampling(key, (dt, dd, pt, pd))
            self._program_keys.add(key)
            dtoks, ddone = np.asarray(dt), np.asarray(dd)
            ptoks, pdone = np.asarray(pt), np.asarray(pd)
        elif ddescs:
            Bd, Wd, tok, pos, dtables = self._pack_decode(ddescs,
                                                          decode_tokens)
            dsp = self._sampling_operands(ddescs, Bd)
            dmask = self._lane_masks(ddescs, [[t] for t in decode_tokens], Bd)
            key = (("decode_m" if dmask is not None else "decode"), Bd, Wd)
            fn = self._sampled_fn(("s",) + key, self._decode_sampled_impl)
            self.cache, dt, dd = self._pop_moe(
                fn(self.params, self.cache, tok, pos, dtables, dsp, dmask,
                   *self._aargs(ddescs, Bd)))
            self._assert_on_device_sampling(key, (dt, dd))
            self._program_keys.add(key)
            dtoks, ddone = np.asarray(dt), np.asarray(dd)
        elif pdescs:
            chunks = [(d, c) for d, (_, c) in zip(pdescs, prefills)]
            cmax = max(len(c) for _, c in chunks)
            Bp, C, Wp, ids, start, nnew, ptables = self._pack_chunks(
                chunks, pad_chunk=self.config.serving.bin_chunk(cmax))
            psp = self._sampling_operands(pdescs, Bp)
            pmask = self._lane_masks(pdescs, [c for _, c in prefills], Bp)
            key = (("extend_m" if pmask is not None else "extend"), Bp, C, Wp)
            fn = self._sampled_fn(("s",) + key, self._extend_sampled_impl)
            self.cache, pt, pd = self._pop_moe(
                fn(self.params, self.cache, ids, start,
                   nnew, ptables, psp, pmask,
                   *self._aargs(pdescs, Bp)))
            self._assert_on_device_sampling(key, (pt, pd))
            self._program_keys.add(key)
            ptoks, pdone = np.asarray(pt), np.asarray(pd)
        else:
            return dtoks, ddone, ptoks, pdone
        self.dispatch_count += 1

        for i, d in enumerate(ddescs):
            d.seen_tokens += 1
            d.tokens.append(int(decode_tokens[i]))
            d.last_logits = None
            self._commit(d)
        for i, (d, (_, chunk)) in enumerate(zip(pdescs, prefills)):
            d.seen_tokens += len(chunk)
            d.tokens.extend(chunk)
            d.last_logits = None
            self._commit(d)
        return dtoks[:nd], ddone[:nd], ptoks[:npre], pdone[:npre]

    def _speculative_sampled_dispatch(self, decode_tokens, ddescs, prefills,
                                      pdescs, speculative, sdescs):
        """The spec-lane tail of step_sampled(): pack all three lanes plus
        their sampling operands, run ONE ``_spec_sampled_impl`` dispatch,
        apply chain-match acceptance, rewind rejected draft KV, and emit
        the seeded chain per row."""
        sv = self.config.serving
        lora = self.adapters is not None
        dops = pops = ()
        dsp = psp = ()
        dmask = pmask = None
        Bd = Wd = Bp = C = Wp = 0
        if ddescs:
            Bd, Wd, tok, pos, dtables = self._pack_decode(ddescs,
                                                          decode_tokens)
            dops = (tok, pos, dtables)
            if lora:
                dops += (self._aslots(ddescs, Bd),)
            dsp = self._sampling_operands(ddescs, Bd)
            dmask = self._lane_masks(ddescs, [[t] for t in decode_tokens], Bd)
        if pdescs:
            chunks = [(d, c) for d, (_, c) in zip(pdescs, prefills)]
            cmax = max(len(c) for _, c in chunks)
            Bp, C, Wp, ids, start, nnew, ptables = self._pack_chunks(
                chunks, pad_chunk=sv.bin_chunk(cmax))
            pops = (ids, start, nnew, ptables)
            if lora:
                pops += (self._aslots(pdescs, Bp),)
            psp = self._sampling_operands(pdescs, Bp)
            pmask = self._lane_masks(pdescs, [c for _, c in prefills], Bp)
        schunks = [(d, c) for d, (_, c) in zip(sdescs, speculative)]
        kmax = max(len(c) for _, c in schunks) - 1
        Bs, Cs, Ws, sids, sstart, snnew, stables = self._pack_chunks(
            schunks, pad_chunk=sv.speculative.bin_k(kmax) + 1)
        sops = (sids, sstart, snnew, stables)
        if lora:
            sops += (self._aslots(sdescs, Bs),)
        ssp = self._sampling_operands(sdescs, Bs)

        masked = dmask is not None or pmask is not None
        key = (("spec_m" if masked else "spec"),
               Bd, Wd, Bp, C, Wp, Bs, Cs, Ws)
        fn = self._sampled_fn(("s",) + key, self._spec_sampled_impl)
        self.cache, dres, pres, sres = self._pop_moe(fn(
            self.params, self.cache, dops, pops, sops, dsp, psp, ssp,
            dmask, pmask,
            *((self.adapters.device_operands(),) if lora else ())))
        self.dispatch_count += 1
        self._assert_on_device_sampling(key, (dres, pres, sres))
        self._program_keys.add(key)
        if dres is not None:
            dtoks, ddone = np.asarray(dres[0]), np.asarray(dres[1])
        else:
            dtoks, ddone = np.zeros((0,), np.int32), np.zeros((0,), bool)
        if pres is not None:
            ptoks, pdone = np.asarray(pres[0]), np.asarray(pres[1])
        else:
            ptoks, pdone = np.zeros((0,), np.int32), np.zeros((0,), bool)
        chain, accepted = (np.asarray(x) for x in sres)

        for i, d in enumerate(ddescs):
            d.seen_tokens += 1
            d.tokens.append(int(decode_tokens[i]))
            d.last_logits = None
            self._commit(d)
        for i, (d, (_, chunk)) in enumerate(zip(pdescs, prefills)):
            d.seen_tokens += len(chunk)
            d.tokens.extend(chunk)
            d.last_logits = None
            self._commit(d)
        spec_results = []
        for i, (d, chunk) in enumerate(schunks):
            n, a = len(chunk), int(accepted[i])
            d.seen_tokens += n
            d.tokens.extend(chunk)
            if a < n - 1:
                self._rewind(d, d.seen_tokens - (n - 1 - a))
            d.last_logits = None
            self._commit(d)
            spec_results.append((a, chunk[1:1 + a] + [int(chain[i, a])]))
        return (dtoks[:len(ddescs)], ddone[:len(ddescs)],
                ptoks[:len(pdescs)], pdone[:len(pdescs)], spec_results)

    # -- fused multi-token decode --------------------------------------

    def _decode_loop_fn(self, key):
        fn = self._loop_cache.get(key) if hasattr(self, "_loop_cache") else None
        if fn is not None:
            return fn
        if not hasattr(self, "_loop_cache"):
            self._loop_cache = {}
        import jax

        B, n_steps = key

        def impl(params, cache, tok, pos, btables, apool=None, aslots=None):
            import jax.numpy as jnp

            def step(carry, _):
                cache, tok, pos, _ = carry
                out = self._paged_decode_impl(params, cache, tok,
                                              pos, btables,
                                              apool=apool,
                                              aslots=aslots)
                cache, logits = out[:2]
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (cache, nxt, pos + 1, logits), (nxt,) + out[2:]

            logits0 = jnp.zeros((B, self._mcfg.vocab_size), jnp.float32)
            (cache, _, _, logits), ys = jax.lax.scan(
                step, (cache, tok, pos, logits0), None, length=n_steps)
            # ys[0] [n_steps, B] tokens; a trailing MoE-counts element
            # (stacked [n_steps, L, E]) rides when MoE serving is on
            return (cache, ys[0].T, logits) + tuple(ys[1:])

        fn = jax.jit(impl, donate_argnums=_donate_cache())
        self._loop_cache[key] = fn
        return fn

    @atomic_on_reject
    def decode_loop(self, uids: Sequence[int], tokens: Sequence[int],
                    n_steps: int) -> np.ndarray:
        """Greedy-decode ``n_steps`` tokens for known uids in ONE device
        program (a ``lax.scan`` over the paged decode step with on-device
        argmax feedback). The host sees a single dispatch, so per-token
        latency is the ENGINE's, not the host/tunnel round trip — the
        serving-latency isolation the per-``put`` API number can't give
        (each put() is a host RTT). Returns the generated tokens
        [len(uids), n_steps]; descriptors advance as if put() had run
        n_steps times.

        The reference's FastGen equivalent is host-looped puts
        (inference/v2/engine_v2.py:107) — on TPU the fused loop is the
        shape a serving process should prefer for long generations."""
        self._require_resident(uids, "decode_loop()")
        descs = [self._seqs[u] for u in uids]
        # Admission control BEFORE any mutation (same contract as put():
        # a rejected call leaves allocator + descriptors untouched), via
        # _admission_detail so the copy-on-write surcharge for shared
        # write-span blocks (forked tails) is budgeted too — a bare
        # blocks_needed count would admit, then fail mid-COW with earlier
        # descriptors already cloned. The length cap matters doubly here —
        # in-jit btable indexing clamps instead of erroring, so an overrun
        # would silently write another sequence's KV blocks.
        ok, _, why = self._admission_detail(uids, [n_steps] * len(uids))
        if not ok:
            raise RuntimeError(f"cannot schedule decode_loop: {why}")
        for d in descs:
            self._ensure_blocks(d, d.seen_tokens + n_steps)
        # binned table width (round 9): the decode kernels stream every
        # table entry's block, so a max_seq_len-wide table reads ~3x the
        # live KV at typical fills — width covers exactly the blocks this
        # loop can touch, rounded up a power of two to bound compiles
        W = self._binned_width(max(len(d.blocks) for d in descs))
        btables = np.stack([self._table(d, W) for d in descs]).astype(np.int32)
        self._last_decode_table_width = W
        pos = np.asarray([d.seen_tokens for d in descs], np.int32)
        tok0 = np.asarray(tokens, np.int32)
        fn = self._decode_loop_fn((len(uids), int(n_steps)))
        self.cache, toks, last_logits = self._pop_moe(
            fn(self.params, self.cache, tok0, pos, btables,
               *self._aargs(descs, len(uids))))
        self.dispatch_count += 1
        self._program_keys.add(("decode_loop", len(uids), int(n_steps), W))
        last_logits = np.asarray(last_logits)
        toks = np.asarray(toks)
        for i, d in enumerate(descs):
            d.seen_tokens += n_steps
            # written KV slots: the seed token plus every generated token
            # except the last (which has logits but no KV entry yet)
            d.tokens.append(int(tok0[i]))
            d.tokens.extend(int(t) for t in toks[i, :-1])
            d.last_logits = last_logits[i]
            self._commit(d)
        return toks

    # -- disaggregated prefill/decode: block export / import -----------
    # (ISSUE 7: the PagedKVCache block IS the wire format — a prefill
    # worker exports a finished sequence's blocks, the transfer substrate
    # moves the bytes (serving/disagg.py stages them through the AIO
    # pinned-buffer pool), and a decode worker imports them under an
    # admission handshake: blocks are acquired BEFORE any payload bytes
    # move, atomic-on-reject with _admission_detail-named errors.)

    def export_kv_blocks(self, uid: int) -> "KVBlockPayload":
        """Snapshot ``uid``'s written KV blocks + host state for a
        disaggregated transfer. The payload arrays are the pool's OWN
        storage layout ([L, nb, KV, block, Dh] data, [L, nb, KV, block]
        scale planes for quantized pools) pulled to host — bf16 pools
        round-trip bit-exactly, quantized pools byte-exactly (payload and
        scales are copied, never re-quantized). The source sequence stays
        live; the caller flushes it when the handoff is done."""
        desc = self._seqs.get(uid)
        if desc is None:
            raise ValueError(f"unknown uid {uid}")
        bs = self.cache.block_size
        nb = blocks_needed(desc.seen_tokens, bs)
        assert len(desc.blocks) >= nb, (uid, len(desc.blocks), nb)
        spilled = sorted(i for i in desc.spilled if i < nb)
        if not spilled:
            idx = np.asarray(desc.blocks[:nb], np.int32)
            planes = [np.asarray(p[:, idx]) for p in self._pool_planes()]
        else:
            # tiered compose (ISSUE 15): a parked sequence's payload is
            # assembled from BOTH tiers — resident positions gather pool
            # storage, spilled positions read the host tier's byte-exact
            # copy — so a failover KV-migration of a spilled sequence
            # ships the same bytes a fully-resident export would (no
            # fetch, no re-prefill, no re-quantization)
            resident = [i for i in range(nb) if i not in desc.spilled]
            idx = np.asarray([desc.blocks[i] for i in resident], np.int32)
            pool_planes = [np.asarray(p[:, idx])
                           for p in self._pool_planes()]
            tidx, tplanes = self.tier.load(uid, count=False)
            planes = []
            for pp, tp in zip(pool_planes, tplanes):
                full = np.empty((pp.shape[0], nb) + pp.shape[2:], pp.dtype)
                for j, i in enumerate(resident):
                    full[:, i] = pp[:, j]
                for j, i in enumerate(tidx):
                    if i < nb:
                        full[:, i] = tp[:, j]
                planes.append(full)
        quantized = self.cache.quantized
        return KVBlockPayload(
            uid=uid,
            tokens=list(desc.tokens),
            seen_tokens=desc.seen_tokens,
            last_logits=None if desc.last_logits is None
            else np.asarray(desc.last_logits),
            k=planes[0],
            v=planes[1],
            k_scale=planes[2] if quantized else None,
            v_scale=planes[3] if quantized else None,
            kv_cache_dtype=self.config.kv_cache_dtype,
            block_size=bs,
            weight_version=self.weight_version,
        )

    @atomic_on_reject
    def begin_import(self, uid: int, n_tokens: int) -> "ImportReservation":
        """The admission half of the disagg handshake: acquire the KV
        blocks a ``n_tokens``-token import needs BEFORE any payload bytes
        move. Atomic-on-reject — a refused reservation mutates nothing,
        and the error names needed-vs-free blocks via the same
        ``_admission_detail`` discipline as put()/step(). The transfer
        then either ``commit_import``s the payload into the reserved
        blocks or ``abort_import``s to release them."""
        if uid in self._seqs:
            raise ValueError(f"uid {uid} is already live")
        if n_tokens < 1:
            raise ValueError(f"import of {n_tokens} tokens")
        ok, _, why = self._admission_detail([uid], [n_tokens])
        if not ok:
            raise RuntimeError(f"cannot reserve KV import for uid {uid}: "
                               f"{why}")
        blocks = self.allocator.allocate(
            blocks_needed(n_tokens, self.cache.block_size))
        return ImportReservation(uid=uid, blocks=blocks,
                                 n_tokens=int(n_tokens))

    def abort_import(self, resv: "ImportReservation") -> None:
        """Release a reservation's blocks (transfer failed or was vetoed).
        Idempotent via the ``done`` flag so cleanup paths can call it
        unconditionally."""
        if not resv.done:
            resv.done = True
            self.allocator.free(resv.blocks)

    def _import_fn(self, nb: int, quantized: bool):
        key = ("import", nb, quantized)
        fn = self._mixed_cache.get(key)
        if fn is not None:
            return fn
        import jax

        from ..utils.placement import cache_safe_donate_argnums

        if quantized:
            def impl(cache, idx, k, v, ks, vs):
                return PagedKVCache(cache.k.at[:, idx].set(k),
                                    cache.v.at[:, idx].set(v),
                                    cache.k_scale.at[:, idx].set(ks),
                                    cache.v_scale.at[:, idx].set(vs))
        else:
            def impl(cache, idx, k, v):
                return PagedKVCache(cache.k.at[:, idx].set(k),
                                    cache.v.at[:, idx].set(v))
        # the pool is argument 0 here (no params operand), unlike the
        # layer-scan programs where it rides at 1
        fn = jax.jit(impl, donate_argnums=cache_safe_donate_argnums((0,)))
        self._mixed_cache[key] = fn
        return fn

    def commit_import(self, resv: "ImportReservation",
                      payload: "KVBlockPayload") -> None:
        """Write a transferred payload into the reserved blocks and bring
        the sequence live. Validates the wire format against THIS pool
        (block size, kv_cache_dtype, per-block shape) before touching
        device state — a mismatch raises with both sides named and the
        reservation still held, so the caller's cleanup path aborts it.
        The imported descriptor commits its full blocks to the prefix
        registry like any locally-prefilled sequence would (disagg
        requires identical weights fleet-wide for token parity, which is
        exactly the prefix cache's validity condition)."""
        if resv.done:
            raise RuntimeError(f"reservation for uid {resv.uid} already "
                               f"committed or aborted")
        if resv.uid in self._seqs:
            raise ValueError(f"uid {resv.uid} is already live")
        if payload.block_size != self.cache.block_size:
            raise ValueError(
                f"wire-format mismatch: payload blocks are "
                f"{payload.block_size} tokens, this pool's are "
                f"{self.cache.block_size}")
        if payload.kv_cache_dtype != self.config.kv_cache_dtype:
            raise ValueError(
                f"wire-format mismatch: payload kv_cache_dtype "
                f"{payload.kv_cache_dtype!r}, this pool stores "
                f"{self.config.kv_cache_dtype!r}")
        if (payload.weight_version is not None
                and payload.weight_version != self.weight_version):
            raise ValueError(
                f"weight-version mismatch: payload KV was computed under "
                f"version {payload.weight_version} but this engine serves "
                f"version {self.weight_version} — KV bytes are only valid "
                f"against the weights that wrote them (re-prefill instead)")
        if payload.seen_tokens != resv.n_tokens:
            raise ValueError(
                f"payload carries {payload.seen_tokens} tokens but the "
                f"reservation was for {resv.n_tokens}")
        nb = len(resv.blocks)
        want = (self.cache.k.shape[0], nb) + self.cache.k.shape[2:]
        if tuple(payload.k.shape) != want:
            raise ValueError(
                f"wire-format mismatch: payload k is "
                f"{tuple(payload.k.shape)}, this pool expects {want}")
        idx = np.asarray(resv.blocks, np.int32)
        quantized = self.cache.quantized
        fn = self._import_fn(nb, quantized)
        if quantized:
            self.cache = fn(self.cache, idx,
                            payload.k.astype(self.cache.k.dtype),
                            payload.v.astype(self.cache.v.dtype),
                            payload.k_scale, payload.v_scale)
        else:
            self.cache = fn(self.cache, idx,
                            payload.k.astype(self.cache.k.dtype),
                            payload.v.astype(self.cache.v.dtype))
        resv.done = True
        desc = SequenceDescriptor(
            uid=resv.uid, seen_tokens=payload.seen_tokens,
            blocks=list(resv.blocks),
            last_logits=None if payload.last_logits is None
            else np.asarray(payload.last_logits),
            tokens=list(payload.tokens))
        self._seqs[resv.uid] = desc
        self._commit(desc)

    # -- versioned weight swap (ISSUE 11: the RLHF train->serve flip) ---
    # The serving programs are weight-agnostic jitted functions, so a
    # weight swap is a pytree pointer flip: paged KV pools, block
    # allocator, and every compiled program survive it untouched (zero
    # recompiles across flips — tests/test_rlhf.py pins it). What a swap
    # MUST do is invalidate the prefix-cache content registry (keys hash
    # token history, not weights) and bar live mixed-weight sequences
    # from committing their blocks. Delivery is two-phase so a fleet
    # publish can crash between replicas and leave every one of them
    # serving the OLD weights (serving/router.py publish_weights).

    @atomic_on_reject(check="validate")
    def stage_weights(self, params, version: Optional[int] = None,
                      prepared: bool = False) -> None:
        """Phase 1 of the train->serve flip: cast/quantize/place the new
        tree into the staging slot without touching serving state. The
        prepare is the half that can fail (casts, device transfer,
        quantization); after it returns, ``commit_staged_weights`` is a
        host pointer swap. Validates the prepared tree's structure against
        the live one BEFORE staging, so a later commit cannot discover a
        mismatch mid-flip. ``prepared=True`` takes ``params`` as already
        run through ``_prepare_params`` — the router prepares ONCE per
        serving-transform key and hands the same placed tree to every
        replica (sharing the device buffers; the serving programs never
        donate the params operand)."""
        import jax

        placed = params if prepared else self._prepare_params(params)
        new_td = jax.tree_util.tree_structure(placed)
        old_td = jax.tree_util.tree_structure(self.params)
        if new_td != old_td:
            raise ValueError(
                "stage_weights: published tree structure does not match the "
                f"serving tree ({new_td} vs {old_td}) — publish the "
                "model-structured weights (engine.module_weights())")
        self._staged_weights = (placed,
                                None if version is None else int(version))

    def discard_staged_weights(self) -> None:
        """Drop an uncommitted staging slot (fleet-publish rollback path).
        Safe to call when nothing is staged."""
        self._staged_weights = None

    def commit_staged_weights(self, force: bool = False,
                              defer: bool = False) -> bool:
        """Phase 2 of the flip: move serving onto the staged tree.

        Live sequences hold KV computed under the OLD weights, so a commit
        under them would silently mix weights into their continuations.
        The guard ladder:

        - no live sequences: install immediately (the staged slot empties);
        - live + ``defer=True``: the staged tree becomes PENDING and is
          installed at the next tick boundary (``apply_pending_weights``,
          which the scheduler calls at tick entry after the in-flight tick
          has fully drained) — the router's delivery mode, safe to call
          while another thread is mid-tick;
        - live + ``force=True``: install NOW (the PR 2 hard-swap for
          callers that accept mid-episode approximation);
        - live + neither: refuse, keep the staged tree for a retry, and
          return False."""
        if self._staged_weights is None:
            raise RuntimeError("commit_staged_weights: nothing staged "
                               "(stage_weights first)")
        if self._seqs and not (force or defer):
            logger.warning(
                f"commit_staged_weights: {len(self._seqs)} live sequences "
                "hold KV from the current weights; refusing the swap (drain "
                "or flush() them, or pass force=True / defer=True)")
            return False
        if self._seqs and defer and not force:
            self._pending_weights = self._staged_weights
            self._staged_weights = None
            return True
        staged, self._staged_weights = self._staged_weights, None
        self._install_weights(*staged)
        return True

    def _install_weights(self, placed, version: Optional[int]) -> None:
        """The actual swap: flip the params pointer, stamp the version,
        and invalidate everything that silently assumed weight identity —
        the content index points at KV computed under the OLD weights
        (keys are pure functions of token history, so a post-swap
        admission hashing the same system prompt would reuse stale KV),
        and live sequences carry mixed-weight KV that must never enter
        the registry."""
        self.params = placed
        self.weight_version = (self.weight_version + 1 if version is None
                               else int(version))
        self.allocator.invalidate_registry()
        for d in self._seqs.values():
            d.no_commit = True

    @property
    def has_pending_weights(self) -> bool:
        return self._pending_weights is not None

    def apply_pending_weights(self) -> bool:
        """Install a deferred weight commit — the tick-boundary half of
        ``commit_staged_weights(defer=True)``. The scheduler calls this at
        tick entry (the previous tick's dispatch has fully drained, the
        next has not started), which is the only point a swap can land
        without interleaving a half-executed tick; direct ``step()``
        drivers own their tick boundary and call it themselves. Returns
        True when a swap was applied."""
        if self._pending_weights is None:
            return False
        pending, self._pending_weights = self._pending_weights, None
        self._install_weights(*pending)
        return True

    def publish_weights(self, params, version: Optional[int] = None,
                        force: bool = False, defer: bool = False) -> bool:
        """In-memory weight delivery (the RLHF train->serve flip): stage +
        commit in one call. ``rlhf.WeightPublisher`` hands the gathered
        training tree here; the fleet path goes through
        ``serving/router.py publish_weights`` instead so the stage phase
        completes on EVERY replica before any replica flips."""
        self.stage_weights(params, version=version)
        return self.commit_staged_weights(force=force, defer=defer)

    def reload_weights(self, ckpt_dir: str, tag: Optional[str] = None,
                       force: bool = False, defer: bool = False) -> bool:
        """Hot-swap serving weights from a training checkpoint (see the base
        engine), with a continuous-batching guard: live sequences hold KV
        entries computed under the OLD weights, so swapping under them would
        silently corrupt their continuations. With live sequences the swap
        is refused (returns False, keeps serving) unless the caller opts
        in: ``defer=True`` applies the swap at the next tick boundary (the
        scheduler drains the in-flight tick first — the footgun-free mode
        the router uses), ``force=True`` hard-swaps immediately (RLHF
        rollouts mid-episode that accept the approximation). Load failures
        — mid-save, torn ``latest``, corrupted shards — keep serving the
        current weights and return False either way."""
        if self._seqs and not (force or defer):
            logger.warning(
                f"reload_weights: {len(self._seqs)} live sequences hold KV "
                "from the current weights; refusing the hot-swap (drain or "
                "flush() them, or pass force=True / defer=True)")
            return False
        params = self._try_load_serving_weights(ckpt_dir, tag=tag)
        if params is None:
            return False
        self.stage_weights(params)
        return self.commit_staged_weights(force=force, defer=defer)

    def flush(self, uids: Sequence[int], early_stop: bool = False) -> None:
        """Free all state for finished sequences (engine_v2.py:242).
        Spilled blocks (ISSUE 15) have no pool slot to free — their host
        tier entry is dropped instead. ``early_stop=True`` marks an
        EOS/stop-sequence termination (ISSUE 16): the freed pool slots are
        tallied in ``early_stop_freed_blocks`` so the scheduler's
        sampling/* counters can report the KV the stop returned ahead of
        the request's budgeted lifetime."""
        for uid in uids:
            desc = self._seqs.pop(uid, None)
            if desc is None:
                raise ValueError(f"unknown uid {uid}")
            self._pending_sampling.pop(uid, None)
            self._pending_adapter.pop(uid, None)
            if desc.adapter_id is not None and self.adapters is not None:
                # unpin the slot; the adapter stays resident (warm) until
                # LRU eviction needs it
                self.adapters.release(desc.adapter_id)
            if early_stop:
                self.early_stop_freed_blocks += sum(
                    1 for b in desc.blocks if b >= 0)
            if desc.spilled:
                self.allocator.free([b for b in desc.blocks if b >= 0])
                self.tier.drop(uid)
            else:
                self.allocator.free(desc.blocks)
