"""Inference engine v2 — continuous batching over a paged KV cache.

Capability analog of the reference FastGen stack (``inference/v2/engine_v2.py:30``
InferenceEngineV2, ``ragged/ragged_manager.py:19`` DSStateManager,
``ragged/sequence_descriptor.py:59``): host-side sequence state + block
allocator, device-side paged attention, and the ``put / query / flush``
serving API. Logits come back to the host (the reference samples on host
too); the v1 engine's fused generate covers the on-device loop.

TPU-first: every device program has static shapes — prompts are bucketed to
block multiples, decode batches to power-of-two widths — so a serving
process compiles a handful of programs total and replays them (the XLA
equivalent of the reference's CUDA-graph strategy).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import logger
from .config import InferenceConfig
from .engine import InferenceEngine, _bucket, _rope_rows, _apply_rope_batched
from .paged import (BlockedAllocator, PagedKVCache, append_token_kv, blocks_needed,
                    paged_decode_attention, write_prefill_kv)


@dataclasses.dataclass
class SequenceDescriptor:
    """Host state for one live sequence (ragged/sequence_descriptor.py:59)."""

    uid: int
    seen_tokens: int = 0
    blocks: List[int] = dataclasses.field(default_factory=list)
    last_logits: Optional[np.ndarray] = None


class InferenceEngineV2(InferenceEngine):
    """Paged continuous-batching engine.

    ``put(uids, tokens)`` runs prefill for new uids and single/multi-token
    extension for known ones, returning next-token logits per uid in order.
    """

    def __init__(self, model, params, config: Optional[InferenceConfig] = None):
        super().__init__(model, params, config)
        cfg, mcfg = self.config, self._mcfg
        if cfg.max_seq_len % cfg.kv_block_size:
            raise ValueError("max_seq_len must be a multiple of kv_block_size")
        self.cache = PagedKVCache.create(mcfg.n_layers, cfg.num_kv_blocks, cfg.kv_block_size,
                                         mcfg.kv_heads, mcfg.head_dim, cfg.jax_dtype())
        self.allocator = BlockedAllocator(cfg.num_kv_blocks)
        # block 0 is scratch: padding table entries scribble here, never read.
        self._scratch = self.allocator.allocate(1)[0]
        self._seqs: Dict[int, SequenceDescriptor] = {}
        self._max_blocks = cfg.max_seq_len // cfg.kv_block_size
        self._prefill_cache: Dict[int, object] = {}
        self._decode_cache: Dict[int, object] = {}

    # -- scheduling queries (engine_v2.py:158-232) ---------------------

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    def query(self, uid: int) -> Tuple[int, int]:
        """(max further tokens for uid, free blocks) — engine_v2.py:158."""
        desc = self._seqs.get(uid)
        seen = desc.seen_tokens if desc else 0
        have = len(desc.blocks) * self.cache.block_size if desc else 0
        headroom = (have - seen) + self.allocator.free_blocks * self.cache.block_size
        return min(self.config.max_seq_len - seen, headroom), self.allocator.free_blocks

    def can_schedule(self, uids: Sequence[int], lengths: Sequence[int]) -> bool:
        """Admission check (engine_v2.py:184 can_schedule)."""
        need = 0
        for uid, n in zip(uids, lengths):
            desc = self._seqs.get(uid)
            seen = desc.seen_tokens if desc else 0
            have = len(desc.blocks) if desc else 0
            if seen + n > self.config.max_seq_len:
                return False
            need += max(0, blocks_needed(seen + n, self.cache.block_size) - have)
        return need <= self.allocator.free_blocks

    # -- device programs ----------------------------------------------

    def _paged_prefill_fn(self, tpad: int):
        fn = self._prefill_cache.get(tpad)
        if fn is not None:
            return fn
        import jax

        fn = jax.jit(functools.partial(self._paged_prefill_impl, tpad=tpad),
                     donate_argnums=(1,))
        self._prefill_cache[tpad] = fn
        return fn

    def _paged_prefill_impl(self, params, cache: PagedKVCache, ids, plen, btable, *, tpad: int):
        """ids [1,tpad]; btable [tpad//block] (scratch-padded); -> cache, logits [1,V]."""
        import jax
        import jax.numpy as jnp

        from ..ops.flash_attention import flash_attention

        mcfg = self._mcfg
        x, (cos, sin), positions = self._embed_at(params, ids, jnp.zeros((1,), jnp.int32))

        def layer_fn(h, layer_and_cache):
            lw, ck, cv = layer_and_cache

            def attn_fn(q, k, v):
                ck2, cv2 = write_prefill_kv(ck, cv, k[0], v[0], btable)
                return flash_attention(q, k, v, causal=True,
                                       impl=self.config.attention_impl), (ck2, cv2)

            return self._layer_body(lw, h, cos, sin, positions, attn_fn)

        x, (kp, vp) = jax.lax.scan(layer_fn, x, (params["layers"], cache.k, cache.v))
        x_last = jnp.take_along_axis(x, (plen - 1)[:, None, None].astype(jnp.int32), axis=1)
        logits = self.model.head(params, x_last)[:, 0]
        return PagedKVCache(kp, vp), logits

    def _paged_decode_fn(self, b: int):
        fn = self._decode_cache.get(b)
        if fn is not None:
            return fn
        import jax

        fn = jax.jit(self._paged_decode_impl, donate_argnums=(1,))
        self._decode_cache[b] = fn
        return fn

    def _paged_decode_impl(self, params, cache: PagedKVCache, tok, pos, btables):
        """tok [B], pos [B] (next slot), btables [B, max_blocks]."""
        import jax
        import jax.numpy as jnp

        x, (cos, sin), _ = self._embed_at(params, tok[:, None], pos)

        def layer_fn(h, layer_and_cache):
            lw, ck, cv = layer_and_cache

            def attn_fn(q, k, v):
                ck2, cv2 = append_token_kv(ck, cv, k[:, 0], v[:, 0], btables, pos)
                return paged_decode_attention(q, ck2, cv2, btables, kv_len=pos + 1), (ck2, cv2)

            return self._layer_body(lw, h, cos, sin, pos, attn_fn)

        x, (kp, vp) = jax.lax.scan(layer_fn, x, (params["layers"], cache.k, cache.v))
        logits = self.model.head(params, x)[:, 0]
        return PagedKVCache(kp, vp), logits

    # -- host-side scheduling ------------------------------------------

    def _ensure_blocks(self, desc: SequenceDescriptor, total_tokens: int) -> None:
        need = blocks_needed(total_tokens, self.cache.block_size) - len(desc.blocks)
        if need > 0:
            desc.blocks.extend(self.allocator.allocate(need))

    def _table(self, desc: SequenceDescriptor) -> np.ndarray:
        t = np.full((self._max_blocks,), self._scratch, dtype=np.int32)
        t[:len(desc.blocks)] = desc.blocks
        return t

    def put(self, uids: Sequence[int], tokens: Sequence[Sequence[int]]) -> np.ndarray:
        """Serve one engine step (engine_v2.py:107). New uids are prefilled;
        known uids extended by their new tokens. Returns fp32 logits
        [len(uids), vocab] for each sequence's latest position, in order."""
        import jax.numpy as jnp

        if len(uids) != len(tokens):
            raise ValueError("uids and tokens must align")
        if len(set(uids)) != len(uids):
            raise ValueError("duplicate uid in one put() batch: a sequence can "
                             "advance at most one decode position per engine step")
        if not self.can_schedule(uids, [len(t) for t in tokens]):
            raise RuntimeError("cannot schedule batch: KV pool exhausted or length cap hit "
                               "(check query()/free_blocks, flush finished sequences)")
        bs = self.cache.block_size
        prefills: List[Tuple[SequenceDescriptor, List[int]]] = []
        extends: List[Tuple[SequenceDescriptor, List[int]]] = []
        new_uids = []
        for uid, toks in zip(uids, tokens):
            toks = list(map(int, toks))
            if uid in self._seqs:
                if toks:
                    extends.append((self._seqs[uid], toks))
            else:
                if not toks:
                    raise ValueError(f"new uid {uid} with no tokens")
                new_uids.append(uid)
                desc = SequenceDescriptor(uid=uid)
                prefills.append((desc, toks))
        # Admission check BEFORE any KV mutation: a rejected put() must leave
        # the engine untouched so the caller can retry it verbatim.
        if len(extends) > self.config.max_batch_size:
            raise ValueError(f"decode batch {len(extends)} exceeds max_batch_size "
                             f"{self.config.max_batch_size} (raise it in the inference config)")
        for uid, (desc, _) in zip(new_uids, prefills):
            self._seqs[uid] = desc

        for desc, toks in prefills:
            T = len(toks)
            self._ensure_blocks(desc, T)
            tpad = max(bs, _bucket(T, minimum=bs))
            tpad = min(-(-tpad // bs) * bs, self.config.max_seq_len)
            nblk_pad = tpad // bs
            ids = np.zeros((1, tpad), np.int32)
            ids[0, :T] = toks
            btable = np.full((nblk_pad,), self._scratch, np.int32)
            btable[:len(desc.blocks)] = desc.blocks[:nblk_pad]
            fn = self._paged_prefill_fn(tpad)
            self.cache, logits = fn(self.params, self.cache, ids,
                                    np.array([T], np.int32), btable)
            desc.seen_tokens = T
            desc.last_logits = np.asarray(logits[0])

        # multi-token extension = repeated batched single-token decode
        # (chunked-prefill analog; reference schedules these as ragged atoms)
        while any(toks for _, toks in extends):
            batch = [(d, toks.pop(0)) for d, toks in extends if toks]
            for d, _ in batch:
                self._ensure_blocks(d, d.seen_tokens + 1)
            B = _bucket(len(batch), minimum=1)
            tok = np.zeros((B,), np.int32)
            pos = np.zeros((B,), np.int32)
            tables = np.full((B, self._max_blocks), self._scratch, np.int32)
            for i, (d, t) in enumerate(batch):
                tok[i], pos[i] = t, d.seen_tokens
                tables[i] = self._table(d)
            fn = self._paged_decode_fn(B)
            self.cache, logits = fn(self.params, self.cache, tok, pos, tables)
            logits = np.asarray(logits)
            for i, (d, _) in enumerate(batch):
                d.seen_tokens += 1
                d.last_logits = logits[i]

        return np.stack([self._seqs[uid].last_logits for uid in uids])

    def flush(self, uids: Sequence[int]) -> None:
        """Free all state for finished sequences (engine_v2.py:242)."""
        for uid in uids:
            desc = self._seqs.pop(uid, None)
            if desc is None:
                raise ValueError(f"unknown uid {uid}")
            self.allocator.free(desc.blocks)
