"""Token sampling inside jit (reference leaves sampling to the host caller;
``inference/v2/engine_v2.py:107`` returns logits — we additionally provide
fused on-device sampling so the decode loop never leaves the chip).

All samplers take fp32 logits [B, V] and return int32 tokens [B].
"""

from __future__ import annotations



def greedy(logits):
    import jax.numpy as jnp

    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample(logits, rng, temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0):
    """Temperature / top-k / top-p (nucleus) sampling.

    ``top_k`` is static (compiled in); temperature and top_p are traced.
    """
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    neg = jnp.finfo(jnp.float32).min

    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)

    # nucleus: keep the smallest prefix of the sorted distribution with
    # cumulative prob >= top_p (always keep the argmax).
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # first token always kept, even at top_p == 0 (cum - probs == 0 there)
    keep = cum - probs < jnp.maximum(top_p, 1e-6)
    cutoff = jnp.where(keep, sorted_logits, jnp.inf).min(axis=-1, keepdims=True)
    logits = jnp.where(logits < cutoff, neg, logits)

    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_or_greedy(logits, rng, temperature: float, top_k: int = 0, top_p: float = 1.0):
    """Static dispatch: temperature == 0 (python float) means greedy."""
    if temperature == 0.0:
        return greedy(logits)
    return sample(logits, rng, temperature=temperature, top_k=top_k, top_p=top_p)


def seeded_tokens(logits, seeds, positions, temperature, top_k, top_p,
                  mask=None):
    """Fused per-row seeded sampler for the one-dispatch serving step
    (ISSUE 16). EVERY parameter is a traced per-row operand — one
    compiled program serves any mix of greedy and sampled rows, so the
    warmed server's program-key ladder never grows with sampling config.

    ``logits`` ``[..., V]`` (any float dtype), and per-row ``[...]``:
    ``seeds`` (uint32-range ints), ``positions`` (the ABSOLUTE sequence
    index of the token being emitted), ``temperature`` (0 = greedy),
    ``top_k`` (0 = off), ``top_p`` (1 = off). ``mask`` is an optional
    ``[..., V]`` bool (True = allowed) constrained-decoding plane,
    respected by greedy and sampled rows alike. Returns int32 tokens
    ``[...]``.

    Gumbel-max coupling: the sampled token is
    ``argmax(filtered_logits / T + gumbel(fold_in(PRNGKey(seed), pos)))``
    — a pure function of ``(seed, position, distribution)``. Because the
    key depends only on the request's seed and the token's absolute
    index, the chain is invariant to batch composition, tick boundaries,
    preemption/failover replay, and speculative verification (which
    evaluates the SAME function at the same positions); at temperature 0
    it degenerates to plain argmax, bit-identical to the greedy path.
    """
    import jax
    import jax.numpy as jnp

    V = logits.shape[-1]
    lead = logits.shape[:-1]
    flat = logits.reshape(-1, V).astype(jnp.float32)
    neg = jnp.finfo(jnp.float32).min
    if mask is not None:
        flat = jnp.where(mask.reshape(-1, V), flat, neg)
    T = temperature.reshape(-1).astype(jnp.float32)
    tk = top_k.reshape(-1).astype(jnp.int32)
    tp = top_p.reshape(-1).astype(jnp.float32)
    greedy_tok = jnp.argmax(flat, axis=-1).astype(jnp.int32)

    def _noise(seed, pos):
        key = jax.random.fold_in(
            jax.random.PRNGKey(seed.astype(jnp.uint32)),
            pos.astype(jnp.uint32))
        return jax.random.gumbel(key, (V,), jnp.float32)

    g = jax.vmap(_noise)(seeds.reshape(-1), positions.reshape(-1))
    # top-k/top-p filtering in sorted space (the cutoff idiom sample()
    # uses): compute the smallest kept logit per row and drop below it
    Tsafe = jnp.maximum(T, 1e-6)[:, None]
    svals = jax.lax.top_k(flat, V)[0]                 # descending
    probs = jax.nn.softmax(svals / Tsafe, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    j = jnp.arange(V)[None, :]
    # rank 0 always kept (cum - probs == 0 there), so the filter can
    # never empty a row even at top_k == 1 or vanishing top_p
    keep = (cum - probs) < jnp.maximum(tp, 1e-6)[:, None]
    keep &= j < jnp.where(tk > 0, tk, V)[:, None]
    cutoff = jnp.where(keep, svals, jnp.inf).min(axis=-1, keepdims=True)
    filt = jnp.where(flat >= cutoff, flat, neg)
    sampled = jnp.argmax(filt / Tsafe + g, axis=-1).astype(jnp.int32)
    out = jnp.where(T > 0.0, sampled, greedy_tok)
    return out.reshape(lead)
