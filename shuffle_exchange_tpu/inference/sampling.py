"""Token sampling inside jit (reference leaves sampling to the host caller;
``inference/v2/engine_v2.py:107`` returns logits — we additionally provide
fused on-device sampling so the decode loop never leaves the chip).

All samplers take fp32 logits [B, V] and return int32 tokens [B].
"""

from __future__ import annotations



def greedy(logits):
    import jax.numpy as jnp

    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample(logits, rng, temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0):
    """Temperature / top-k / top-p (nucleus) sampling.

    ``top_k`` is static (compiled in); temperature and top_p are traced.
    """
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    neg = jnp.finfo(jnp.float32).min

    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)

    # nucleus: keep the smallest prefix of the sorted distribution with
    # cumulative prob >= top_p (always keep the argmax).
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # first token always kept, even at top_p == 0 (cum - probs == 0 there)
    keep = cum - probs < jnp.maximum(top_p, 1e-6)
    cutoff = jnp.where(keep, sorted_logits, jnp.inf).min(axis=-1, keepdims=True)
    logits = jnp.where(logits < cutoff, neg, logits)

    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_or_greedy(logits, rng, temperature: float, top_k: int = 0, top_p: float = 1.0):
    """Static dispatch: temperature == 0 (python float) means greedy."""
    if temperature == 0.0:
        return greedy(logits)
    return sample(logits, rng, temperature=temperature, top_k=top_k, top_p=top_p)
