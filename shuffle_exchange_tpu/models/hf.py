"""HuggingFace model import: config + weight conversion into the model zoo.

Capability parity with the reference's per-architecture support surface —
the v1 injection policies/containers (``module_inject/containers/`` gpt2,
llama/llama2, opt, …) and the v2 engine factory's arch dispatch
(``inference/v2/engine_factory.py:32,69``: llama, mistral, mixtral, opt,
phi/phi3, qwen/qwen2, falcon). A reference user points the engine at an HF
model; here ``from_hf(model_or_path)`` returns ``(Transformer, params)``
ready for ``sxt.initialize`` / ``init_inference``.

TPU-native shape: instead of swapping nn.Modules layer by layer, the HF
state dict is re-laid-out once into the zoo Transformer's stacked-scanned
format (per-layer weights stacked on a leading L dim; torch Linear weights
transposed to [in, out]); tensor-parallel sharding then comes from
``Transformer.partition_specs`` (the AutoTP analog) with no per-arch
kernels. Conversions accept a transformers model object, a state-dict, or
a local checkpoint directory — no network access is assumed.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ..utils.logging import logger
from .transformer import Transformer, TransformerConfig

# HF architecture class name -> family key
_ARCH_FAMILIES = {
    "LlamaForCausalLM": "llama",
    "MistralForCausalLM": "llama",        # same wiring, different defaults
    "Qwen2ForCausalLM": "qwen2",
    "MixtralForCausalLM": "mixtral",
    "GPT2LMHeadModel": "gpt2",
    "OPTForCausalLM": "opt",
    "Phi3ForCausalLM": "phi3",
    "Qwen2MoeForCausalLM": "qwen2moe",
    "GPTJForCausalLM": "gptj",
    "GPTNeoXForCausalLM": "gptneox",
    "FalconForCausalLM": "falcon",
    "RWForCausalLM": "falcon",            # legacy tiiuae checkpoints
    "BloomForCausalLM": "bloom",
    "BertForMaskedLM": "bert",
    "BertForPreTraining": "bert",
    "BertModel": "bert",
    "DistilBertForMaskedLM": "distilbert",
    "GPTNeoForCausalLM": "gptneo",
    "InternLMForCausalLM": "internlm",
    "InternLM2ForCausalLM": "internlm2",
}


_MODEL_TYPE_FAMILIES = {"llama": "llama", "mistral": "llama", "qwen2": "qwen2",
                        "mixtral": "mixtral", "gpt2": "gpt2", "opt": "opt",
                        "phi3": "phi3", "gptj": "gptj", "gpt_neox": "gptneox",
                        "falcon": "falcon", "bloom": "bloom", "qwen2_moe": "qwen2moe",
                        "bert": "bert", "distilbert": "distilbert",
                        "gpt_neo": "gptneo", "internlm": "internlm",
                        "internlm2": "internlm2", "megatron": "megatron",
                        "megatron-gpt": "megatron", "megatron_gpt": "megatron"}


def _family(cfg: Dict[str, Any]) -> str:
    archs = cfg.get("architectures") or []
    family = next((_ARCH_FAMILIES[a] for a in archs if a in _ARCH_FAMILIES), None)
    if family is None:
        family = _MODEL_TYPE_FAMILIES.get(cfg.get("model_type", ""))
    if family is None:
        raise ValueError(f"Unsupported HF architecture {archs or cfg.get('model_type')!r}; "
                         f"supported: {sorted(set(_ARCH_FAMILIES.values()))}")
    return family


def config_from_hf(hf_config) -> TransformerConfig:
    """Map an HF config object/dict to a TransformerConfig."""
    cfg = hf_config if isinstance(hf_config, dict) else hf_config.to_dict()
    family = _family(cfg)

    if family == "gpt2":
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["n_embd"], n_layers=cfg["n_layer"],
            n_heads=cfg["n_head"], max_seq_len=cfg.get("n_positions", 1024),
            activation=cfg.get("activation_function", "gelu_new"),
            norm="layernorm", position="learned",
            norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
            attn_qkv_bias=True, attn_out_bias=True, tie_embeddings=True)
    if family == "opt":
        if cfg.get("word_embed_proj_dim") not in (None, cfg["hidden_size"]):
            raise ValueError(
                "OPT with word_embed_proj_dim != hidden_size (project_in/out, e.g. "
                "opt-350m) is not supported by this conversion")
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["hidden_size"],
            n_layers=cfg["num_hidden_layers"], n_heads=cfg["num_attention_heads"],
            d_ff=cfg.get("ffn_dim"), max_seq_len=cfg.get("max_position_embeddings", 2048),
            activation=cfg.get("activation_function", "relu"),
            norm="layernorm", position="learned", pos_offset=2,
            attn_qkv_bias=cfg.get("enable_bias", True), attn_out_bias=cfg.get("enable_bias", True),
            tie_embeddings=cfg.get("tie_word_embeddings", True))
    if family == "gptj":
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["n_embd"], n_layers=cfg["n_layer"],
            n_heads=cfg["n_head"], max_seq_len=cfg.get("n_positions", 2048),
            activation=cfg.get("activation_function", "gelu_new"),
            norm="layernorm", position="rope", rope_theta=10000.0,
            rotary_dim=cfg.get("rotary_dim") or 0, rope_interleaved=True,
            parallel_block=True, parallel_shared_ln=True,
            norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
            tie_embeddings=cfg.get("tie_word_embeddings", False),
            unembed_bias=True)
    if family == "gptneox":
        head_dim = cfg["hidden_size"] // cfg["num_attention_heads"]
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["hidden_size"],
            n_layers=cfg["num_hidden_layers"], n_heads=cfg["num_attention_heads"],
            d_ff=cfg.get("intermediate_size"),
            max_seq_len=cfg.get("max_position_embeddings", 2048),
            activation=cfg.get("hidden_act", "gelu"),
            norm="layernorm", position="rope",
            rope_theta=float(cfg.get("rotary_emb_base", 10000.0)),
            rotary_dim=int(cfg.get("rotary_pct", 1.0) * head_dim),
            parallel_block=cfg.get("use_parallel_residual", True),
            attn_qkv_bias=cfg.get("attention_bias", True),
            attn_out_bias=cfg.get("attention_bias", True),
            norm_eps=cfg.get("layer_norm_eps", 1e-5),
            tie_embeddings=cfg.get("tie_word_embeddings", False))
    if family == "falcon":
        H = cfg["num_attention_heads"]
        new_arch = cfg.get("new_decoder_architecture", False)
        kv = (cfg.get("num_kv_heads") or H) if new_arch else (
            1 if cfg.get("multi_query", True) else H)
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["hidden_size"],
            n_layers=cfg["num_hidden_layers"], n_heads=H, n_kv_heads=kv,
            max_seq_len=cfg.get("max_position_embeddings", 2048),
            activation="gelu", norm="layernorm",
            position="alibi" if cfg.get("alibi", False) else "rope",
            # falcon baddbmm uses beta = inv_norm_factor: alibi is scaled by
            # 1/sqrt(Dh) (bloom's beta is 1.0 — unscaled)
            alibi_slope_scale=(cfg["hidden_size"] // H) ** -0.5,
            d_ff=cfg.get("ffn_hidden_size"),
            rope_theta=float(cfg.get("rope_theta", 10000.0)),
            parallel_block=cfg.get("parallel_attn", True),
            parallel_shared_ln=cfg.get("parallel_attn", True) and not new_arch,
            attn_qkv_bias=cfg.get("bias", False), attn_out_bias=cfg.get("bias", False),
            mlp_bias=cfg.get("bias", False),
            norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
            tie_embeddings=cfg.get("tie_word_embeddings", True))
    if family == "bert":
        # encoder family (reference module_inject/containers/bert.py):
        # post-LN blocks, bidirectional attention, token types, MLM head
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["hidden_size"],
            n_layers=cfg["num_hidden_layers"], n_heads=cfg["num_attention_heads"],
            d_ff=cfg.get("intermediate_size"),
            max_seq_len=cfg.get("max_position_embeddings", 512),
            activation=cfg.get("hidden_act", "gelu"),
            norm="layernorm", position="learned",
            norm_eps=cfg.get("layer_norm_eps", 1e-12),
            attn_qkv_bias=True, attn_out_bias=True, tie_embeddings=True,
            causal=False, post_ln=True, embed_ln=True, mlm_head=True,
            type_vocab_size=cfg.get("type_vocab_size", 2))
    if family == "distilbert":
        # distil_bert.py container: bert minus token types, untied projector
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["dim"],
            n_layers=cfg["n_layers"], n_heads=cfg["n_heads"],
            d_ff=cfg.get("hidden_dim"),
            max_seq_len=cfg.get("max_position_embeddings", 512),
            activation=cfg.get("activation", "gelu"),
            norm="layernorm", position="learned", norm_eps=1e-12,
            attn_qkv_bias=True, attn_out_bias=True, tie_embeddings=False,
            causal=False, post_ln=True, embed_ln=True, mlm_head=True)
    if family == "gptneo":
        # containers/gptneo.py: unscaled attention, alternating
        # global/local layers with a trailing window
        pattern = tuple(cfg.get("attention_layers")
                        or [t for grp in cfg.get("attention_types", [[["global"], 1]])
                            for t in grp[0] * grp[1]])
        has_local = "local" in pattern
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["hidden_size"],
            n_layers=cfg["num_layers"], n_heads=cfg["num_heads"],
            d_ff=cfg.get("intermediate_size") or 4 * cfg["hidden_size"],
            max_seq_len=cfg.get("max_position_embeddings", 2048),
            activation=cfg.get("activation_function", "gelu_new"),
            norm="layernorm", position="learned",
            norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
            attn_qkv_bias=False, attn_out_bias=True, tie_embeddings=True,
            attn_scale=1.0,
            # all-global checkpoints keep the flash path; the window mask
            # needs score-level access only when a local layer exists
            local_attention_window=(cfg.get("window_size", 256) if has_local else 0),
            attention_pattern=(pattern if has_local else ()),
            attention_impl=("reference" if has_local else "auto"))
    if family == "megatron":
        # Megatron-LM GPT (reference module_inject/containers/
        # megatron_gpt.py + megatron_gpt_moe.py): GPT-2-style blocks with
        # the fused query_key_value projection; config uses Megatron arg
        # names (no HF config class exists)
        D, H = cfg["hidden_size"], cfg["num_attention_heads"]
        ne = cfg.get("num_experts", 0) or 0
        if isinstance(ne, (list, tuple)):     # Megatron --num-experts is nargs='+'
            ne = ne[0] if ne else 0
        # --use-rotary-position-embeddings (newer Megatron recipes):
        # rope replaces the learned position table
        rotary = bool(cfg.get("use_rotary_position_embeddings", False)
                      or str(cfg.get("position_embedding_type", "learned")
                             ).lower() in ("rope", "rotary"))
        c = TransformerConfig(
            vocab_size=cfg.get("padded_vocab_size") or cfg["vocab_size"],
            d_model=D, n_layers=cfg["num_layers"], n_heads=H,
            d_ff=cfg.get("ffn_hidden_size") or 4 * D,
            max_seq_len=cfg.get("max_position_embeddings", 2048),
            activation="gelu", norm="layernorm",
            position="rope" if rotary else "learned",
            rope_theta=float(cfg.get("rotary_base", 10000.0)),
            # --rotary-percent < 1 ropes only the leading fraction of Dh
            rotary_dim=(int((D // H) * cfg["rotary_percent"])
                        if rotary and cfg.get("rotary_percent", 1.0) < 1.0
                        else 0),
            attn_qkv_bias=True, attn_out_bias=True,
            tie_embeddings=not cfg.get("untie_embeddings_and_output_weights", False),
            norm_eps=cfg.get("layernorm_epsilon", 1e-5),
            n_experts=int(ne),
            moe_top_k=int(cfg.get("moe_top_k", cfg.get("topk", 2)) or 2))
        return c
    if family == "bloom":
        return TransformerConfig(
            vocab_size=cfg["vocab_size"], d_model=cfg["hidden_size"],
            n_layers=cfg["n_layer"], n_heads=cfg["n_head"],
            max_seq_len=cfg.get("seq_length", 2048),
            activation="gelu_new",   # BloomGelu is the tanh approximation
            norm="layernorm", position="alibi", embed_ln=True,
            attn_qkv_bias=True, attn_out_bias=True,
            norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
            tie_embeddings=cfg.get("tie_word_embeddings", True))
    # rope/rmsnorm families
    common = dict(
        vocab_size=cfg["vocab_size"], d_model=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"], n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads"),
        d_ff=cfg.get("intermediate_size"),
        max_seq_len=cfg.get("max_position_embeddings", 4096),
        activation="swiglu", norm="rmsnorm", position="rope",
        rope_theta=float(cfg.get("rope_theta", 10000.0)),
        norm_eps=cfg.get("rms_norm_eps", 1e-6),
        tie_embeddings=cfg.get("tie_word_embeddings", False))
    if family == "qwen2":
        return TransformerConfig(attn_qkv_bias=True, **common)
    if family in ("internlm", "internlm2"):
        # internlm v1 = llama wiring + optional qkvo biases
        # (module_inject/containers/internlm.py); v2 fuses wqkv
        bias = bool(cfg.get("bias", family == "internlm"))
        return TransformerConfig(attn_qkv_bias=bias, attn_out_bias=bias,
                                 **common)
    if family == "qwen2moe":
        if cfg.get("decoder_sparse_step", 1) != 1 or cfg.get("mlp_only_layers"):
            raise ValueError("qwen2-moe with dense interleaved layers "
                             "(decoder_sparse_step != 1 / mlp_only_layers) is not supported")
        common["d_ff"] = cfg.get("moe_intermediate_size")
        return TransformerConfig(
            attn_qkv_bias=True,
            n_experts=cfg["num_experts"], moe_top_k=cfg.get("num_experts_per_tok", 4),
            moe_norm_topk=bool(cfg.get("norm_topk_prob", False)),
            moe_shared_expert_ff=cfg.get("shared_expert_intermediate_size", 0),
            aux_loss_coef=cfg.get("router_aux_loss_coef", 0.001),
            capacity_factor=float(cfg.get("capacity_factor", 8.0)), **common)
    if family == "mixtral":
        return TransformerConfig(
            n_experts=cfg["num_local_experts"], moe_top_k=cfg.get("num_experts_per_tok", 2),
            aux_loss_coef=cfg.get("router_aux_loss_coef", 0.02),
            # generous capacity: HF routes without drops
            capacity_factor=float(cfg.get("capacity_factor", 8.0)), **common)
    return TransformerConfig(**common)  # llama / mistral / phi3


# ---------------------------------------------------------------------------
# weight conversion
# ---------------------------------------------------------------------------


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().to("cpu")
        try:
            return t.numpy().astype(np.float32)
        except TypeError:
            return t.float().numpy()
    return np.asarray(t, dtype=np.float32)


def _stack(sd: Dict[str, Any], fmt: str, L: int, transpose: bool = False) -> np.ndarray:
    mats = [_np(sd[fmt.format(i)]) for i in range(L)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


def params_from_state_dict(sd: Dict[str, Any], config: TransformerConfig,
                           family: str, megatron_v2: bool = True) -> Dict[str, Any]:
    """Re-lay an HF state dict into the zoo Transformer's stacked format."""
    L = config.n_layers
    sd = {k.removeprefix("transformer.").removeprefix("model.")
           .removeprefix("gpt_neox.").removeprefix("bert.")
           .removeprefix("distilbert."): v
          for k, v in sd.items()}
    p: Dict[str, Any] = {}

    if family == "gpt2":
        p["embed"] = _np(sd["wte.weight"])
        p["pos_embed"] = _np(sd["wpe.weight"])
        # GPT-2 Conv1D stores [in, out] — our layout already; fused qkv split.
        qkv = _stack(sd, "h.{}.attn.c_attn.weight", L)          # [L, D, 3D]
        D = config.d_model
        p_layers = {
            "ln1_w": _stack(sd, "h.{}.ln_1.weight", L), "ln1_b": _stack(sd, "h.{}.ln_1.bias", L),
            "ln2_w": _stack(sd, "h.{}.ln_2.weight", L), "ln2_b": _stack(sd, "h.{}.ln_2.bias", L),
            "wq": qkv[:, :, :D], "wk": qkv[:, :, D:2 * D], "wv": qkv[:, :, 2 * D:],
            "wo": _stack(sd, "h.{}.attn.c_proj.weight", L),
            "b_o": _stack(sd, "h.{}.attn.c_proj.bias", L),
            "w_up": _stack(sd, "h.{}.mlp.c_fc.weight", L),
            "b_up": _stack(sd, "h.{}.mlp.c_fc.bias", L),
            "w_down": _stack(sd, "h.{}.mlp.c_proj.weight", L),
            "b_down": _stack(sd, "h.{}.mlp.c_proj.bias", L),
        }
        qkv_b = _stack(sd, "h.{}.attn.c_attn.bias", L)
        p_layers["b_q"], p_layers["b_k"], p_layers["b_v"] = (
            qkv_b[:, :D], qkv_b[:, D:2 * D], qkv_b[:, 2 * D:])
        p["layers"] = p_layers
        p["ln_f_w"], p["ln_f_b"] = _np(sd["ln_f.weight"]), _np(sd["ln_f.bias"])
        return p

    if family == "opt":
        dec = "decoder."
        p["embed"] = _np(sd[dec + "embed_tokens.weight"])
        p["pos_embed"] = _np(sd[dec + "embed_positions.weight"])
        p["layers"] = {
            "ln1_w": _stack(sd, dec + "layers.{}.self_attn_layer_norm.weight", L),
            "ln1_b": _stack(sd, dec + "layers.{}.self_attn_layer_norm.bias", L),
            "ln2_w": _stack(sd, dec + "layers.{}.final_layer_norm.weight", L),
            "ln2_b": _stack(sd, dec + "layers.{}.final_layer_norm.bias", L),
            "wq": _stack(sd, dec + "layers.{}.self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, dec + "layers.{}.self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, dec + "layers.{}.self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, dec + "layers.{}.self_attn.out_proj.weight", L, transpose=True),
            "b_q": _stack(sd, dec + "layers.{}.self_attn.q_proj.bias", L),
            "b_k": _stack(sd, dec + "layers.{}.self_attn.k_proj.bias", L),
            "b_v": _stack(sd, dec + "layers.{}.self_attn.v_proj.bias", L),
            "b_o": _stack(sd, dec + "layers.{}.self_attn.out_proj.bias", L),
            "w_up": _stack(sd, dec + "layers.{}.fc1.weight", L, transpose=True),
            "b_up": _stack(sd, dec + "layers.{}.fc1.bias", L),
            "w_down": _stack(sd, dec + "layers.{}.fc2.weight", L, transpose=True),
            "b_down": _stack(sd, dec + "layers.{}.fc2.bias", L),
        }
        p["ln_f_w"] = _np(sd[dec + "final_layer_norm.weight"])
        p["ln_f_b"] = _np(sd[dec + "final_layer_norm.bias"])
        if not config.tie_embeddings:
            p["unembed"] = _np(sd["lm_head.weight"]).T
        return p

    if family == "gptj":
        p["embed"] = _np(sd["wte.weight"])
        p["layers"] = {
            "ln1_w": _stack(sd, "h.{}.ln_1.weight", L),
            "ln1_b": _stack(sd, "h.{}.ln_1.bias", L),
            # parallel_shared_ln: no ln2 in GPT-J
            "wq": _stack(sd, "h.{}.attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, "h.{}.attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, "h.{}.attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, "h.{}.attn.out_proj.weight", L, transpose=True),
            "w_up": _stack(sd, "h.{}.mlp.fc_in.weight", L, transpose=True),
            "b_up": _stack(sd, "h.{}.mlp.fc_in.bias", L),
            "w_down": _stack(sd, "h.{}.mlp.fc_out.weight", L, transpose=True),
            "b_down": _stack(sd, "h.{}.mlp.fc_out.bias", L),
        }
        p["ln_f_w"], p["ln_f_b"] = _np(sd["ln_f.weight"]), _np(sd["ln_f.bias"])
        p["unembed"] = _np(sd["lm_head.weight"]).T
        p["unembed_b"] = _np(sd["lm_head.bias"])
        return p

    if family in ("gptneox", "bloom"):
        # fused QKV with per-head-interleaved rows: weight [3D, D] is
        # (H, 3, Dh) on the output dim (GPTNeoXAttention/_split_heads,
        # BloomAttention view(B,T,H,3,Dh))
        H, Dh = config.n_heads, config.head_dim
        D = config.d_model

        def split_qkv(fmt, bias=False):
            w = _stack(sd, fmt, L)                               # [L, 3D(out)] or [L, 3D, D]
            if bias:
                w = w.reshape(L, H, 3, Dh)
                return w[:, :, 0].reshape(L, H * Dh), w[:, :, 1].reshape(L, H * Dh), \
                    w[:, :, 2].reshape(L, H * Dh)
            w = w.reshape(L, H, 3, Dh, D)
            q = w[:, :, 0].reshape(L, H * Dh, D).transpose(0, 2, 1)
            k = w[:, :, 1].reshape(L, H * Dh, D).transpose(0, 2, 1)
            v = w[:, :, 2].reshape(L, H * Dh, D).transpose(0, 2, 1)
            return q, k, v

        if family == "gptneox":
            pre = "layers.{}."
            p["embed"] = _np(sd["embed_in.weight"])
            wq, wk, wv = split_qkv(pre + "attention.query_key_value.weight")
            bq, bk, bv = split_qkv(pre + "attention.query_key_value.bias", bias=True)
            p["layers"] = {
                "ln1_w": _stack(sd, pre + "input_layernorm.weight", L),
                "ln1_b": _stack(sd, pre + "input_layernorm.bias", L),
                "ln2_w": _stack(sd, pre + "post_attention_layernorm.weight", L),
                "ln2_b": _stack(sd, pre + "post_attention_layernorm.bias", L),
                "wq": wq, "wk": wk, "wv": wv, "b_q": bq, "b_k": bk, "b_v": bv,
                "wo": _stack(sd, pre + "attention.dense.weight", L, transpose=True),
                "b_o": _stack(sd, pre + "attention.dense.bias", L),
                "w_up": _stack(sd, pre + "mlp.dense_h_to_4h.weight", L, transpose=True),
                "b_up": _stack(sd, pre + "mlp.dense_h_to_4h.bias", L),
                "w_down": _stack(sd, pre + "mlp.dense_4h_to_h.weight", L, transpose=True),
                "b_down": _stack(sd, pre + "mlp.dense_4h_to_h.bias", L),
            }
            p["ln_f_w"] = _np(sd["final_layer_norm.weight"])
            p["ln_f_b"] = _np(sd["final_layer_norm.bias"])
            if not config.tie_embeddings:
                p["unembed"] = _np(sd["embed_out.weight"]).T
            return p

        pre = "h.{}."
        p["embed"] = _np(sd["word_embeddings.weight"])
        p["embed_ln_w"] = _np(sd["word_embeddings_layernorm.weight"])
        p["embed_ln_b"] = _np(sd["word_embeddings_layernorm.bias"])
        wq, wk, wv = split_qkv(pre + "self_attention.query_key_value.weight")
        bq, bk, bv = split_qkv(pre + "self_attention.query_key_value.bias", bias=True)
        p["layers"] = {
            "ln1_w": _stack(sd, pre + "input_layernorm.weight", L),
            "ln1_b": _stack(sd, pre + "input_layernorm.bias", L),
            "ln2_w": _stack(sd, pre + "post_attention_layernorm.weight", L),
            "ln2_b": _stack(sd, pre + "post_attention_layernorm.bias", L),
            "wq": wq, "wk": wk, "wv": wv, "b_q": bq, "b_k": bk, "b_v": bv,
            "wo": _stack(sd, pre + "self_attention.dense.weight", L, transpose=True),
            "b_o": _stack(sd, pre + "self_attention.dense.bias", L),
            "w_up": _stack(sd, pre + "mlp.dense_h_to_4h.weight", L, transpose=True),
            "b_up": _stack(sd, pre + "mlp.dense_h_to_4h.bias", L),
            "w_down": _stack(sd, pre + "mlp.dense_4h_to_h.weight", L, transpose=True),
            "b_down": _stack(sd, pre + "mlp.dense_4h_to_h.bias", L),
        }
        p["ln_f_w"], p["ln_f_b"] = _np(sd["ln_f.weight"]), _np(sd["ln_f.bias"])
        return p

    if family == "falcon":
        H, KV, Dh = config.n_heads, config.kv_heads, config.head_dim
        D = config.d_model
        G = H // KV
        pre = "h.{}."
        p["embed"] = _np(sd["word_embeddings.weight"])
        # fused-QKV layout (modeling_falcon._split_heads): new arch groups
        # [KV, G q + 1 k + 1 v]; old multi_query is the KV==1 case of the
        # same grouping; old multi-head (falcon-rw) interleaves [H, 3, Dh].
        grouped_arch = config.parallel_block and not config.parallel_shared_ln

        def split_qkv_w(w):                      # w [L, out, D]
            if grouped_arch or KV == 1:
                g = w.reshape(L, KV, G + 2, Dh, D)
                return (g[:, :, :G].reshape(L, H * Dh, D).transpose(0, 2, 1),
                        g[:, :, G].reshape(L, KV * Dh, D).transpose(0, 2, 1),
                        g[:, :, G + 1].reshape(L, KV * Dh, D).transpose(0, 2, 1))
            g = w.reshape(L, H, 3, Dh, D)
            return (g[:, :, 0].reshape(L, H * Dh, D).transpose(0, 2, 1),
                    g[:, :, 1].reshape(L, H * Dh, D).transpose(0, 2, 1),
                    g[:, :, 2].reshape(L, H * Dh, D).transpose(0, 2, 1))

        def split_qkv_b(b):                      # b [L, out]
            if grouped_arch or KV == 1:
                g = b.reshape(L, KV, G + 2, Dh)
                return (g[:, :, :G].reshape(L, H * Dh), g[:, :, G].reshape(L, KV * Dh),
                        g[:, :, G + 1].reshape(L, KV * Dh))
            g = b.reshape(L, H, 3, Dh)
            return (g[:, :, 0].reshape(L, H * Dh), g[:, :, 1].reshape(L, H * Dh),
                    g[:, :, 2].reshape(L, H * Dh))

        wq, wk, wv = split_qkv_w(_stack(sd, pre + "self_attention.query_key_value.weight", L))
        layers = {
            "wq": wq, "wk": wk, "wv": wv,
            "wo": _stack(sd, pre + "self_attention.dense.weight", L, transpose=True),
            "w_up": _stack(sd, pre + "mlp.dense_h_to_4h.weight", L, transpose=True),
            "w_down": _stack(sd, pre + "mlp.dense_4h_to_h.weight", L, transpose=True),
        }
        if config.attn_qkv_bias:   # falcon-rw: bias=True
            layers["b_q"], layers["b_k"], layers["b_v"] = split_qkv_b(
                _stack(sd, pre + "self_attention.query_key_value.bias", L))
        if config.attn_out_bias:
            layers["b_o"] = _stack(sd, pre + "self_attention.dense.bias", L)
        if config.mlp_bias:
            layers["b_up"] = _stack(sd, pre + "mlp.dense_h_to_4h.bias", L)
            layers["b_down"] = _stack(sd, pre + "mlp.dense_4h_to_h.bias", L)
        if grouped_arch:
            # new arch (falcon-40b style): two parallel norms
            layers["ln1_w"] = _stack(sd, pre + "ln_attn.weight", L)
            layers["ln1_b"] = _stack(sd, pre + "ln_attn.bias", L)
            layers["ln2_w"] = _stack(sd, pre + "ln_mlp.weight", L)
            layers["ln2_b"] = _stack(sd, pre + "ln_mlp.bias", L)
        else:
            layers["ln1_w"] = _stack(sd, pre + "input_layernorm.weight", L)
            layers["ln1_b"] = _stack(sd, pre + "input_layernorm.bias", L)
            if not config.parallel_block:   # sequential old arch (falcon-rw)
                layers["ln2_w"] = _stack(sd, pre + "post_attention_layernorm.weight", L)
                layers["ln2_b"] = _stack(sd, pre + "post_attention_layernorm.bias", L)
        p["layers"] = layers
        p["ln_f_w"], p["ln_f_b"] = _np(sd["ln_f.weight"]), _np(sd["ln_f.bias"])
        if not config.tie_embeddings:
            p["unembed"] = _np(sd["lm_head.weight"]).T
        return p

    if family == "bert":
        p["embed"] = _np(sd["embeddings.word_embeddings.weight"])
        p["pos_embed"] = _np(sd["embeddings.position_embeddings.weight"])
        p["token_type_embed"] = _np(sd["embeddings.token_type_embeddings.weight"])
        p["embed_ln_w"] = _np(sd["embeddings.LayerNorm.weight"])
        p["embed_ln_b"] = _np(sd["embeddings.LayerNorm.bias"])
        enc = "encoder.layer.{}."
        p["layers"] = {
            # post-LN: ln1 = attention-output LN, ln2 = ffn-output LN
            "ln1_w": _stack(sd, enc + "attention.output.LayerNorm.weight", L),
            "ln1_b": _stack(sd, enc + "attention.output.LayerNorm.bias", L),
            "ln2_w": _stack(sd, enc + "output.LayerNorm.weight", L),
            "ln2_b": _stack(sd, enc + "output.LayerNorm.bias", L),
            "wq": _stack(sd, enc + "attention.self.query.weight", L, transpose=True),
            "wk": _stack(sd, enc + "attention.self.key.weight", L, transpose=True),
            "wv": _stack(sd, enc + "attention.self.value.weight", L, transpose=True),
            "wo": _stack(sd, enc + "attention.output.dense.weight", L, transpose=True),
            "b_q": _stack(sd, enc + "attention.self.query.bias", L),
            "b_k": _stack(sd, enc + "attention.self.key.bias", L),
            "b_v": _stack(sd, enc + "attention.self.value.bias", L),
            "b_o": _stack(sd, enc + "attention.output.dense.bias", L),
            "w_up": _stack(sd, enc + "intermediate.dense.weight", L, transpose=True),
            "b_up": _stack(sd, enc + "intermediate.dense.bias", L),
            "w_down": _stack(sd, enc + "output.dense.weight", L, transpose=True),
            "b_down": _stack(sd, enc + "output.dense.bias", L),
        }
        if config.mlm_head:
            p["mlm_dense_w"] = _np(sd["cls.predictions.transform.dense.weight"]).T
            p["mlm_dense_b"] = _np(sd["cls.predictions.transform.dense.bias"])
            p["mlm_ln_w"] = _np(sd["cls.predictions.transform.LayerNorm.weight"])
            p["mlm_ln_b"] = _np(sd["cls.predictions.transform.LayerNorm.bias"])
            p["mlm_bias"] = _np(sd.get("cls.predictions.bias",
                                       sd.get("cls.predictions.decoder.bias")))
        return p

    if family == "distilbert":
        p["embed"] = _np(sd["embeddings.word_embeddings.weight"])
        p["pos_embed"] = _np(sd["embeddings.position_embeddings.weight"])
        p["embed_ln_w"] = _np(sd["embeddings.LayerNorm.weight"])
        p["embed_ln_b"] = _np(sd["embeddings.LayerNorm.bias"])
        tl = "transformer.layer.{}." if any(
            k.startswith("transformer.layer.") for k in sd) else "layer.{}."
        p["layers"] = {
            "ln1_w": _stack(sd, tl + "sa_layer_norm.weight", L),
            "ln1_b": _stack(sd, tl + "sa_layer_norm.bias", L),
            "ln2_w": _stack(sd, tl + "output_layer_norm.weight", L),
            "ln2_b": _stack(sd, tl + "output_layer_norm.bias", L),
            "wq": _stack(sd, tl + "attention.q_lin.weight", L, transpose=True),
            "wk": _stack(sd, tl + "attention.k_lin.weight", L, transpose=True),
            "wv": _stack(sd, tl + "attention.v_lin.weight", L, transpose=True),
            "wo": _stack(sd, tl + "attention.out_lin.weight", L, transpose=True),
            "b_q": _stack(sd, tl + "attention.q_lin.bias", L),
            "b_k": _stack(sd, tl + "attention.k_lin.bias", L),
            "b_v": _stack(sd, tl + "attention.v_lin.bias", L),
            "b_o": _stack(sd, tl + "attention.out_lin.bias", L),
            "w_up": _stack(sd, tl + "ffn.lin1.weight", L, transpose=True),
            "b_up": _stack(sd, tl + "ffn.lin1.bias", L),
            "w_down": _stack(sd, tl + "ffn.lin2.weight", L, transpose=True),
            "b_down": _stack(sd, tl + "ffn.lin2.bias", L),
        }
        p["mlm_dense_w"] = _np(sd["vocab_transform.weight"]).T
        p["mlm_dense_b"] = _np(sd["vocab_transform.bias"])
        p["mlm_ln_w"] = _np(sd["vocab_layer_norm.weight"])
        p["mlm_ln_b"] = _np(sd["vocab_layer_norm.bias"])
        p["unembed"] = _np(sd["vocab_projector.weight"]).T
        p["mlm_bias"] = _np(sd["vocab_projector.bias"])
        return p

    if family == "gptneo":
        p["embed"] = _np(sd["wte.weight"])
        p["pos_embed"] = _np(sd["wpe.weight"])
        p["layers"] = {
            "ln1_w": _stack(sd, "h.{}.ln_1.weight", L),
            "ln1_b": _stack(sd, "h.{}.ln_1.bias", L),
            "ln2_w": _stack(sd, "h.{}.ln_2.weight", L),
            "ln2_b": _stack(sd, "h.{}.ln_2.bias", L),
            "wq": _stack(sd, "h.{}.attn.attention.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, "h.{}.attn.attention.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, "h.{}.attn.attention.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, "h.{}.attn.attention.out_proj.weight", L, transpose=True),
            "b_o": _stack(sd, "h.{}.attn.attention.out_proj.bias", L),
            "w_up": _stack(sd, "h.{}.mlp.c_fc.weight", L, transpose=True),
            "b_up": _stack(sd, "h.{}.mlp.c_fc.bias", L),
            "w_down": _stack(sd, "h.{}.mlp.c_proj.weight", L, transpose=True),
            "b_down": _stack(sd, "h.{}.mlp.c_proj.bias", L),
        }
        p["ln_f_w"], p["ln_f_b"] = _np(sd["ln_f.weight"]), _np(sd["ln_f.bias"])
        return p

    if family == "internlm2":
        # fused wqkv, grouped per kv head: [KV, G + 2, Dh, D] with the G q
        # rows then k then v inside each group
        H, KV, Dh = config.n_heads, config.kv_heads, config.head_dim
        G = H // KV
        p["embed"] = _np(sd["tok_embeddings.weight"])
        wqkv = np.stack([_np(sd[f"layers.{i}.attention.wqkv.weight"]) for i in range(L)])
        wqkv = wqkv.reshape(L, KV, G + 2, Dh, config.d_model)
        wq = wqkv[:, :, :G].reshape(L, H * Dh, config.d_model)
        wk = wqkv[:, :, G].reshape(L, KV * Dh, config.d_model)
        wv = wqkv[:, :, G + 1].reshape(L, KV * Dh, config.d_model)
        p["layers"] = {
            "ln1_w": _stack(sd, "layers.{}.attention_norm.weight", L),
            "ln2_w": _stack(sd, "layers.{}.ffn_norm.weight", L),
            "wq": wq.transpose(0, 2, 1), "wk": wk.transpose(0, 2, 1),
            "wv": wv.transpose(0, 2, 1),
            "wo": _stack(sd, "layers.{}.attention.wo.weight", L, transpose=True),
            "w_gate": _stack(sd, "layers.{}.feed_forward.w1.weight", L, transpose=True),
            "w_up": _stack(sd, "layers.{}.feed_forward.w3.weight", L, transpose=True),
            "w_down": _stack(sd, "layers.{}.feed_forward.w2.weight", L, transpose=True),
        }
        if config.attn_qkv_bias:
            bqkv = np.stack([_np(sd[f"layers.{i}.attention.wqkv.bias"]) for i in range(L)])
            bqkv = bqkv.reshape(L, KV, G + 2, Dh)
            p["layers"]["b_q"] = bqkv[:, :, :G].reshape(L, H * Dh)
            p["layers"]["b_k"] = bqkv[:, :, G].reshape(L, KV * Dh)
            p["layers"]["b_v"] = bqkv[:, :, G + 1].reshape(L, KV * Dh)
        if config.attn_out_bias:
            p["layers"]["b_o"] = np.stack(
                [_np(sd[f"layers.{i}.attention.wo.bias"]) for i in range(L)])
        p["ln_f_w"] = _np(sd["norm.weight"])
        p["ln_f_b"] = np.zeros_like(p["ln_f_w"])
        if not config.tie_embeddings:
            p["unembed"] = _np(sd["output.weight"]).T
        return p

    if family == "megatron":
        # strip the megatron module nesting left after the generic prefixes
        sd = {k.removeprefix("language_model.").removeprefix("encoder."): v
              for k, v in sd.items()}
        D = config.d_model
        H, Dh = config.n_heads, config.head_dim
        p["embed"] = _np(sd["embedding.word_embeddings.weight"])[:config.vocab_size]
        if config.position == "learned":
            if "embedding.position_embeddings.weight" not in sd:
                raise ValueError(
                    "megatron import: no position_embeddings in the "
                    "checkpoint but the config does not declare rotary "
                    "positions — set use_rotary_position_embeddings/"
                    "position_embedding_type in the config dict")
            p["pos_embed"] = _np(sd["embedding.position_embeddings.weight"])
        attn = ("self_attention"
                if "layers.0.self_attention.query_key_value.weight" in sd
                else "attention")
        if f"layers.0.{attn}.query_key_value.bias" not in sd:
            raise ValueError(
                "megatron import expects biased projections (the classic "
                "GPT recipe); this checkpoint looks like a "
                "--disable-bias-linear run — import it through the llama "
                "family layout instead")
        qkv_w = np.stack([_np(sd[f"layers.{i}.{attn}.query_key_value.weight"])
                          for i in range(L)])                    # [L, 3D, D]
        qkv_b = np.stack([_np(sd[f"layers.{i}.{attn}.query_key_value.bias"])
                          for i in range(L)])                    # [L, 3D]
        # megatron_v2 interleaves per head ([H, 3, Dh] rows); v0 groups by
        # kind ([3, H, Dh]) — reference MegatronContainer.transpose().
        # Selected via the config dict ("megatron_v2": false for old
        # checkpoints), threaded explicitly through from_hf.
        v2 = bool(megatron_v2)
        if v2:
            qw = qkv_w.reshape(L, H, 3, Dh, D)
            qb = qkv_b.reshape(L, H, 3, Dh)
            get_w = lambda j: qw[:, :, j].reshape(L, H * Dh, D)
            get_b = lambda j: qb[:, :, j].reshape(L, H * Dh)
        else:
            qw = qkv_w.reshape(L, 3, H, Dh, D)
            qb = qkv_b.reshape(L, 3, H, Dh)
            get_w = lambda j: qw[:, j].reshape(L, H * Dh, D)
            get_b = lambda j: qb[:, j].reshape(L, H * Dh)
        layers = {
            "ln1_w": _stack(sd, "layers.{}.input_layernorm.weight", L),
            "ln1_b": _stack(sd, "layers.{}.input_layernorm.bias", L),
            "ln2_w": _stack(sd, "layers.{}.post_attention_layernorm.weight", L),
            "ln2_b": _stack(sd, "layers.{}.post_attention_layernorm.bias", L),
            "wq": get_w(0).transpose(0, 2, 1), "wk": get_w(1).transpose(0, 2, 1),
            "wv": get_w(2).transpose(0, 2, 1),
            "b_q": get_b(0), "b_k": get_b(1), "b_v": get_b(2),
            "wo": _stack(sd, "layers.{}." + attn + ".dense.weight", L, transpose=True),
            "b_o": _stack(sd, "layers.{}." + attn + ".dense.bias", L),
        }
        if config.n_experts > 0:
            E = config.n_experts
            D_ = config.d_model
            moe = "layers.{}.mlp.deepspeed_moe.experts.deepspeed_experts.{}."
            moe_layers = {i for i in range(L)
                          if moe.format(i, 0) + "dense_h_to_4h.weight" in sd}
            if not moe_layers:
                raise ValueError(
                    "megatron MoE: num_experts > 0 but no deepspeed_moe "
                    "expert weights found in the checkpoint")
            # --expert-interval (round 5, missing r4 #3): interleaved dense
            # layers import with their FFN in expert SLOT 0 (zeros in slots
            # 1..E-1, zero gate); config.moe_layer_pattern carries the
            # per-layer flags the traced scan switches on (from_hf derives
            # it from the checkpoint before calling here).
            declared = config.moe_layer_pattern or (True,) * L
            expected = {i for i in range(L)
                        if declared[i % len(declared)]}
            if moe_layers != expected:
                raise ValueError(
                    f"megatron MoE: layers {sorted(moe_layers)} carry "
                    f"experts but the config's moe_layer_pattern expects "
                    f"{sorted(expected)} — import through from_hf, which "
                    "derives the pattern from the checkpoint")
            dense_pre = "layers.{}.mlp."

            def stack_kind(kind, dense_kind, ours, width):
                ws, bs, any_bias = [], [], False
                for i in range(L):
                    if i in moe_layers:
                        ws.append(np.stack([
                            _np(sd[moe.format(i, e) + kind + ".weight"]).T
                            for e in range(E)]))
                        bk = moe.format(i, 0) + kind + ".bias"
                        if bk in sd:
                            any_bias = True
                            bs.append(np.stack([
                                _np(sd[moe.format(i, e) + kind + ".bias"])
                                for e in range(E)]))
                        else:
                            bs.append(np.zeros((E, width), np.float32))
                    else:
                        w0 = _np(sd[dense_pre.format(i) + dense_kind + ".weight"]).T
                        w = np.zeros((E,) + w0.shape, w0.dtype)
                        w[0] = w0
                        ws.append(w)
                        b = np.zeros((E, width), np.float32)
                        bk = dense_pre.format(i) + dense_kind + ".bias"
                        if bk in sd:
                            any_bias = True
                            b[0] = _np(sd[bk])
                        bs.append(b)
                layers[ours] = np.stack(ws)
                if any_bias:
                    # biased experts (round 5, VERDICT r4 #8; reference
                    # containers/megatron_gpt_moe.py imports them): the
                    # expert MLP adds [L, E, width] as a grouped epilogue
                    layers[ours.replace("_w_", "_b_")] = np.stack(bs)

            F_ = config.ff_dim
            stack_kind("dense_h_to_4h", "dense_h_to_4h", "moe_w_up", F_)
            stack_kind("dense_4h_to_h", "dense_4h_to_h", "moe_w_down", D_)
            gate_key = "layers.{}.mlp.deepspeed_moe.gate.wg.weight"
            layers["moe_gate"] = np.stack([
                _np(sd[gate_key.format(i)]).T if i in moe_layers
                else np.zeros((D_, E), np.float32) for i in range(L)])
        else:
            layers["w_up"] = _stack(sd, "layers.{}.mlp.dense_h_to_4h.weight", L,
                                    transpose=True)
            layers["b_up"] = _stack(sd, "layers.{}.mlp.dense_h_to_4h.bias", L)
            layers["w_down"] = _stack(sd, "layers.{}.mlp.dense_4h_to_h.weight", L,
                                      transpose=True)
            layers["b_down"] = _stack(sd, "layers.{}.mlp.dense_4h_to_h.bias", L)
        p["layers"] = layers
        p["ln_f_w"] = _np(sd["final_layernorm.weight"])
        p["ln_f_b"] = _np(sd["final_layernorm.bias"])
        if not config.tie_embeddings:
            # --untie-embeddings-and-output-weights
            p["unembed"] = _np(sd["output_layer.weight"])[:config.vocab_size].T
        return p

    # rope/rmsnorm families: llama / mistral / qwen2 / phi3 / mixtral / internlm
    p["embed"] = _np(sd["embed_tokens.weight"])
    layers: Dict[str, np.ndarray] = {
        "ln1_w": _stack(sd, "layers.{}.input_layernorm.weight", L),
        "ln2_w": _stack(sd, "layers.{}.post_attention_layernorm.weight", L),
    }
    H, KV, Dh = config.n_heads, config.kv_heads, config.head_dim
    if family == "phi3":
        qkv = _stack(sd, "layers.{}.self_attn.qkv_proj.weight", L, transpose=True)
        q_dim = H * Dh
        layers["wq"] = qkv[:, :, :q_dim]
        layers["wk"] = qkv[:, :, q_dim:q_dim + KV * Dh]
        layers["wv"] = qkv[:, :, q_dim + KV * Dh:]
        layers["wo"] = _stack(sd, "layers.{}.self_attn.o_proj.weight", L, transpose=True)
        gate_up = _stack(sd, "layers.{}.mlp.gate_up_proj.weight", L, transpose=True)
        F = config.ff_dim
        layers["w_gate"], layers["w_up"] = gate_up[:, :, :F], gate_up[:, :, F:]
        layers["w_down"] = _stack(sd, "layers.{}.mlp.down_proj.weight", L, transpose=True)
    else:
        layers["wq"] = _stack(sd, "layers.{}.self_attn.q_proj.weight", L, transpose=True)
        layers["wk"] = _stack(sd, "layers.{}.self_attn.k_proj.weight", L, transpose=True)
        layers["wv"] = _stack(sd, "layers.{}.self_attn.v_proj.weight", L, transpose=True)
        layers["wo"] = _stack(sd, "layers.{}.self_attn.o_proj.weight", L, transpose=True)
        if config.attn_qkv_bias:
            layers["b_q"] = _stack(sd, "layers.{}.self_attn.q_proj.bias", L)
            layers["b_k"] = _stack(sd, "layers.{}.self_attn.k_proj.bias", L)
            layers["b_v"] = _stack(sd, "layers.{}.self_attn.v_proj.bias", L)
        if config.attn_out_bias:   # internlm v1 bias=True
            layers["b_o"] = _stack(sd, "layers.{}.self_attn.o_proj.bias", L)
        if family in ("mixtral", "qwen2moe"):
            E = config.n_experts

            def experts(fmt):
                return np.stack([
                    np.stack([_np(sd[fmt.format(i, e)]).T for e in range(E)])
                    for i in range(L)])

            if family == "mixtral":
                layers["moe_gate"] = _stack(sd, "layers.{}.block_sparse_moe.gate.weight", L,
                                            transpose=True)
                # HF mixtral: w1 = gate, w3 = up, w2 = down
                layers["moe_w_gate"] = experts("layers.{}.block_sparse_moe.experts.{}.w1.weight")
                layers["moe_w_up"] = experts("layers.{}.block_sparse_moe.experts.{}.w3.weight")
                layers["moe_w_down"] = experts("layers.{}.block_sparse_moe.experts.{}.w2.weight")
            else:
                layers["moe_gate"] = _stack(sd, "layers.{}.mlp.gate.weight", L, transpose=True)
                layers["moe_w_gate"] = experts("layers.{}.mlp.experts.{}.gate_proj.weight")
                layers["moe_w_up"] = experts("layers.{}.mlp.experts.{}.up_proj.weight")
                layers["moe_w_down"] = experts("layers.{}.mlp.experts.{}.down_proj.weight")
                layers["moe_shared_w_gate"] = _stack(
                    sd, "layers.{}.mlp.shared_expert.gate_proj.weight", L, transpose=True)
                layers["moe_shared_w_up"] = _stack(
                    sd, "layers.{}.mlp.shared_expert.up_proj.weight", L, transpose=True)
                layers["moe_shared_w_down"] = _stack(
                    sd, "layers.{}.mlp.shared_expert.down_proj.weight", L, transpose=True)
                layers["moe_shared_gate"] = _stack(
                    sd, "layers.{}.mlp.shared_expert_gate.weight", L, transpose=True)
        else:
            layers["w_gate"] = _stack(sd, "layers.{}.mlp.gate_proj.weight", L, transpose=True)
            layers["w_up"] = _stack(sd, "layers.{}.mlp.up_proj.weight", L, transpose=True)
            layers["w_down"] = _stack(sd, "layers.{}.mlp.down_proj.weight", L, transpose=True)
    p["layers"] = layers
    p["ln_f_w"] = _np(sd["norm.weight"])
    p["ln_f_b"] = np.zeros_like(p["ln_f_w"])  # rmsnorm has no bias; kept for tree parity
    if not config.tie_embeddings:
        p["unembed"] = _np(sd["lm_head.weight"]).T
    return p


def from_hf(model_or_path, dtype=None) -> Tuple[Transformer, Dict[str, Any]]:
    """(Transformer, params) from a transformers model object, a
    (config, state_dict) pair, or a local checkpoint directory."""
    if isinstance(model_or_path, tuple):
        hf_config, sd = model_or_path
    elif isinstance(model_or_path, str):
        import transformers

        hf_config = transformers.AutoConfig.from_pretrained(model_or_path)
        model = transformers.AutoModelForCausalLM.from_pretrained(model_or_path)
        sd = model.state_dict()
    else:
        hf_config = model_or_path.config
        sd = model_or_path.state_dict()

    cfg_dict = hf_config if isinstance(hf_config, dict) else hf_config.to_dict()
    family = _family(cfg_dict)
    config = config_from_hf(cfg_dict)
    if family == "bert" and not any(k.startswith("cls.") for k in sd):
        # headless BertModel checkpoint: no MLM head to load — the tied
        # unembed still gives token scores
        import dataclasses as _dc

        config = _dc.replace(config, mlm_head=False)
        logger.info("bert: no cls.* keys (headless BertModel); importing "
                    "without the MLM head")
    if family == "megatron" and config.n_experts > 0:
        # --expert-interval: derive the per-layer MoE pattern from the
        # checkpoint (which layers actually carry deepspeed_moe experts)
        import dataclasses as _dc

        # normalize EXACTLY like params_from_state_dict: generic prefixes
        # first (transformer./model./...), then the megatron nesting —
        # raw checkpoints arrive as model.language_model.encoder.layers.*
        stripped = {k.removeprefix("transformer.").removeprefix("model.")
                    .removeprefix("gpt_neox.").removeprefix("bert.")
                    .removeprefix("distilbert.")
                    .removeprefix("language_model.").removeprefix("encoder.")
                    for k in sd}
        pat = tuple(
            f"layers.{i}.mlp.deepspeed_moe.experts.deepspeed_experts.0."
            "dense_h_to_4h.weight" in stripped
            for i in range(config.n_layers))
        if any(pat) and not all(pat):
            config = _dc.replace(config, moe_layer_pattern=pat)
            logger.info("megatron MoE: interleaved dense layers detected "
                        "(--expert-interval); MoE layers: %s",
                        [i for i, m in enumerate(pat) if m])
    megatron_v2 = bool(cfg_dict.get("megatron_v2", True))
    params = params_from_state_dict(sd, config, family, megatron_v2=megatron_v2)
    import jax.numpy as jnp

    if dtype is not None:
        params = _tree_cast(params, dtype)
    else:
        params = _tree_cast(params, jnp.float32)
    return Transformer(config), params


def load_draft_model(model_or_path, dtype=None) -> Tuple[Transformer, Dict[str, Any]]:
    """(Transformer, params) for a speculative-serving DRAFT model
    (ISSUE 8): ``from_hf`` with the optional ``transformers`` dependency
    gated up front — a serving config naming a ``draft_model`` checkpoint
    on a box without transformers fails at drafter construction with the
    fix named, not with an ImportError in the middle of a serve loop.
    Accepts everything ``from_hf`` does (model object, (config,
    state_dict) pair, local checkpoint dir)."""
    if isinstance(model_or_path, str):
        try:
            import transformers  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                f"serving.speculative.draft_model={model_or_path!r} needs "
                "the optional `transformers` package to load an HF "
                "checkpoint; install it, or pass the scheduler a drafter "
                "built from an in-process (model, params) pair "
                "(inference.speculative.DraftModelDrafter)") from e
    return from_hf(model_or_path, dtype=dtype)


def _tree_cast(tree, dtype):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype=dtype), tree)
