"""Decoder-only transformer family, TPU-first.

This is the model zoo used by the benchmarks and the serving engine — the
capability analog of the reference's supported architectures
(``module_inject/containers/`` gpt2/llama/llama2 etc., and
``inference/v2/model_implementations/llama_v2/model.py``), built the JAX way:

- **Scanned layers**: per-layer params are stacked on a leading dim and the
  layer body runs under ``lax.scan`` — O(1) compile time in depth, natural
  remat boundaries, and the stack dim later doubles as the pipeline-stage
  dim.
- **Mesh-aware partition specs**: every weight carries a logical
  PartitionSpec (heads/ffn over "tensor", vocab over "tensor") — the AutoTP
  analog (module_inject/auto_tp.py): XLA inserts the row/column-parallel
  collectives the reference implements as LinearLayer/LinearAllreduce
  (module_inject/layers.py:388,465).
- bf16-friendly: params live in the engine's train dtype; norms/softmax/CE
  computed in fp32.

Configs cover GPT-2 (learned pos, LayerNorm, GELU) and Llama-3 (RoPE,
RMSNorm, SwiGLU, GQA) families plus tiny test sizes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None          # None = MHA; < n_heads = GQA
    d_ff: Optional[int] = None                 # default 4*d (gelu) or 8/3*d (swiglu)
    max_seq_len: int = 2048
    activation: str = "gelu"                   # "gelu" | "swiglu"
    norm: str = "layernorm"                    # "layernorm" | "rmsnorm"
    position: str = "learned"                  # "learned" | "rope"
    rope_theta: float = 500000.0
    tie_embeddings: bool = True
    dropout: float = 0.0
    norm_eps: float = 1e-5
    attn_qkv_bias: bool = False                # Qwen2-style q/k/v biases
    attn_out_bias: bool = False                # GPT-2/OPT-style out-proj bias
    pos_offset: int = 0                        # OPT offsets positions by 2
    # Family structure flags (round 3, HF import breadth — reference
    # module_inject/containers/{gptj,gptneox,bloom}.py + falcon in
    # inference/v2/engine_factory.py):
    parallel_block: bool = False               # h + attn(y1) + mlp(y2) (GPT-J/NeoX/Falcon)
    parallel_shared_ln: bool = False           # y2 = y1, no ln2 (GPT-J, Falcon-7B)
    rotary_dim: int = 0                        # rope on first rotary_dim dims (0 = all)
    rope_interleaved: bool = False             # GPT-J rotate-every-two pairs
    embed_ln: bool = False                     # BLOOM word_embeddings_layernorm
    alibi_slope_scale: float = 1.0             # falcon scales alibi by 1/sqrt(Dh)
    mlp_bias: bool = True                      # gelu-path fc biases (False: Falcon)
    unembed_bias: bool = False                 # GPT-J lm_head bias
    # Random-LTD (reference runtime/data_pipeline/data_routing): middle
    # layers skip a random token subset per step. TPU (static-shape) form:
    # dropped tokens FREEZE their hidden state through the layer (masked
    # select) instead of being gathered out — same schedule/regularization,
    # no dynamic shapes. They remain visible as keys, a documented deviation.
    random_ltd: bool = False
    random_ltd_start_layer: int = 1
    random_ltd_end_layer: int = -1             # exclusive; -1 = n_layers - 1
    # Encoder-family structure (round 4, reference module_inject/containers/
    # bert.py + distil_bert.py): bidirectional attention, post-LN residual
    # order, token-type embeddings, and the BERT MLM head
    # (transform dense + LN + tied decoder with its own bias).
    causal: bool = True                        # False = bidirectional (BERT)
    post_ln: bool = False                      # LN(h + sublayer) (BERT)
    type_vocab_size: int = 0                   # token_type embeddings (BERT)
    mlm_head: bool = False                     # BertForMaskedLM cls head
    # GPT-Neo structure (reference module_inject/containers/gptneo.py):
    # unscaled attention + alternating global/local layers.
    attn_scale: float = 0.0                    # 0 = 1/sqrt(Dh); GPT-Neo: 1.0
    local_attention_window: int = 0            # window for "local" layers
    attention_pattern: Tuple[str, ...] = ()    # per-layer "global"/"local",
                                               # cycled over n_layers
    dtype: Any = None                          # compute dtype override (engine usually casts)
    remat: bool = False
    remat_policy: str = "dots_saveable"
    # MoE (reference moe/layer.py MoE wrapper; Mixtral-style when set)
    n_experts: int = 0                         # 0 = dense
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    moe_impl: str = "auto"   # auto | capacity (index dispatch) | capacity_einsum | ragged (dropless)
    moe_shared_expert_ff: int = 0              # Qwen2-MoE shared expert (0 = none)
    moe_norm_topk: bool = True                 # renormalize top-k weights (Mixtral);
                                               # False = raw softmax probs (Qwen2-MoE)
    # Megatron --expert-interval interleaving: per-layer MoE flags, cycled
    # over n_layers; () = every layer is MoE (when n_experts > 0). Dense
    # layers store their FFN in expert slot 0 of the stacked arrays and a
    # traced per-layer flag selects the dense path inside the scan.
    moe_layer_pattern: Tuple[bool, ...] = ()
    attention_impl: str = "auto"
    # Chunked vocab CE (reference FPDT chunked logits loss,
    # sequence/fpdt_layer.py:1137): compute the loss in seq chunks under
    # remat so [B, T, vocab] logits are never materialized. 0 = full logits;
    # -1 = auto (chunk when T * vocab is large enough to matter).
    loss_chunk: int = -1
    # Pad the chunked-loss unembed to a 128-multiple vocab (MXU lane tile)
    # with -1e30-masked pad columns. None = auto (TPU, unaligned vocab only).
    pad_vocab_logits: Optional[bool] = None
    # Sequence-parallel attention flavor when the mesh has seq > 1:
    # "ulysses" (a2a seq<->head reshard around the local attention_impl
    # kernel) or "ring" (KV blocks rotate via ppermute — the context-
    # parallel form; no head-count divisibility requirement). Ring is its
    # own chunked online-softmax (attention_impl is not used); each hop is
    # checkpointed, so backward residuals are O(T/sp * D) per layer
    # (score tiles are recomputed hop by hop, never saved).
    sp_attention: str = "ulysses"
    # Ring-CP tuning (ISSUE 15; set by sxt.initialize from the engine
    # config's context_parallel section): per-hop KV tile for the jnp
    # chunked ring, and the hop-kernel routing ("auto" gates on shape/
    # backend, "pallas" forces the flash_attention_lse hop kernel,
    # "xla" keeps the jnp chunked online-softmax).
    cp_kv_chunk: int = 1024
    cp_use_kernel: str = "auto"

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def rotary_dims(self) -> int:
        return self.rotary_dim or self.head_dim

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff:
            return self.d_ff
        if self.activation == "swiglu":
            # Llama convention: 2/3 * 4d rounded to multiple of 256
            d = int(8 * self.d_model / 3)
            return 256 * ((d + 255) // 256)
        return 4 * self.d_model


# ---------------------------------------------------------------------------
# Presets (sizes match the reference's benchmark configs, BASELINE.md)
# ---------------------------------------------------------------------------

def gpt2_small() -> TransformerConfig:  # 125M — capability config #1
    return TransformerConfig(vocab_size=50257, d_model=768, n_layers=12, n_heads=12,
                             max_seq_len=1024, activation="gelu", norm="layernorm", position="learned",
                             attn_qkv_bias=True, attn_out_bias=True)


def gpt2_large() -> TransformerConfig:
    return TransformerConfig(vocab_size=50257, d_model=1280, n_layers=36, n_heads=20,
                             max_seq_len=1024, activation="gelu", norm="layernorm", position="learned",
                             attn_qkv_bias=True, attn_out_bias=True)


def llama3_8b() -> TransformerConfig:  # capability config #2 (north star)
    return TransformerConfig(vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
                             d_ff=14336, max_seq_len=8192, activation="swiglu", norm="rmsnorm",
                             position="rope", rope_theta=500000.0, tie_embeddings=False)


def llama3_70b() -> TransformerConfig:  # capability config #4
    return TransformerConfig(vocab_size=128256, d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                             d_ff=28672, max_seq_len=8192, activation="swiglu", norm="rmsnorm",
                             position="rope", tie_embeddings=False)


def mixtral_8x7b() -> TransformerConfig:  # capability config #3
    return TransformerConfig(vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
                             d_ff=14336, max_seq_len=8192, activation="swiglu", norm="rmsnorm",
                             position="rope", rope_theta=1e6, tie_embeddings=False,
                             n_experts=8, moe_top_k=2)


def tiny(vocab=256, d=64, layers=2, heads=4, seq=64, **kw) -> TransformerConfig:
    return TransformerConfig(vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
                             max_seq_len=seq, **kw)


def tiny_moe(vocab=256, d=64, layers=2, heads=4, seq=64, experts=4, **kw) -> TransformerConfig:
    return TransformerConfig(vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
                             max_seq_len=seq, activation="swiglu", norm="rmsnorm", position="rope",
                             n_experts=experts, moe_top_k=2, **kw)


# ---------------------------------------------------------------------------
# Core ops (jnp reference implementations; Pallas kernels swap in via ops/)
# ---------------------------------------------------------------------------


def activation_fn(name: str):
    """Non-gated activation dispatch ("swiglu" is handled structurally).

    "gelu" is the exact (erf) form as in HF; "gelu_new"/"gelu_pytorch_tanh"
    are the tanh approximation (GPT-2 lineage)."""
    import functools as _ft

    import jax

    try:
        return {"gelu": _ft.partial(jax.nn.gelu, approximate=False),
                "relu": jax.nn.relu, "silu": jax.nn.silu,
                "gelu_new": _ft.partial(jax.nn.gelu, approximate=True),
                "gelu_pytorch_tanh": _ft.partial(jax.nn.gelu, approximate=True)}[name]
    except KeyError:
        raise ValueError(f"Unsupported activation {name!r}; use swiglu/gelu/relu/silu/gelu_new")


def _norm(x, weight, bias, kind: str, eps: float = 1e-5):
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    if kind == "rmsnorm":
        from ..ops.rmsnorm import rmsnorm

        return rmsnorm(x32, weight.astype(jnp.float32), eps=eps).astype(x.dtype)
    mean = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mean) * (1.0 / jnp.sqrt(var + eps))
    out = out * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def rope_table(seq_len: int, head_dim: int, theta: float):
    import jax.numpy as jnp

    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    angles = jnp.outer(t, freqs)  # [T, D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin, interleaved: bool = False):
    """x: [B, T, H, D]. Rotates the first ``2 * cos.shape[-1]`` dims (partial
    rotary — GPT-NeoX rotary_pct / GPT-J rotary_dim); the rest pass through.

    interleaved=False: llama/NeoX rotate-half pairing (dim i with i + rd/2).
    interleaved=True:  GPT-J rotate-every-two pairing (dim 2i with 2i+1).
    """
    import jax.numpy as jnp

    rd = 2 * cos.shape[-1]
    rot, rest = (x[..., :rd], x[..., rd:]) if rd < x.shape[-1] else (x, None)
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    if interleaved:
        x1, x2 = rot[..., 0::2], rot[..., 1::2]
        out = jnp.stack([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
        out = out.reshape(rot.shape)
    else:
        x1, x2 = jnp.split(rot, 2, axis=-1)
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out if rest is None else jnp.concatenate([out, rest], axis=-1)


def alibi_slopes(n_heads: int):
    """BLOOM/ALiBi head slopes (press et al.; matches HF build_alibi_tensor)."""
    import numpy as np

    def pow2(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start ** (i + 1) for i in range(n)]

    if math.log2(n_heads).is_integer():
        s = pow2(n_heads)
    else:
        m = 2 ** math.floor(math.log2(n_heads))
        s = pow2(m) + pow2(2 * m)[0::2][: n_heads - m]
    return np.asarray(s, np.float32)


def decode_fusion_eligibility(cfg: "TransformerConfig",
                              speculative_k: int = 0) -> dict:
    """Which parts of the fused Pallas decode path (ops/fused_decode.py)
    this model STRUCTURE supports — the single source of truth both
    serving engines consult when ``decode_kernel`` resolves to "pallas".

    Returns ``{"qkv": None | reason, "mlp": None | reason,
    "verify": None | reason}``; ``None`` means fusable. Per-layer
    WEIGHT-form checks (dense vs QuantizedMatrix, group sizes) happen at
    dispatch time in the engines — this classifies only what is knowable
    from the config. Attention fusion has no structural requirements
    beyond the engine-wide pre-LN layer body (GQA H % KV == 0 is a
    construction invariant).

    ``speculative_k`` (ISSUE 8 satellite): the serving config's draft
    width. The fused decode kernels — QKV+RoPE+pool-append and the split-K
    flash-decode — are SINGLE-token by construction (one row, one new KV
    slot, ``kv_len = pos + 1``); a speculative verify row is ``k+1``
    tokens wide and silently routing it through them would read a stale
    kv_len and drop k appends. The ``"verify"`` entry makes that gate
    explicit: with ``speculative_k > 0`` the verify rows must take the
    paged-EXTEND path (the chunked-prefill kernel, which is multi-token
    by construction), and only plain 1-token decode rows stay fused.

    One-dispatch sampling (ISSUE 16) does not change this
    classification: the fused sampler
    (``inference/sampling.py::seeded_tokens``) composes AFTER the layer
    stack, on the gathered final-position logits, inside the same
    compiled program — so every sampling mode (greedy, temperature/
    top-k/top-p, logit-masked, EOS early-stop) keeps whatever fused
    decode path the structure earns here. The only sampling-adjacent
    routing change is the one speculation already imposes: sampled
    verify rows are still ``k+1`` tokens wide and still take the
    paged-extend route per the ``"verify"`` entry.
    """
    from ..ops.fused_decode import FUSABLE_ACTIVATIONS

    qkv = None
    if cfg.position == "rope" and cfg.rope_interleaved:
        qkv = ("interleaved (GPT-J rotate-every-two) rope pairing: the "
               "fused kernel's lane-roll rotate-half form does not cover it")
    mlp = None
    if cfg.n_experts > 0:
        mlp = ("MoE FFN (expert dispatch stays on the moe_layer path, "
               "which itself admits int8/fp8 streamed expert weights — "
               "the grouped-GEMM/einsum dequant fuses into the dot)")
    elif cfg.activation not in FUSABLE_ACTIVATIONS:
        mlp = (f"activation {cfg.activation!r} has no Mosaic lowering "
               f"(fusable: {', '.join(FUSABLE_ACTIVATIONS)})")
    elif cfg.norm not in ("rmsnorm", "layernorm"):
        mlp = f"unknown norm {cfg.norm!r}"
    verify = None
    if speculative_k > 0:
        verify = (
            f"speculative verify rows are {speculative_k + 1} tokens wide; "
            "the fused decode kernels are single-token (one append, "
            "kv_len = pos + 1) — verify rows route through the "
            "paged-extend kernel; fused decode applies to plain decode "
            "rows only")
    return {"qkv": qkv, "mlp": mlp, "verify": verify}


def causal_attention(q, k, v, attention_impl: str = "auto", alibi=None,
                     causal: bool = True):
    """q: [B,T,H,D], k/v: [B,T,Hkv,D] → [B,T,H,D]. fp32 softmax.

    Dispatches to the Pallas flash kernel on TPU (ops/flash_attention);
    jnp reference elsewhere. ``alibi`` = per-head slopes [H] (BLOOM).
    ``causal=False`` = bidirectional (encoder models)."""
    import jax.numpy as jnp

    from ..ops.flash_attention import flash_attention

    return flash_attention(q, k, v, causal=causal, impl=attention_impl,
                           alibi_slopes=alibi)


def _windowed_attention(q, k, v, window: int, local_flag):
    """Causal attention with a conditional trailing window (GPT-Neo local
    layers, reference containers/gptneo.py). ``local_flag`` is a traced
    bool — True restricts key j to i - j < window — so global and local
    layers share one scanned program."""
    import jax
    import jax.numpy as jnp

    from ..ops.flash_attention import _repeat_kv

    n_rep = q.shape[2] // k.shape[2]
    k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32) * scale,
                        k.astype(jnp.float32))
    T = q.shape[1]
    i = jnp.arange(T)[:, None]
    j = jnp.arange(T)[None, :]
    mask = j <= i
    mask = mask & jnp.where(local_flag, (i - j) < window, True)
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# The model
# ---------------------------------------------------------------------------


class Transformer:
    """Functional model: ``init(rng) -> params``, ``apply(params, ids) ->
    logits``, ``loss(params, batch, rng) -> scalar`` (next-token CE)."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    # -- parameters ----------------------------------------------------

    def init(self, rng) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        L, D, H, KV, Dh, F = cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.head_dim, cfg.ff_dim
        keys = iter(jax.random.split(rng, 16))

        params: Dict[str, Any] = {
            "embed": jax.random.normal(next(keys), (cfg.vocab_size, D), jnp.float32) * 0.02,
        }
        if cfg.position == "learned":
            # +pos_offset rows so OPT-style offset indexing stays in bounds
            # right up to T == max_seq_len (checkpoints for such archs store
            # the offset rows the same way).
            params["pos_embed"] = jax.random.normal(
                next(keys), (cfg.max_seq_len + cfg.pos_offset, D), jnp.float32) * 0.02
        # stacked per-layer weights: leading dim L
        def stack(key, shape, fan_in, scale=1.0):
            return jax.random.normal(key, (L,) + shape, jnp.float32) * (scale / math.sqrt(fan_in))

        layer = {
            "ln1_w": jnp.ones((L, D)), "ln1_b": jnp.zeros((L, D)),
            "wq": stack(next(keys), (D, H * Dh), D),
            "wk": stack(next(keys), (D, KV * Dh), D),
            "wv": stack(next(keys), (D, KV * Dh), D),
            "wo": stack(next(keys), (H * Dh, D), H * Dh, scale=1.0 / math.sqrt(2 * L)),
        }
        if not (cfg.parallel_block and cfg.parallel_shared_ln):
            layer["ln2_w"], layer["ln2_b"] = jnp.ones((L, D)), jnp.zeros((L, D))
        if cfg.attn_qkv_bias:
            layer["b_q"] = jnp.zeros((L, H * Dh))
            layer["b_k"] = jnp.zeros((L, KV * Dh))
            layer["b_v"] = jnp.zeros((L, KV * Dh))
        if cfg.attn_out_bias:
            layer["b_o"] = jnp.zeros((L, D))
        if cfg.n_experts > 0:
            import jax.random as jrandom

            from ..moe.layer import init_expert_mlp

            ek = next(keys)
            per_layer = [init_expert_mlp(k, cfg.n_experts, D, F, cfg.activation)
                         for k in jrandom.split(ek, L)]
            layer["moe_gate"] = stack(next(keys), (D, cfg.n_experts), D)
            for name in per_layer[0]:
                layer[f"moe_{name}"] = jnp.stack([p[name] for p in per_layer])
            if cfg.moe_shared_expert_ff > 0:
                Fs = cfg.moe_shared_expert_ff
                layer["moe_shared_w_gate"] = stack(next(keys), (D, Fs), D)
                layer["moe_shared_w_up"] = stack(next(keys), (D, Fs), D)
                layer["moe_shared_w_down"] = stack(next(keys), (Fs, D), Fs)
                layer["moe_shared_gate"] = jnp.zeros((L, D, 1))
        elif cfg.activation == "swiglu":
            layer["w_gate"] = stack(next(keys), (D, F), D)
            layer["w_up"] = stack(next(keys), (D, F), D)
            layer["w_down"] = stack(next(keys), (F, D), F, scale=1.0 / math.sqrt(2 * L))
        else:
            layer["w_up"] = stack(next(keys), (D, F), D)
            layer["w_down"] = stack(next(keys), (F, D), F, scale=1.0 / math.sqrt(2 * L))
            if cfg.mlp_bias:
                layer["b_up"] = jnp.zeros((L, F))
                layer["b_down"] = jnp.zeros((L, D))
        params["layers"] = layer
        if cfg.type_vocab_size > 0:
            params["token_type_embed"] = jax.random.normal(
                next(keys), (cfg.type_vocab_size, D), jnp.float32) * 0.02
        if cfg.embed_ln:
            params["embed_ln_w"], params["embed_ln_b"] = jnp.ones((D,)), jnp.zeros((D,))
        if not cfg.post_ln:
            # post-LN encoders (BERT) normalize inside each block and have
            # no final norm before the head
            params["ln_f_w"] = jnp.ones((D,))
            params["ln_f_b"] = jnp.zeros((D,))
        if cfg.mlm_head:
            params["mlm_dense_w"] = jax.random.normal(next(keys), (D, D), jnp.float32) / math.sqrt(D)
            params["mlm_dense_b"] = jnp.zeros((D,))
            params["mlm_ln_w"], params["mlm_ln_b"] = jnp.ones((D,)), jnp.zeros((D,))
            params["mlm_bias"] = jnp.zeros((cfg.vocab_size,))
        if not cfg.tie_embeddings:
            params["unembed"] = jax.random.normal(next(keys), (D, cfg.vocab_size), jnp.float32) * 0.02
            if cfg.unembed_bias:
                params["unembed_b"] = jnp.zeros((cfg.vocab_size,))
        return params

    # -- partition specs (AutoTP analog) -------------------------------

    def partition_specs(self, params) -> Dict[str, Any]:
        import jax
        from jax.sharding import PartitionSpec as P

        cfg = self.config

        def spec_for(path: Tuple[str, ...], leaf):
            name = path[-1]
            stacked = path[0] == "layers"
            lead = (None,) if stacked else ()
            if name.startswith("moe_shared"):
                # shared expert = a dense MLP: column/row parallel like w_*
                if name in ("moe_shared_w_gate", "moe_shared_w_up"):
                    return P(*lead, None, "tensor")
                if name == "moe_shared_w_down":
                    return P(*lead, "tensor", None)
                return P(*lead, None, None)      # the scalar gate
            if name.startswith("moe_") and name != "moe_gate":
                # single source of truth for expert sharding lives in moe/layer.py
                from ..moe.layer import expert_partition_specs

                base = expert_partition_specs({name[4:]: None})[name[4:]]
                return P(*lead, *base)
            if name == "moe_gate":
                return P(*lead, None, None)
            if name in ("wq", "wk", "wv", "w_gate", "w_up"):
                return P(*lead, None, "tensor")       # column parallel
            if name in ("wo", "w_down"):
                return P(*lead, "tensor", None)       # row parallel
            if name in ("b_up", "b_q", "b_k", "b_v"):
                return P(*lead, "tensor")  # column-parallel biases
            if name == "embed":
                return P("tensor", None)              # vocab parallel
            if name == "unembed":
                return P(None, "tensor")
            return P(*((None,) * leaf.ndim))

        flat = {}
        def walk(tree, path):
            if isinstance(tree, dict):
                return {k: walk(v, path + (k,)) for k, v in tree.items()}
            return spec_for(path, tree)

        return walk(params, ())

    # -- forward pieces (shared by the plain and pipelined paths) ------

    def embed(self, params, input_ids):
        """ids [.., T] -> (x [.., T, D], rope (cos, sin) or (None, None))."""
        import jax.numpy as jnp

        cfg = self.config
        T = input_ids.shape[-1]
        x = jnp.take(params["embed"], input_ids, axis=0)
        if cfg.position == "learned":
            x = x + params["pos_embed"][cfg.pos_offset:cfg.pos_offset + T].astype(x.dtype)
        if cfg.type_vocab_size > 0:
            # token_type row 0 (the HF default when token_type_ids is None)
            x = x + params["token_type_embed"][0].astype(x.dtype)
        if cfg.embed_ln:
            # BLOOM word_embeddings_layernorm; BERT embeddings.LayerNorm
            # (after the word+pos+type sum — BLOOM has no learned pos, so
            # the shared placement is exact for both)
            x = _norm(x, params["embed_ln_w"], params["embed_ln_b"], cfg.norm,
                      eps=cfg.norm_eps)
        if cfg.position in ("learned", "alibi"):
            return x, (None, None)
        return x, rope_table(T, cfg.rotary_dims, cfg.rope_theta)

    def layer_apply(self, lw, h, rope, local=None, moe_on=None):
        """One transformer block. h [B, T, D] -> (h, moe_aux).

        ``local`` (traced bool scalar, GPT-Neo): this layer restricts
        attention to the trailing ``local_attention_window`` positions.
        ``moe_on`` (traced bool scalar, Megatron --expert-interval): False
        routes this layer through the dense FFN stored in expert slot 0
        (the flag is replica-identical, so both lax.cond branches keep a
        uniform collective schedule across devices)."""
        import jax
        import jax.numpy as jnp

        cfg = self.config
        B, T = h.shape[:2]
        H, KV, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
        cos, sin = rope
        dtype = h.dtype
        if cfg.post_ln:
            y = h   # BERT: sublayer input is unnormalized; LN follows the add
        else:
            y = _norm(h, lw["ln1_w"], lw.get("ln1_b", 0), cfg.norm, eps=cfg.norm_eps)
        q = (y @ lw["wq"]).reshape(B, T, H, Dh)
        k = (y @ lw["wk"]).reshape(B, T, KV, Dh)
        v = (y @ lw["wv"]).reshape(B, T, KV, Dh)
        if cfg.attn_qkv_bias:
            q = q + lw["b_q"].astype(dtype).reshape(H, Dh)
            k = k + lw["b_k"].astype(dtype).reshape(KV, Dh)
            v = v + lw["b_v"].astype(dtype).reshape(KV, Dh)
        if cfg.position == "rope":
            q = apply_rope(q, cos, sin, interleaved=cfg.rope_interleaved)
            k = apply_rope(k, cos, sin, interleaved=cfg.rope_interleaved)
        # Name the KV residuals so remat_policy="offload_kv_host" can park
        # them in host RAM between fwd and bwd (FPDT SequenceChunk offload,
        # reference sequence/fpdt_layer.py:462; XLA schedules the transfers
        # and double-buffers the prefetch). q joins for the selective-save
        # policies (save_attn_seams / save_ffn). No-op under other policies.
        from jax.ad_checkpoint import checkpoint_name

        q = checkpoint_name(q, "q")
        k = checkpoint_name(k, "kv")
        v = checkpoint_name(v, "kv")
        alibi = (alibi_slopes(H) * cfg.alibi_slope_scale
                 if cfg.position == "alibi" else None)
        if cfg.attn_scale:
            # GPT-Neo omits the 1/sqrt(Dh) score scaling; the attention
            # internals always divide, so pre-multiply q to net attn_scale
            q = q * jnp.asarray(cfg.attn_scale * math.sqrt(Dh), q.dtype)
        if cfg.local_attention_window and local is not None:
            attn = _windowed_attention(q, k, v, cfg.local_attention_window,
                                       local).reshape(B, T, H * Dh)
        else:
            attn = self._attention(q, k, v, alibi).reshape(B, T, H * Dh)
        attn = checkpoint_name(attn, "attn")
        attn_out = attn @ lw["wo"]
        if cfg.attn_out_bias:
            attn_out = attn_out + lw["b_o"].astype(dtype)
        if cfg.post_ln:
            h = _norm(h + attn_out, lw["ln1_w"], lw.get("ln1_b", 0), cfg.norm,
                      eps=cfg.norm_eps)
            y2 = h
        elif cfg.parallel_block:
            # GPT-J/NeoX/Falcon: h + attn(ln1 h) + mlp(ln2 h or ln1 h)
            y2 = y if cfg.parallel_shared_ln else _norm(
                h, lw["ln2_w"], lw.get("ln2_b", 0), cfg.norm, eps=cfg.norm_eps)
        else:
            h = h + attn_out
            y2 = _norm(h, lw["ln2_w"], lw.get("ln2_b", 0), cfg.norm, eps=cfg.norm_eps)
        aux = jnp.zeros((), jnp.float32)
        if cfg.n_experts > 0:
            from ..moe.layer import moe_layer

            expert_params = {name[4:]: lw[name] for name in lw
                             if name.startswith("moe_")
                             and name != "moe_gate" and not name.startswith("moe_shared")}

            def moe_branch(y2):
                # scanned=True: layer_apply always runs under stack_apply's
                # lax.scan — "auto" must not pick the megablox ragged path
                # there (the ~4x scanned-gmm cliff, moe/resolve_moe_impl)
                res = moe_layer(lw["moe_gate"], expert_params, y2, k=cfg.moe_top_k,
                                capacity_factor=cfg.capacity_factor, activation=cfg.activation,
                                impl=cfg.moe_impl, normalize_weights=cfg.moe_norm_topk,
                                scanned=True)
                return res.output, res.aux_loss

            if moe_on is None:
                ff, aux = moe_branch(y2)
            else:
                def dense_branch(y2):
                    # expert slot 0 carries the dense FFN of interleaved
                    # dense layers (Megatron --expert-interval import)
                    up = y2 @ expert_params["w_up"][0].astype(dtype)
                    if "b_up" in expert_params:
                        up = up + expert_params["b_up"][0].astype(dtype)
                    if cfg.activation == "swiglu":
                        g = y2 @ expert_params["w_gate"][0].astype(dtype)
                        if "b_gate" in expert_params:
                            g = g + expert_params["b_gate"][0].astype(dtype)
                        hh = jax.nn.silu(g) * up
                    else:
                        hh = activation_fn(cfg.activation)(up)
                    out = hh @ expert_params["w_down"][0].astype(dtype)
                    if "b_down" in expert_params:
                        out = out + expert_params["b_down"][0].astype(dtype)
                    return out, jnp.zeros((), jnp.float32)

                from ..parallel.mesh import inside_manual_region

                if inside_manual_region():
                    # under a partial-manual region (pipeline stage) a cond
                    # around the MoE dispatch CHECK-fails XLA's partitioner;
                    # compute both branches and select — the dense branch
                    # is one FFN, small next to the expert compute
                    ff_m, aux_m = moe_branch(y2)
                    ff_d, aux_d = dense_branch(y2)
                    ff = jnp.where(moe_on, ff_m, ff_d)
                    aux = jnp.where(moe_on, aux_m, aux_d)
                else:
                    ff, aux = jax.lax.cond(moe_on, moe_branch, dense_branch, y2)
            if cfg.moe_shared_expert_ff > 0:
                # Qwen2-MoE shared expert: a dense swiglu MLP every token
                # runs, added with a per-token sigmoid gate
                shared = (jax.nn.silu(y2 @ lw["moe_shared_w_gate"])
                          * (y2 @ lw["moe_shared_w_up"])) @ lw["moe_shared_w_down"]
                gate_s = jax.nn.sigmoid(y2 @ lw["moe_shared_gate"])
                ff = ff + gate_s.astype(ff.dtype) * shared
        elif cfg.activation == "swiglu":
            # Tagged so remat_policy="save_ffn" can keep the two big FFN
            # projections (the bulk of layer FLOPs) out of the backward
            # recompute; the elementwise silu/mul re-derives from them free.
            gate = checkpoint_name(y2 @ lw["w_gate"], "ffn_gate")
            up = checkpoint_name(y2 @ lw["w_up"], "ffn_up")
            ff = (jax.nn.silu(gate) * up) @ lw["w_down"]
        elif cfg.mlp_bias:
            act = activation_fn(cfg.activation)
            ff = act(y2 @ lw["w_up"] + lw["b_up"].astype(dtype)) @ lw["w_down"] + lw["b_down"].astype(dtype)
        else:
            act = activation_fn(cfg.activation)
            ff = act(y2 @ lw["w_up"]) @ lw["w_down"]
        if cfg.post_ln:
            h = _norm(h + ff, lw["ln2_w"], lw.get("ln2_b", 0), cfg.norm,
                      eps=cfg.norm_eps)
        elif cfg.parallel_block:
            h = h + attn_out + ff
        else:
            h = h + ff
        return h, aux

    @staticmethod
    def _sp_mesh():
        """(sp_degree, mesh) from the live topology; (1, None) when no
        sequence-parallel axis is active."""
        from ..parallel.mesh import get_topology, topology_is_initialized

        if not topology_is_initialized():
            return 1, None
        topo = get_topology()
        return topo.size("seq"), topo.mesh

    def _attention(self, q, k, v, alibi):
        """Core attention, sequence-parallel when the mesh has a "seq" axis.

        Ulysses (reference DistributedAttention, sequence/layer.py:331)
        engaged via shard_map inside the jitted step: activations shard
        [batch over data+fsdp, seq over "seq"], the two all-to-alls swap
        seq<->head sharding around the local flash kernel. ALiBi rides
        both SP flavors (round 5): Ulysses slices the slope vector per
        head shard, the ring adds the bias at global key positions; see
        alibi_sp_ok below for the replicated-fallback cases."""
        cfg = self.config
        sp, mesh = self._sp_mesh()
        if (cfg.remat and cfg.remat_policy == "save_flash_lse"
                and alibi is None and sp <= 1 and cfg.causal
                and not cfg.local_attention_window):
            # save_flash_lse: route through the lse-emitting kernel so the
            # policy has residuals to save — the stock flash kernel's
            # custom-vjp residuals are anonymous, which is exactly why
            # save_attn_seams regressed (it paid HBM for the named "attn"
            # seam while the flash forward still re-ran in backward to
            # rebuild its out+lse residuals). SXT_LSE_INTERPRET=1 drives
            # the kernel in interpret mode for CPU parity tests.
            import os

            from ..ops.flash_attention import (flash_attention_remat,
                                               flash_lse_ok)

            interp = bool(os.environ.get("SXT_LSE_INTERPRET"))
            if interp or flash_lse_ok(q, k, cfg.causal):
                return flash_attention_remat(q, k, v, causal=True,
                                             interpret=interp)
            from ..utils.logging import warning_once

            warning_once(
                "remat_policy=save_flash_lse: shapes/backend do not qualify "
                "for the lse flash kernel (head_dim 64/128, causal, Pallas "
                "backend) — attention takes the standard path and the "
                "policy saves nothing for this layer")
        if sp > 1:
            # The shard_map's batch spec needs the global batch divisible by
            # the data x fsdp extent; callers outside the training layout
            # (e.g. a 1-prompt inference forward while a seq mesh is live)
            # fall back to replicated attention rather than failing to trace.
            dp = int(mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1))
            if q.shape[0] % dp:
                from ..utils.logging import warning_once

                # sxt: ignore[SXT005] batch sizes are bounded by the shape-bin ladder; mesh extent is fixed
                warning_once(
                    f"sequence-parallel attention skipped: batch {q.shape[0]} "
                    f"not divisible by data*fsdp={dp} (replicated fallback)")
                sp = 1
        H_all, KV_all = q.shape[2], k.shape[2]
        # ALiBi composes with SP (round 5): Ulysses scatters WHOLE heads, so
        # each device's head block takes its own slope slice; the ring path
        # adds the bias with global kv positions. Falls back to replicated
        # attention when head counts don't split evenly (the uneven-head pad
        # path would misalign slope indices), for bidirectional ALiBi, or
        # with a live tensor axis (the slope slice would also need the
        # tensor-rank head offset — not wired; replicated attention under
        # TP still shards heads and slopes consistently via auto sharding).
        tp_live = int(mesh.shape.get("tensor", 1)) > 1 if sp > 1 else False
        alibi_sp_ok = (alibi is not None and sp > 1 and cfg.causal
                       and not tp_live
                       and (cfg.sp_attention == "ring"
                            or (H_all % sp == 0 and KV_all % sp == 0)))
        if sp > 1 and alibi is not None and not alibi_sp_ok:
            from ..utils.logging import warning_once

            warning_once(
                "mesh seq > 1 with an ALiBi model: this shape can't ride "
                "the SP paths (uneven heads under Ulysses, a live tensor "
                "axis, or bidirectional) — attention stays replicated")
        if sp <= 1 or (alibi is not None and not alibi_sp_ok):
            return causal_attention(q, k, v, attention_impl=cfg.attention_impl,
                                    alibi=alibi, causal=cfg.causal)
        import functools as ft

        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..parallel.sequence import ulysses_attention

        # Ragged T (e.g. T-1 from next-token label shifting): pad the seq
        # dim up to a multiple of sp. Padded keys sit at positions past
        # every real query, so the causal mask zeroes their influence;
        # padded query rows are sliced away.
        T0 = q.shape[1]
        pad = -T0 % sp
        if pad and not cfg.causal:
            # bidirectional attention would attend INTO pad keys — no mask
            # hides them without segment ids; keep replicated attention
            return causal_attention(q, k, v, attention_impl=cfg.attention_impl,
                                    alibi=alibi, causal=False)
        if pad:
            p4 = lambda t: jnp.pad(t, ((0, 0), (0, pad), (0, 0), (0, 0)))
            q, k, v = p4(q), p4(k), p4(v)
        # With an active tensor axis the head dim stays tensor-sharded
        # through the manual region: each (seq, tensor) device holds
        # [B/dp, T/sp, H/tp, D] and the Ulysses a2a over "seq" swaps to
        # [B/dp, T, H/(tp*sp), D] — TP x SP composition.
        tp = int(mesh.shape.get("tensor", 1))
        head_ax = "tensor" if tp > 1 else None
        if head_ax and (q.shape[2] % tp or k.shape[2] % tp):
            from ..utils.logging import warning_once

            # sxt: ignore[SXT005] head counts and mesh extent are fixed per process — dedup cardinality 1
            warning_once(
                f"seq x tensor attention: heads ({q.shape[2]}/{k.shape[2]} kv) "
                f"not divisible by tensor={tp}; heads gather across the "
                "tensor axis inside the attention region (slower, correct)")
            head_ax = None
        spec = P(("data", "fsdp"), "seq", head_ax, None)
        slopes_all = (jnp.asarray(alibi, jnp.float32)
                      if alibi is not None else None)
        if cfg.sp_attention == "ring":
            import os

            from ..parallel.sequence import ring_attention

            # save_flash_lse x ring (ISSUE 15): drop the ring's inner
            # per-hop checkpoint so THIS layer's checkpoint policy saves
            # each hop kernel's tagged (out, lse) — backward enters the
            # dq/dkv kernels from saved lse, no forward re-run (PR 3
            # discipline per hop). Every other policy keeps the per-hop
            # checkpoint (O(T/sp · D) residuals, fwd recomputed per hop).
            lse_policy = bool(cfg.remat
                              and cfg.remat_policy == "save_flash_lse")
            if cfg.cp_use_kernel not in ("auto", "pallas", "xla"):
                # the config-section path validates this in
                # ContextParallelConfig; the low-level spelling
                # (TransformerConfig built directly) bypasses it
                raise ValueError(
                    f'cp_use_kernel must be "auto", "pallas" or "xla", '
                    f'got {cfg.cp_use_kernel!r}')
            use_kernel = {"auto": "auto", "pallas": True,
                          "xla": False}[cfg.cp_use_kernel]
            interp = bool(os.environ.get("SXT_LSE_INTERPRET"))
            sp_fn = ft.partial(ring_attention, axis_name="seq",
                               causal=cfg.causal, alibi_slopes=slopes_all,
                               kv_chunk=cfg.cp_kv_chunk,
                               use_kernel=use_kernel, interpret=interp,
                               hop_remat=not lse_policy)
        elif cfg.sp_attention == "ulysses":
            if slopes_all is None:
                local = ft.partial(causal_attention,
                                   attention_impl=cfg.attention_impl,
                                   causal=cfg.causal)
            else:
                def local(q, k, v):
                    # after the seq->head a2a, device d owns the contiguous
                    # head block [d*Hc, (d+1)*Hc) — its slope slice
                    Hc = q.shape[2]
                    idx = jax.lax.axis_index("seq")
                    sl = jax.lax.dynamic_slice_in_dim(
                        slopes_all, idx * Hc, Hc)
                    return causal_attention(
                        q, k, v, attention_impl=cfg.attention_impl,
                        alibi=sl, causal=cfg.causal)
            sp_fn = ft.partial(ulysses_attention, axis_name="seq",
                               attn_fn=local, causal=cfg.causal)
        else:
            raise ValueError(f"Unsupported sp_attention {cfg.sp_attention!r}; "
                             "use 'ulysses' or 'ring'")
        # Partial-manual over exactly the axes this region names: it can
        # then NEST inside the pipeline's manual-over-"pipe" region (the
        # reference runs Ulysses inside PP stages via its group registry,
        # utils/groups.py:633 — here SP×PP composes as nested shard_maps).
        # Inside an enclosing manual region the nested call must use the
        # CONTEXT mesh (whose outer axes are typed Manual), not the
        # concrete topology mesh.
        manual = {"data", "fsdp", "seq"} | ({"tensor"} if head_ax else set())
        from ..parallel.mesh import constraint_mesh, native_shard_map
        from ..parallel.mesh import shard_map as _shard_map

        if not native_shard_map():
            # jax 0.4.x partial-manual lowering: an all-to-all/all-gather
            # inside a region that still has a LIVE (size > 1) auto axis
            # trips an XLA SPMD-partitioner CHECK (a process abort, not an
            # exception — see parallel/mesh.py::native_shard_map). Live
            # auto axes here: expert always; pipe when this region nests in
            # the pipeline's; tensor when heads don't split. Fall back to
            # replicated attention (correct, not sequence-parallel) rather
            # than abort the process.
            live_auto = [ax for ax, n in mesh.shape.items()
                         if n > 1 and ax not in manual]
            if live_auto:
                from ..utils.logging import warning_once

                # sxt: ignore[SXT005] live_auto derives from the mesh shape, fixed per process
                warning_once(
                    "sequence-parallel attention: jax 0.4.x cannot lower "
                    f"the Ulysses/ring region with live auto axes "
                    f"{sorted(live_auto)} (XLA partial-manual CHECK); "
                    "attention runs replicated for this config — upgrade "
                    "jax for SP x " + "/".join(sorted(live_auto)))
                out = causal_attention(q, k, v,
                                       attention_impl=cfg.attention_impl,
                                       alibi=alibi, causal=cfg.causal)
                return out[:, :T0] if pad else out
        out = _shard_map(sp_fn, mesh=constraint_mesh(mesh),
                         in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names=manual)(q, k, v)
        return out[:, :T0] if pad else out

    def stack_apply(self, stacked_layers, x, rope, ltd_mask=None,
                    layer_keep=None, layer_ids=None):
        """Scan the (sub)stack of layers over x. Returns (x, summed aux).

        ``ltd_mask`` [B, T] bool (True = keep): random-LTD token freezing
        for the configured middle layers.
        ``layer_keep`` [L] bool (True = run): progressive layer drop
        (reference runtime/progressive_layer_drop.py) — a dropped layer is
        an identity skip (its aux loss is zeroed too). Both masks are
        traced, so the anneal never recompiles.
        ``layer_ids`` [L_local] int32 (pipeline stages): each scanned row's
        GLOBAL layer index — per-layer pattern flags (attention_pattern,
        moe_layer_pattern, random-LTD ranges) must be derived from global
        positions, not the stage-local row number; pad rows carry
        id == n_layers and map to all-off flags."""
        import jax
        import jax.numpy as jnp

        cfg = self.config
        # Sequence-parallel activation layout: pin hidden states to
        # [batch over data+fsdp, seq over "seq"] so per-token compute and
        # activation memory split across the seq axis (the attention inside
        # layer_apply handles the seq<->head all-to-alls).
        sp, mesh = self._sp_mesh()
        if sp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import constraint_mesh

            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(constraint_mesh(mesh),
                                 P(("data", "fsdp"), "seq", None)))
        L = jax.tree_util.tree_leaves(stacked_layers)[0].shape[0]
        LG = cfg.n_layers

        def per_layer_flags(fn):
            """[L_local] bool from a global-layer predicate; pad id -> False."""
            glob = jnp.asarray([bool(fn(i)) for i in range(LG)] + [False])
            if layer_ids is None:
                return glob[:L]
            return jnp.take(glob, jnp.asarray(layer_ids, jnp.int32))

        use_local = bool(cfg.local_attention_window and cfg.attention_pattern)
        local_flags = None
        if use_local:
            ap = cfg.attention_pattern
            local_flags = per_layer_flags(lambda i: ap[i % len(ap)] == "local")
        # Megatron --expert-interval: per-layer MoE/dense flags (cycled)
        mixed_moe = bool(cfg.n_experts > 0 and cfg.moe_layer_pattern
                         and not all(cfg.moe_layer_pattern))
        moe_flags = None
        if mixed_moe:
            mp = cfg.moe_layer_pattern
            moe_flags = per_layer_flags(lambda i: mp[i % len(mp)])

        if ltd_mask is None and layer_keep is None and not mixed_moe:
            if use_local:
                def layer_fn(h, xs):
                    lw, loc = xs
                    return self.layer_apply(lw, h, rope, local=loc)

                xs = (stacked_layers, local_flags)
            else:
                def layer_fn(h, lw):
                    return self.layer_apply(lw, h, rope)

                xs = stacked_layers
            if cfg.remat:
                layer_fn = jax.checkpoint(layer_fn, policy=_remat_policy(cfg.remat_policy))
            x, aux_losses = jax.lax.scan(layer_fn, x, xs)
            return x, jnp.sum(aux_losses)

        if ltd_mask is not None:
            end = cfg.random_ltd_end_layer if cfg.random_ltd_end_layer >= 0 else LG - 1
            active = per_layer_flags(
                lambda i: cfg.random_ltd_start_layer <= i < end)
        else:
            active = jnp.zeros((L,), bool)
        keep_layers = (jnp.ones((L,), bool) if layer_keep is None
                       else jnp.asarray(layer_keep))
        if local_flags is None:
            local_flags = jnp.zeros((L,), bool)
        if moe_flags is None:
            moe_flags = jnp.ones((L,), bool)

        def layer_fn(h, xs):
            lw, act, keep_l, loc, moe_l = xs
            out, aux = self.layer_apply(lw, h, rope,
                                        local=(loc if use_local else None),
                                        moe_on=(moe_l if mixed_moe else None))
            if ltd_mask is not None:
                keep = jnp.logical_or(~act, ltd_mask)[..., None]   # [B,T,1]
                out = jnp.where(keep, out, h)
            out = jnp.where(keep_l, out, h)
            return out, jnp.where(keep_l, aux, jnp.zeros_like(aux))

        if cfg.remat:
            layer_fn = jax.checkpoint(layer_fn, policy=_remat_policy(cfg.remat_policy))
        x, aux_losses = jax.lax.scan(
            layer_fn, x, (stacked_layers, active, keep_layers, local_flags,
                          moe_flags))
        return x, jnp.sum(aux_losses)

    def _unembed(self, params, dtype):
        """Single source of truth for the unembed projection: (w [D, V],
        bias [V] fp32 or None). Bias exists only on the untied path
        (matches init())."""
        import jax.numpy as jnp

        if self.config.tie_embeddings:
            return params["embed"].T.astype(dtype), None
        bias = (params["unembed_b"].astype(jnp.float32)
                if self.config.unembed_bias else None)
        return params["unembed"].astype(dtype), bias

    def head(self, params, x):
        """Final norm + unembed: x [.., T, D] -> logits [.., T, vocab] fp32.

        The unembed matmul keeps operands in the compute dtype and
        accumulates in fp32 (``preferred_element_type``): on TPU a bf16
        MXU matmul with fp32 accumulation, not the ~6x-slower fp32-operand
        emulation an ``astype(float32)`` on both sides would force. Under
        the fp32 CPU test path this is bit-identical to the old form."""
        import jax
        import jax.numpy as jnp

        cfg = self.config
        if not cfg.post_ln:
            x = _norm(x, params["ln_f_w"], params["ln_f_b"], cfg.norm,
                      eps=cfg.norm_eps)
        if cfg.mlm_head:
            # BERT cls head: dense + gelu + LN, tied decoder with own bias
            x = activation_fn("gelu")(x @ params["mlm_dense_w"].astype(x.dtype)
                                      + params["mlm_dense_b"].astype(x.dtype))
            x = _norm(x, params["mlm_ln_w"], params["mlm_ln_b"], cfg.norm,
                      eps=cfg.norm_eps)
        w, bias = self._unembed(params, x.dtype)
        logits = jnp.matmul(x, w, preferred_element_type=jnp.float32)
        if cfg.mlm_head:
            logits = logits + params["mlm_bias"].astype(jnp.float32)
        return logits if bias is None else logits + bias

    @staticmethod
    def token_loss(logits, labels):
        """Per-batch CE pieces: (nll_sum, token_count); -100/negative = ignore."""
        import jax
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(logits, axis=-1)
        mask = (labels >= 0)
        safe_labels = jnp.where(mask, labels, 0)
        nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
        return (nll * mask).sum(), mask.sum()

    def _pad_vocab(self) -> bool:
        """Pad the unembed to a 128-multiple vocab inside the chunked loss?
        GPT-2's 50257 is the canonical offender: the MXU tiles lanes in 128s,
        and an unaligned contraction output pays a remainder pass. Config
        tri-state: None = auto (TPU only), True/False = forced (tests)."""
        p = self.config.pad_vocab_logits
        if p is not None:
            return bool(p) and self.config.vocab_size % 128 != 0
        if self.config.vocab_size % 128 == 0:
            return False
        import jax

        return jax.default_backend() == "tpu"

    def chunked_loss(self, params, x, labels, chunk: int):
        """Final-norm + unembed + CE, streamed over seq chunks of ``chunk``
        tokens under remat: peak logits memory is [B, chunk, vocab] instead
        of [B, T, vocab] (the dominant activation for big-vocab models).
        Numerically identical to head()+token_loss() — softmax is per-token,
        and when the vocab is padded to the 128 lane tile (``_pad_vocab``)
        the pad columns carry a -1e30 additive mask, so their softmax mass
        underflows to exactly zero.
        Reference capability: chunked logits loss, sequence/fpdt_layer.py:1137.
        """
        import jax
        import jax.numpy as jnp

        cfg = self.config
        B, T, D = x.shape
        n_chunks = -(-T // chunk)
        pad = n_chunks * chunk - T
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
            labels = jnp.pad(labels, ((0, 0), (0, pad)), constant_values=-100)
        xc = x.reshape(B, n_chunks, chunk, D).transpose(1, 0, 2, 3)
        lc = labels.reshape(B, n_chunks, chunk).transpose(1, 0, 2)

        # Unembed weight/bias built ONCE outside the scan (loop-invariant,
        # via the same _unembed as head()): the scan body sees an aligned
        # [D, Vp] matmul; pad columns carry a -1e30 additive mask.
        V = cfg.vocab_size
        vpad = (-V % 128) if self._pad_vocab() else 0
        w, bias = self._unembed(params, x.dtype)
        extra = None
        if vpad:
            w = jnp.pad(w, ((0, 0), (0, vpad)))
            extra = jnp.where(jnp.arange(V + vpad) < V, 0.0, -1e30
                              ).astype(jnp.float32)
            if bias is not None:
                extra = extra + jnp.pad(bias, (0, vpad))
        elif bias is not None:
            extra = bias

        @jax.checkpoint
        def body(carry, xl):
            xch, lch = xl
            xn = _norm(xch, params["ln_f_w"], params["ln_f_b"], cfg.norm,
                       eps=cfg.norm_eps)
            logits = jnp.matmul(xn, w, preferred_element_type=jnp.float32)
            if extra is not None:
                logits = logits + extra
            nll, cnt = self.token_loss(logits, lch)
            nll_sum, cnt_sum = carry
            return (nll_sum + nll, cnt_sum + cnt), None

        (nll_sum, cnt), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)), (xc, lc))
        return nll_sum, cnt

    def _loss_chunk(self, B: int, T: int) -> int:
        """Resolved chunk size: 0 = full logits."""
        if self.config.post_ln or self.config.mlm_head:
            # chunked_loss runs ln_f + plain unembed per chunk; the encoder
            # head shape (no final norm / MLM transform) isn't wired there
            return 0
        c = self.config.loss_chunk
        if c >= 0:
            return 0 if c == 0 else min(c, T)
        # auto: chunk when the full fp32 logits would exceed ~256MB
        if B * T * self.config.vocab_size * 4 <= 256 * 1024 * 1024:
            return 0
        return min(256, T)

    # -- forward -------------------------------------------------------

    def apply(self, params, input_ids):
        """input_ids [B, T] -> logits [B, T, vocab] (fp32)."""
        return self.apply_with_aux(params, input_ids)[0]

    def apply_with_aux(self, params, input_ids, ltd_mask=None, layer_keep=None):
        """Returns (logits, moe_aux_loss) — aux is 0 for dense models."""
        x, rope = self.embed(params, input_ids)
        x, aux = self.stack_apply(params["layers"], x, rope, ltd_mask=ltd_mask,
                                  layer_keep=layer_keep)
        return self.head(params, x), aux

    def loss(self, params, batch, rng=None):
        """Next-token cross entropy. batch: {"input_ids": [B,T]} (+ optional
        "labels" already shifted, -100 = ignore; + optional "ltd_keep_prob"
        [B] for the random-LTD schedule)."""
        import jax.numpy as jnp

        ids = batch["input_ids"]
        if not self.config.causal:
            # encoder (MLM): no next-token shift — labels mark the masked
            # positions (-100 elsewhere); default to full-token recovery
            labels = batch.get("labels", ids)
            model_ids = ids
        elif "labels" in batch:
            labels = batch["labels"]
            model_ids = ids
        else:
            labels = ids[:, 1:]
            model_ids = ids[:, :-1]
        ltd_mask = None
        if self.config.random_ltd and "ltd_keep_prob" in batch and rng is not None:
            import jax

            rng, sub = jax.random.split(rng)
            keep = batch["ltd_keep_prob"][0]
            ltd_mask = jax.random.uniform(sub, model_ids.shape) < keep
        layer_keep = None
        if "pld_theta" in batch and rng is not None:
            # Progressive layer drop (reference progressive_layer_drop.py:10;
            # arXiv 2010.13369): keep prob anneals to theta_t and drops
            # deeper layers more: p_l = 1 - (l/L) * (1 - theta_t).
            import jax

            rng, sub = jax.random.split(rng)
            theta = jnp.asarray(batch["pld_theta"], jnp.float32).reshape(-1)[0]
            L = self.config.n_layers
            p_keep = 1.0 - (jnp.arange(L, dtype=jnp.float32) / L) * (1.0 - theta)
            layer_keep = jax.random.uniform(sub, (L,)) < p_keep
        B, T = model_ids.shape
        chunk = self._loss_chunk(B, T)
        if chunk:
            x, rope = self.embed(params, model_ids)
            x, aux = self.stack_apply(params["layers"], x, rope,
                                      ltd_mask=ltd_mask, layer_keep=layer_keep)
            nll_sum, count = self.chunked_loss(params, x, labels, chunk)
        else:
            logits, aux = self.apply_with_aux(params, model_ids, ltd_mask=ltd_mask,
                                              layer_keep=layer_keep)
            nll_sum, count = self.token_loss(logits, labels)
        ce = nll_sum / jnp.maximum(count, 1)
        return ce + self.config.aux_loss_coef * aux


def _remat_policy(name: str):
    import jax

    policies = {
        "none": None,
        "full": jax.checkpoint_policies.nothing_saveable,
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "dots_with_no_batch_dims_saveable": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # FPDT host offload (reference fpdt_layer.py:462,971): per-layer KV
        # lives in host RAM between fwd and bwd instead of HBM; everything
        # else recomputes. Max context becomes host-RAM-bound, not HBM-bound.
        "offload_kv_host": jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[], names_which_can_be_offloaded=["kv"],
            offload_src="device", offload_dst="pinned_host"),
        # Selective saves between the nothing_saveable / dots_saveable
        # extremes ([B,T,*]-sized named seams only, never the full dots set):
        # "save_attn_seams" keeps q/kv/attn (skips the attention-side
        # recompute in backward, ~1/6 of layer FLOPs at seq 4k);
        # "save_ffn" also keeps the two big FFN projections (skips ~80% of
        # the backward recompute; costs 2*T*d_ff bf16 per layer).
        "save_attn_seams": jax.checkpoint_policies.save_only_these_names(
            "q", "kv", "attn"),
        "save_ffn": jax.checkpoint_policies.save_only_these_names(
            "q", "kv", "attn", "ffn_gate", "ffn_up"),
        # Save the flash kernel's OWN residuals (out + logsumexp, named
        # inside ops/alibi_attention._alibi_flash_fwd_impl) so backward
        # enters the flash bwd kernels directly from saved state — the
        # forward attention kernel is DCE'd out of the remat recompute.
        # Why "save_attn_seams" lost ~1pt despite saving "attn": the layer-
        # level attn seam is NOT a residual of the kernel's custom vjp —
        # the backward replay still re-ran the flash forward to rebuild its
        # (out, lse) residuals, so that policy paid the HBM for the saved
        # seams without removing any attention recompute. Saving the
        # residuals themselves (this policy) is what removes it; cost is
        # out[B,T,H,D] bf16 + lse[B,H,T] f32 per layer. Requires the model
        # to route attention through the lse kernel (Transformer._attention
        # does this automatically under this policy).
        "save_flash_lse": jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse"),
    }
    return policies.get(name, jax.checkpoint_policies.dots_saveable)
