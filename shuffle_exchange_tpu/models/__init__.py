from .transformer import (
    Transformer,
    TransformerConfig,
    gpt2_small,
    gpt2_large,
    llama3_8b,
    llama3_70b,
    mixtral_8x7b,
    tiny,
    tiny_moe,
)

MODEL_REGISTRY = {
    "gpt2-small": gpt2_small,
    "gpt2-large": gpt2_large,
    "llama3-8b": llama3_8b,
    "llama3-70b": llama3_70b,
    "mixtral-8x7b": mixtral_8x7b,
    "tiny": tiny,
    "tiny-moe": tiny_moe,
}


def get_model(name: str, **overrides) -> Transformer:
    import dataclasses

    cfg = MODEL_REGISTRY[name]()
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return Transformer(cfg)
